"""Shard-aware op lowerings: the per-shard kernels under ``shard_map``.

Design (the scaling-book recipe — gather what's small, shard what's big):

- **Map / Filter / GroupBy / Union** are local on row-sharded delta
  buffers: no communication. A GroupBy re-key leaves rows in place; routing
  happens where a *keyed* op consumes them.
- **Reduce**: each shard scatter-adds its local delta rows into a full-K
  contribution table, then one ``psum_scatter`` (reduce-scatter over the
  mesh axis) hands every shard the combined contributions for its owned
  key range — the cross-shard combine the north star names. State tables
  (``wsum``/``wcnt``/``emitted``) live key-sharded; emission covers the
  owned range with global key ids.
- **Join**: per-tick deltas are small, per-key state is big — so both
  delta sides are ``all_gather``'d (tiled), masked to the shard's owned
  key range, localized, and fed to the shared :func:`join_core` over the
  shard's slice of the left table and append arena. Output rows stay on
  the owning shard (row-sharded), keys global.

Keyed state is range-sharded: shard ``i`` of ``n`` owns keys
``[i*K/n, (i+1)*K/n)``. Range (not hash) sharding keeps key<->shard
arithmetic trivial and lets emission use a contiguous ``arange``.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.lowerings import (_LOWERINGS, _agg_tables,
                                            _bcast_w, _differs,
                                            _scatter_contribs, join_core)
from reflow_tpu.graph import Node

__all__ = ["lower_node_sharded"]


def _localize(d: DeviceDelta, base, Kl: int) -> DeviceDelta:
    """Mask a gathered delta to this shard's key range and re-base keys.

    Non-owned rows become weight-0 padding at local key 0 — no-ops of the
    multiset algebra, so the downstream kernel needs no other masking.
    """
    own = (d.keys >= base) & (d.keys < base + Kl)
    return DeviceDelta(
        keys=jnp.where(own, d.keys - base, 0),
        values=d.values,
        weights=jnp.where(own, d.weights, 0),
    )


def _lower_reduce_sharded(op, node: Node, state, ins, axis: str, n: int
                          ) -> Tuple[DeviceDelta, dict]:
    (d,) = ins                      # local delta rows [Cl]
    in_spec = node.inputs[0].spec
    K = in_spec.key_space
    Kl = K // n
    vdtype = node.spec.value_dtype
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)

    # local full-K contributions (one fused scatter), then one
    # reduce-scatter hands each shard its owned range's combined sums
    dws, dwc = _scatter_contribs(d, K)
    vshape = d.values.shape[1:]
    stacked = jnp.concatenate(
        [dws.reshape(K, -1), dwc.astype(jnp.float32)[:, None]], axis=-1)
    combined = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0,
                                    tiled=True)
    wsum = state["wsum"] + combined[:, :-1].reshape((Kl,) + vshape)
    wcnt = state["wcnt"] + combined[:, -1].astype(jnp.int32)

    # dense diff over the owned slice (mirrors _lower_reduce dense mode)
    emitted, em_has = state["emitted"], state["emitted_has"]
    agg, exists = _agg_tables(op, wsum, wcnt, vdtype)
    changed = _differs(agg, emitted, op.tol)
    ins_m = exists & (~em_has | changed)
    ret_m = em_has & (~exists | changed)
    gkeys = base + jnp.arange(Kl, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([gkeys, gkeys]),
        values=jnp.concatenate([emitted, agg]),
        weights=jnp.concatenate(
            [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
    )
    ins_b = _bcast_w(ins_m, agg)
    new_emitted = jnp.where(ins_b, agg, emitted)
    new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~exists, False, em_has))
    return out, {"wsum": wsum, "wcnt": wcnt,
                 "emitted": new_emitted, "emitted_has": new_has}


def _lower_join_sharded(op, node: Node, state, ins, axis: str, n: int
                        ) -> Tuple[DeviceDelta, dict]:
    da, db = ins                    # local delta rows
    K = node.inputs[0].spec.key_space
    Kl = K // n
    Rl = op.arena_capacity // n
    base = (jax.lax.axis_index(axis) * Kl).astype(jnp.int32)

    # deltas are small: gather both sides everywhere, keep only owned rows
    def _route(d):
        if d is None:
            return None
        g = jax.tree.map(lambda x: jax.lax.all_gather(x, axis, tiled=True), d)
        return _localize(g, base, Kl)

    da_l = _route(da)
    db_l = _route(db)

    # per-shard scalar append counter is stored as a length-1 slice of a
    # mesh-length vector; the core kernel wants a scalar
    core_state = dict(state)
    core_state["rcount"] = state["rcount"][0]
    out, new_state = join_core(op, Kl, Rl, node.spec.value_dtype,
                               core_state, da_l, db_l, key_offset=base)
    new_state["rcount"] = new_state["rcount"][None]
    return out, new_state


def lower_node_sharded(node: Node, state, ins: Sequence[DeviceDelta],
                       axis: str, n: int) -> Tuple[DeviceDelta, dict]:
    kind = node.op.kind
    if kind == "reduce":
        return _lower_reduce_sharded(node.op, node, state, ins, axis, n)
    if kind == "join":
        return _lower_join_sharded(node.op, node, state, ins, axis, n)
    # stateless row ops are shard-local
    return _LOWERINGS[kind](node.op, node, state, ins)
