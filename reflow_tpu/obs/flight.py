"""Flight recorder: a crash-surviving on-disk ring of recent spans.

The trace rings (``obs/trace.py``) live in process memory — a kill -9
takes them with it, which is exactly when an operator most wants the
node's last seconds. The :class:`FlightRecorder` keeps a *bounded*
on-disk ring in the node's own state directory (its "disk corner"):
every causality-carrying span plus a small always-record set of
control-plane events (fence rejects, failover elect/replay, reconnect
attempts) is appended as one JSON line, buffered, and flushed to the
OS every ``flush_every`` events — after a SIGKILL the flushed lines
are plain file bytes, readable by anyone (``tools/reflow_flight.py``
merges the corners of a whole fleet into one timeline).

**Ring shape.** Two alternating JSONL files (``flight-a.jsonl`` /
``flight-b.jsonl``), each opened with a fresh header line carrying the
node name, pid, and a ``{mono, wall}`` clock anchor. When the active
file exceeds half the byte budget the recorder truncates the *other*
file and switches to it — so at least half a budget of history always
survives, the files never grow past the budget, and recovery needs no
index: read both files, drop any torn final line (a write cut mid-way
by the kill), and order by the header anchors.

**Crash model.** ``flush()`` pushes buffered lines through the file
object into the OS page cache (no fsync — the recorder survives
process death, which is the chaos benches' failure mode; surviving
power loss is the WAL's job, not the flight recorder's). Eager flushes
fire on the events worth dying with: fence rejects, promotions,
breaker trips (:func:`note`).

Install once per process with :func:`install`; it tees off
:func:`reflow_tpu.obs.trace.evt` via ``set_flight_hook`` so recording
sites need no new code.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from reflow_tpu.obs import trace as _trace
from reflow_tpu.utils.config import env_int
from reflow_tpu.utils.runtime import named_lock

__all__ = ["FLIGHT_SCHEMA", "FlightRecorder", "install", "installed",
           "uninstall", "note", "flush_now", "read_flight_dir"]

FLIGHT_SCHEMA = "reflow.flight/1"

#: span kinds recorded even without a causality token — the
#: control-plane events a post-mortem always wants on the timeline
ALWAYS_RECORD = frozenset({
    "fence_reject", "failover_elect", "failover_replay",
    "net_reconnect", "sub_push",
})

_FILES = ("flight-a.jsonl", "flight-b.jsonl")


class FlightRecorder:
    """One process's bounded on-disk span ring (see module docstring).

    Thread-safe: spans arrive from every recording thread via the
    trace tee. The write path under the lock is a dict build + a
    buffered append; actual file writes happen only on flush/rotate.
    """

    def __init__(self, directory: str, *, node: Optional[str] = None,
                 cap_bytes: Optional[int] = None,
                 flush_every: Optional[int] = None) -> None:
        from reflow_tpu.obs.wire import node_id
        self.dir = directory
        self.node = node if node is not None else node_id()
        self.cap_bytes = cap_bytes if cap_bytes is not None \
            else env_int("REFLOW_FLIGHT_BYTES")
        self.flush_every = flush_every if flush_every is not None \
            else env_int("REFLOW_FLIGHT_FLUSH_EVERY")
        self._lock = named_lock("obs.flight")
        self._seq = 0
        self._buf: List[str] = []
        self._active = 0          # index into _FILES
        self._active_bytes = 0
        self._fh = None
        self.events_total = 0
        self.flushes_total = 0
        self.rotations_total = 0
        self.closed = False
        self._published: List = []  # (registry, prefix) to drop on close
        os.makedirs(self.dir, exist_ok=True)
        with self._lock:
            self._archive_previous()
            self._open_active(truncate=True)

    def _archive_previous(self) -> None:
        """A respawn reopens the same disk corner; the dead
        incarnation's ring is the post-mortem evidence, so move it
        aside (one ``.prev`` generation, bounded) instead of
        truncating over it."""
        for fn in _FILES:
            path = os.path.join(self.dir, fn)
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".prev")
                except OSError:
                    pass

    # -- file machinery (caller holds the lock) ------------------------

    def _header(self) -> str:
        return json.dumps({
            "flight": 1, "schema": FLIGHT_SCHEMA, "node": self.node,
            "pid": os.getpid(),
            "anchor": {"mono": time.perf_counter(),
                       "wall": time.time()}})

    def _open_active(self, truncate: bool) -> None:
        path = os.path.join(self.dir, _FILES[self._active])
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = open(path, "w" if truncate else "a")
        hdr = self._header() + "\n"
        self._fh.write(hdr)
        self._fh.flush()
        self._active_bytes = len(hdr)

    def _rotate(self) -> None:
        self._active = 1 - self._active
        self._open_active(truncate=True)
        self.rotations_total += 1

    def _flush_locked(self) -> None:
        if not self._buf or self._fh is None:
            return
        data = "".join(self._buf)
        self._buf.clear()
        try:
            self._fh.write(data)
            self._fh.flush()
        except OSError:
            return  # a full/ripped disk must never break the data path
        self._active_bytes += len(data)
        self.flushes_total += 1
        if self._active_bytes > max(self.cap_bytes // 2, 4096):
            self._rotate()

    # -- recording -----------------------------------------------------

    def record(self, name: str, ts: float, dur: float,
               track: Optional[str], args: Optional[Dict[str, Any]],
               kind: str = "span") -> None:
        """Append one event line (buffered). ``ts`` is the recording
        process's ``time.perf_counter()``; the header anchor maps it
        onto the wall clock at merge time."""
        with self._lock:
            if self.closed:
                return
            self._seq += 1
            line = {"seq": self._seq, "kind": kind, "name": name,
                    "mono": ts, "dur": dur}
            if track:
                line["track"] = track
            if args:
                line["args"] = args
            self._buf.append(json.dumps(line) + "\n")
            self.events_total += 1
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def _tee(self, name: str, ts: float, dur: float,
             track: Optional[str], args: Optional[Dict[str, Any]]
             ) -> None:
        """The ``trace.set_flight_hook`` target: keep causality-carrying
        spans and the always-record control set; drop the bulk."""
        if name in ALWAYS_RECORD or name.startswith("control.") \
                or (args is not None
                    and ("cause" in args or "causes" in args)):
            self.record(name, ts, dur, track, args)

    def note(self, event: str, *, eager: bool = True, **args: Any
             ) -> None:
        """Record one control-plane event (zero-duration) and — by
        default — flush immediately: these are the moments (fence,
        promote, breaker trip) a process may not outlive."""
        self.record(event, time.perf_counter(), 0.0, "flight",
                    dict(args) or None, kind="event")
        if eager:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            self.closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
        for reg, name in self._published:
            reg.unregister_prefix(f"{name}.")
        self._published = []

    # -- observability -------------------------------------------------

    def publish_metrics(self, registry=None, name: str = "flight"
                        ) -> None:
        from reflow_tpu.obs.registry import REGISTRY
        reg = registry if registry is not None else REGISTRY
        reg.gauge(f"{name}.events_total", lambda: self.events_total)
        reg.gauge(f"{name}.flushes_total", lambda: self.flushes_total)
        reg.gauge(f"{name}.rotations_total",
                  lambda: self.rotations_total)
        self._published.append((reg, name))


# -- module-level install (one recorder per process) ------------------------

_REC: Optional[FlightRecorder] = None


def install(directory: str, *, node: Optional[str] = None,
            cap_bytes: Optional[int] = None,
            flush_every: Optional[int] = None) -> FlightRecorder:
    """Create the process's recorder and tee it off ``trace.evt``.
    Replaces any previous recorder (closing it)."""
    global _REC
    rec = FlightRecorder(directory, node=node, cap_bytes=cap_bytes,
                         flush_every=flush_every)
    old, _REC = _REC, rec
    _trace.set_flight_hook(rec._tee)
    if old is not None:
        old.close()
    return rec


def installed() -> Optional[FlightRecorder]:
    return _REC


def uninstall() -> None:
    global _REC
    _trace.set_flight_hook(None)
    rec, _REC = _REC, None
    if rec is not None:
        rec.close()


def note(event: str, **args: Any) -> None:
    """Record + eagerly flush one control-plane event on the installed
    recorder; a no-op when no recorder is installed (the common case —
    callers never need to guard)."""
    rec = _REC
    if rec is not None:
        rec.note(event, **args)


def flush_now(reason: str = "") -> None:
    """Eagerly flush the installed recorder (no-op when none)."""
    rec = _REC
    if rec is not None:
        rec.flush()


# -- post-mortem reading ----------------------------------------------------

def read_flight_file(path: str) -> Optional[Dict[str, Any]]:
    """Parse one flight file: ``{"header": {...}, "events": [...]}``.
    A torn final line (the kill arrived mid-write) is dropped; a file
    without a valid header returns None."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return None
    lines = raw.split("\n")
    header = None
    events: List[Dict[str, Any]] = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue  # torn by the kill — drop, keep reading
        if header is None:
            if not (isinstance(obj, dict) and obj.get("flight") == 1):
                return None
            header = obj
        elif isinstance(obj, dict):
            events.append(obj)
    if header is None:
        return None
    return {"header": header, "events": events, "path": path}


def read_flight_dir(directory: str) -> List[Dict[str, Any]]:
    """Every ring file of one node's corner — the live generation plus
    the archived ``.prev`` one (a respawned process moved its dead
    predecessor's ring aside) — valid ones only."""
    out = []
    for fn in _FILES:
        for suffix in ("", ".prev"):
            parsed = read_flight_file(
                os.path.join(directory, fn + suffix))
            if parsed is not None:
                out.append(parsed)
    return out
