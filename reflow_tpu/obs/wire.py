"""Telemetry wire plane: registry snapshots over the ``net/`` framed
transports (docs/guide.md "Fleet telemetry").

The data plane ships WAL bytes; this module ships *telemetry* — each
node's :class:`~reflow_tpu.obs.registry.MetricsRegistry` snapshots —
from a :class:`~reflow_tpu.obs.fleet.TelemetryShipper` to the
:class:`~reflow_tpu.obs.fleet.FleetAggregator` behind a
:class:`TelemetryServer`. It deliberately reuses the replication
stack's parts (``Transport``/``Conn`` framing, ``ReconnectPolicy``
backoff, ``WireFaults`` injection via ``FaultyTransport``) so the
telemetry plane inherits the same fault model the chaos bench already
trusts, with one inversion: **telemetry loss is always tolerated**. A
dropped snapshot is a stale gauge, never an error — no call in this
module may block a data-path thread or let a telemetry failure
propagate as an exception.

Requests (pickled tuples, ``net/framing.py``)::

    ("hello", node, anchor)   -> ("ok", server_anchor)
    ("snap", node, snapshot)  -> ("ok",)
    ("fleet",)                -> ("ok", fleet_snapshot)
    ("ping",)                 -> ("ok", {node, nodes})
    anything else             -> ("err", text)

Clock anchoring: every process keeps its own monotonic clock; anchors
(:func:`clock_anchor`) pair a ``monotonic`` reading with the local
wall clock at handshake time so a consumer can *display* cross-node
timestamps on one axis. The offset is an estimate bounded by the
handshake RTT — it is never used for ordering or correctness (the
causality tokens on the data plane do that by exact string equality).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from reflow_tpu.net.backoff import ReconnectPolicy
from reflow_tpu.net.framing import TransportError, WireTimeout
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.utils.config import env_str
from reflow_tpu.utils.runtime import named_lock

__all__ = ["clock_anchor", "node_id", "TelemetryLink",
           "TelemetryServer"]

#: accept/recv poll slice, mirroring net/server.py: how often blocked
#: telemetry threads re-check the stop flag
_POLL_S = 0.2


def node_id() -> str:
    """This process's id on the telemetry plane: ``REFLOW_FLEET_NODE``
    when set, else ``node-<pid>`` (unique per process on one host —
    the single-host fleet the benches run)."""
    nid = env_str("REFLOW_FLEET_NODE")
    return nid if nid else f"node-{os.getpid()}"


def clock_anchor(node: Optional[str] = None) -> Dict[str, Any]:
    """One (monotonic, wall) clock pairing for ``node``, taken now.
    Exchanged at handshake time so consumers can anchor another
    process's monotonic span timestamps to a shared wall-clock axis,
    within handshake-RTT error. Display only — never ordering."""
    return {"node": node if node is not None else node_id(),
            "mono": time.monotonic(), "wall": time.time()}


class TelemetryLink:
    """Client end of one telemetry connection: dial, ``hello``
    handshake (clock-anchor exchange), then ``snap`` pushes.

    The whole unreliable-link lifecycle mirrors
    :class:`~reflow_tpu.net.client.RemoteFollower`: a
    :class:`ReconnectPolicy` gates redials with capped backoff, and
    every failure path degrades to "this snapshot is dropped" —
    :meth:`send_snapshot` returns ``False`` instead of raising, so the
    shipper thread can never crash or stall on weather."""

    def __init__(self, transport: Transport, address, *,
                 node: Optional[str] = None,
                 policy: Optional[ReconnectPolicy] = None,
                 io_timeout_s: Optional[float] = None) -> None:
        self.transport = transport
        self.address = address
        self.node = node if node is not None else node_id()
        self.policy = policy if policy is not None \
            else ReconnectPolicy(f"telemetry/{self.node}")
        self.io_timeout_s = io_timeout_s
        self._conn: Optional[Conn] = None
        self.reconnects_total = 0
        self.link_failures = 0
        self.anchor: Optional[Dict[str, Any]] = None  # server's, +rtt

    @property
    def conn_state(self) -> str:
        return self.policy.state

    def _fail(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self.link_failures += 1
        self.policy.failed()

    def _dial(self) -> bool:
        """One gated dial + hello. True when the link is live."""
        if not self.policy.due():
            return False
        try:
            conn = self.transport.connect(self.address)
        except TransportError:
            self._fail()
            return False
        t0 = time.monotonic()
        try:
            conn.send_msg(("hello", self.node, clock_anchor(self.node)),
                          self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError:
            conn.close()
            self._fail()
            return False
        rtt = time.monotonic() - t0
        if not (isinstance(resp, tuple) and len(resp) >= 2
                and resp[0] == "ok" and isinstance(resp[1], dict)):
            conn.close()
            self._fail()
            return False
        anchor = dict(resp[1])
        # wall-skew estimate against the midpoint of the exchange;
        # error is bounded by rtt/2 and recorded alongside
        anchor["rtt_s"] = rtt
        anchor["wall_offset_s"] = anchor.get("wall", 0.0) - \
            (time.time() - rtt / 2.0)
        self.anchor = anchor
        self._conn = conn
        if self.policy.ok():
            self.reconnects_total += 1
        return True

    def _roundtrip(self, msg: tuple) -> Any:
        """One request-response; None on any link failure (the failure
        is absorbed: connection closed, backoff armed)."""
        if self._conn is None and not self._dial():
            return None
        conn = self._conn
        try:
            conn.send_msg(msg, self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError:
            self._fail()
            return None
        self.policy.ok()
        return resp

    def send_snapshot(self, snapshot: Dict[str, Any]) -> bool:
        """Push one registry snapshot. False means the snapshot was
        dropped (link down / backoff open / failed mid-exchange) —
        always tolerated, never raised."""
        resp = self._roundtrip(("snap", self.node, snapshot))
        return isinstance(resp, tuple) and bool(resp) \
            and resp[0] == "ok"

    def fetch_fleet(self) -> Optional[Dict[str, Any]]:
        """The aggregator's current fleet snapshot, or None when the
        aggregator is unreachable (consumers render the last one they
        saw, stale-marked)."""
        resp = self._roundtrip(("fleet",))
        if isinstance(resp, tuple) and len(resp) >= 2 \
                and resp[0] == "ok" and isinstance(resp[1], dict):
            return resp[1]
        return None

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class TelemetryServer:
    """Serve a :class:`~reflow_tpu.obs.fleet.FleetAggregator` over a
    transport listener — the fleet's telemetry ingest + query endpoint.

    Threading mirrors :class:`~reflow_tpu.net.server.ReplicaServer`:
    one accept loop plus one handler per connection, ``WireTimeout`` as
    "idle", any other ``TransportError`` as the end of that connection.
    A poisoned request degrades to ``("err", ...)`` — the aggregator
    must keep serving the healthy nodes no matter what one link sends.
    """

    def __init__(self, aggregator, transport: Transport, *,
                 node: Optional[str] = None) -> None:
        self.aggregator = aggregator
        self.transport = transport
        self.node = node if node is not None else node_id()
        self._listener = None
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = named_lock("obs.telemetry.server")
        self._conns: list = []
        self._handlers: list = []
        self.connections_total = 0
        self.requests_total = 0

    @property
    def address(self):
        if self._listener is None:
            raise TransportError("telemetry server not started")
        return self._listener.address

    def start(self) -> "TelemetryServer":
        if self._accept_thread is not None:
            return self
        self._listener = self.transport.listen()
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"telemetry-accept/{self.node}", daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept(timeout_s=_POLL_S)
            except WireTimeout:
                continue
            except TransportError:
                return  # listener closed under us
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self.connections_total += 1
                t = threading.Thread(
                    target=self._serve_conn, args=(conn,),
                    name=f"telemetry-serve/{self.connections_total}",
                    daemon=True)
                self._conns.append(conn)
                self._handlers.append(t)
            t.start()

    def _serve_conn(self, conn: Conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv_msg(timeout_s=_POLL_S)
                except WireTimeout:
                    continue
                except TransportError:
                    return
                try:
                    reply = self._dispatch(msg)
                except Exception as e:  # noqa: BLE001 - telemetry must
                    # never crash the aggregator endpoint
                    reply = ("err", f"{type(e).__name__}: {e}")
                try:
                    conn.send_msg(reply)
                except TransportError:
                    return
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _dispatch(self, msg):
        if not isinstance(msg, tuple) or not msg:
            return ("err", f"malformed request {type(msg).__name__}")
        self.requests_total += 1
        op, args = msg[0], msg[1:]
        agg = self.aggregator
        if op == "hello":
            if len(args) >= 2 and isinstance(args[1], dict):
                agg.record_anchor(str(args[0]), args[1])
            return ("ok", clock_anchor(self.node))
        if op == "snap":
            if len(args) < 2 or not isinstance(args[1], dict):
                return ("err", "malformed snap")
            agg.ingest(str(args[0]), args[1])
            return ("ok",)
        if op == "fleet":
            return ("ok", agg.fleet_snapshot())
        if op == "ping":
            return ("ok", {"node": self.node,
                           "nodes": agg.node_count()})
        return ("err", f"unknown op {op!r}")

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns = list(self._conns)
            handlers = list(self._handlers)
        for c in conns:
            c.close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        for h in handlers:
            h.join(timeout=5.0)
