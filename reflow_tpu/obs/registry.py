"""Live metrics: named counters/gauges + periodic JSONL snapshots.

A :class:`MetricsRegistry` holds three kinds of publishable state:

- **counters** — monotonically increasing totals owned by the registry
  (``registry.counter("serve.shed").inc(n)``);
- **gauges** — point-in-time values, either set directly or backed by a
  callable evaluated at snapshot time (``registry.gauge("budget.used",
  lambda: budget.used)``);
- **sources** — callables returning whole dicts, the bridge to the
  existing offline summaries: subsystems register
  ``lambda: summarize_serve(fe).to_dict()`` so live telemetry and
  post-hoc reports share one schema (``publish_metrics()`` on the
  frontend / tier / budget / WAL / scheduler wires these).

:class:`SnapshotEmitter` is a daemon thread appending one JSON line per
interval (schema tag ``reflow.obs.snapshot/1``) — tail the file or diff
trajectories across PRs. ``stop()`` emits a final snapshot so even a
sub-interval run records its end state.

Snapshot evaluation copies the registry under its lock, then calls
gauges/sources *outside* it: a source that itself takes a subsystem
lock (``summarize_tier`` takes the tier lock) can never deadlock
against a concurrent ``register_source``. A failing source degrades to
an ``{"error": ...}`` entry instead of killing the emitter.
"""

from __future__ import annotations

import json
import threading
import time

from reflow_tpu.utils.runtime import named_lock
from collections import deque
from typing import Any, Callable, Dict, Optional

__all__ = ["SNAPSHOT_SCHEMA", "Counter", "Gauge", "MetricsRegistry",
           "SnapshotEmitter", "REGISTRY"]

SNAPSHOT_SCHEMA = "reflow.obs.snapshot/1"


def _jsonify(obj: Any) -> Any:
    # numpy scalars/arrays and deques → plain python, so every snapshot
    # survives json.dumps no matter what a source hands back
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, deque)):
        return [_jsonify(v) for v in obj]
    if hasattr(obj, "item") and callable(obj.item):
        try:
            return obj.item()
        except Exception:
            pass
    if hasattr(obj, "tolist") and callable(obj.tolist):
        try:
            return obj.tolist()
        except Exception:
            pass
    return obj


class Counter:
    """Monotonic counter; ``inc`` is GIL-atomic for int increments."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value: ``set()`` it, or back it with a callable
    evaluated lazily at snapshot time."""

    __slots__ = ("name", "_value", "_fn")

    def __init__(self, name: str,
                 fn: Optional[Callable[[], Any]] = None):
        self.name = name
        self._value: Any = 0
        self._fn = fn

    def set(self, value: Any) -> None:
        self._value = value

    @property
    def value(self) -> Any:
        return self._fn() if self._fn is not None else self._value


class MetricsRegistry:
    """Thread-safe name → Counter/Gauge/source map with one-call
    :meth:`snapshot` (always ``json.dumps``-clean)."""

    def __init__(self):
        self._lock = named_lock("obs.registry")
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._sources: Dict[str, Callable[[], Dict[str, Any]]] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str,
              fn: Optional[Callable[[], Any]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g._fn = fn
            return g

    def register_source(self, name: str,
                        fn: Callable[[], Dict[str, Any]]) -> str:
        with self._lock:
            self._sources[name] = fn
        return name

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def unregister_prefix(self, prefix: str) -> None:
        """Drop every counter/gauge/source whose name starts with
        ``prefix`` — subsystem teardown (``close()``) hygiene."""
        with self._lock:
            for d in (self._counters, self._gauges, self._sources):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]

    def value(self, name: str, default: Any = None) -> Any:
        """Read one counter or gauge by name (counters shadow gauges on
        a name collision; ``default`` when neither exists or the gauge's
        callable fails). The point-read the control plane and bench
        assertions use — cheaper than a full :meth:`snapshot`, and the
        gauge callable runs OUTSIDE the registry lock for the same
        deadlock-hygiene reason snapshot's do."""
        with self._lock:
            c = self._counters.get(name)
            g = self._gauges.get(name)
        if c is not None:
            return c.value
        if g is None:
            return default
        try:
            return g.value
        except Exception:  # noqa: BLE001 - degrade like snapshot()
            return default

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = dict(self._gauges)
            sources = dict(self._sources)
        gvals: Dict[str, Any] = {}
        for k, g in gauges.items():
            try:
                gvals[k] = g.value
            except Exception as e:  # noqa: BLE001 - degrade per-gauge
                gvals[k] = f"error: {e}"
        svals: Dict[str, Any] = {}
        for k, fn in sources.items():
            try:
                svals[k] = fn()
            except Exception as e:  # noqa: BLE001 - degrade per-source
                svals[k] = {"error": str(e)}
        return _jsonify({"counters": counters, "gauges": gvals,
                         "sources": svals})


#: the process-wide default registry ``publish_metrics()`` targets when
#: no explicit registry is passed
REGISTRY = MetricsRegistry()


class SnapshotEmitter:
    """Background JSONL telemetry: appends one snapshot line every
    ``interval_s`` seconds (plus a final one at :meth:`stop`)."""

    def __init__(self, path: str, *, interval_s: float = 5.0,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.path = path
        self.interval_s = interval_s
        self.registry = registry if registry is not None else REGISTRY
        self.lines = 0
        self._clock = clock
        self._deadline: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._f = None

    def start(self) -> "SnapshotEmitter":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._f = open(self.path, "a")
        self._thread = threading.Thread(
            target=self._loop, name="reflow-obs-snapshot", daemon=True)
        self._thread.start()
        return self

    def _sleep_s(self) -> float:
        """Time left until the armed deadline — shrinks by however long
        the last emit took, so cadence does not drift with emit cost."""
        return max(0.0, self._deadline - self._clock())

    def _rearm(self) -> None:
        """Advance the deadline one interval from the *previous*
        deadline (fixed-rate), not from now (fixed-delay — the drift
        bug). If an emit overran a whole interval, snap forward instead
        of burst-emitting to catch up."""
        self._deadline += self.interval_s
        now = self._clock()
        if self._deadline <= now:
            self._deadline = now + self.interval_s

    def _loop(self) -> None:
        self._deadline = self._clock() + self.interval_s
        while not self._stop.wait(self._sleep_s()):
            self._emit()
            self._rearm()

    def _emit(self) -> None:
        snap = {"schema": SNAPSHOT_SCHEMA, "ts": time.time(),
                **self.registry.snapshot()}
        self._f.write(json.dumps(snap) + "\n")
        self._f.flush()
        self.lines += 1

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None
        self._emit()  # final snapshot: short runs still record end state
        self._f.close()
        self._f = None

    def __enter__(self) -> "SnapshotEmitter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
