"""Trace spans: lock-free per-thread ring buffers of timed events.

The serving stack mints a :class:`TraceCtx` at ``IngestFrontend.submit``
and carries it on the :class:`~reflow_tpu.serve.tickets.Ticket`; each
subsystem a ticket crosses (admission, coalesce queue, pump/tick, WAL
group-commit, resolve) records stage spans via :func:`evt`. Events land
in a fixed-size ring owned by the *recording* thread — no locks, no
allocation beyond the event tuple — so tracing a hot pump costs one
attribute check when disabled and one ring slot when enabled.

Disabled by default. Enable with ``REFLOW_TRACE=1`` in the environment
or ``obs.enable()`` at runtime; every instrumentation site guards with
a direct ``if trace.ENABLED:`` module-attribute read so the disabled
cost stays at a single dict lookup (the <1% serve-bench regression
budget in ISSUE 4).

Per-ticket sampling: minting is counted globally and every
``SAMPLE_EVERY``-th ticket (``REFLOW_TRACE_SAMPLE``, default 16) gets
``sampled=True`` — only sampled tickets emit the six-stage end-to-end
timeline (:func:`ticket_stages`); unsampled traffic still appears in
the aggregate per-thread spans (windows, ticks, WAL appends).

The stage tiling is exact by construction: ``admission`` ``[t0,t_adm]``,
``coalesce`` ``[t_adm,t_ready]``, ``sched_delay`` ``[t_ready,t_exec0]``,
``execute`` ``[t_exec0,t_exec1]``, ``fsync`` ``[t_exec1,t_dur]``,
``resolve`` ``[t_dur,t_res]`` — the six durations tile ``[t0,t_res]``
with no gaps or overlap, so they sum to the measured end-to-end ticket
latency (the 10% acceptance budget is headroom for export rounding, not
for model error). With the asynchronous WAL committer the ``fsync``
stage is the *durability wait*: the gap between the execute finishing
(``t_exec1``) and the ticket's LSN passing the durable watermark
(``t_dur``) — near-zero when the committer's fsync fully overlapped the
execute, the exposed disk latency when it didn't. The committer's own
``wal_fsync`` spans land on the ``wal-committer`` track.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ENABLED", "RING_CAPACITY", "SAMPLE_EVERY", "STAGES",
           "TraceCtx", "enable", "disable", "enabled", "reset", "evt",
           "mint", "mint_cause", "sample", "set_flight_hook",
           "ticket_stages", "wal_accum_reset", "wal_accum_add",
           "wal_accum_take"]

#: hot-path gate — read directly (``if trace.ENABLED:``) at every
#: instrumentation site; never wrapped in a function call
ENABLED = False

from reflow_tpu.utils.config import env_flag, env_int

RING_CAPACITY = env_int("REFLOW_TRACE_RING")
SAMPLE_EVERY = max(1, env_int("REFLOW_TRACE_SAMPLE"))

#: the per-ticket stage names, in pipeline order
STAGES = ("admission", "coalesce", "sched_delay", "execute", "fsync",
          "resolve")

#: event tuple: (name, ts_s, dur_s, track_override_or_None, args_or_None)
Event = Tuple[str, float, float, Optional[str], Optional[Dict[str, Any]]]

_rings: List["Ring"] = []
from reflow_tpu.utils.runtime import named_lock

_rings_lock = named_lock("obs.trace.rings")  # ring *registration* only, never puts
_tls = threading.local()
_gen = 0
_mint_n = itertools.count()
_cause_n = itertools.count()

#: optional flight-recorder tee (obs/flight.py installs it): called as
#: ``hook(name, ts, dur, track, args)`` after every ring put. A plain
#: module global (like ENABLED) so the disabled cost is one None check.
_flight_hook = None


def set_flight_hook(hook) -> None:
    """Install (or clear, with None) the flight-recorder tee on
    :func:`evt`. One consumer at a time — the per-process
    :class:`~reflow_tpu.obs.flight.FlightRecorder`."""
    global _flight_hook
    _flight_hook = hook


class TraceCtx:
    """Per-submission trace context carried on the Ticket.

    ``cause`` is the optional causality token (:func:`mint_cause`) that
    correlates this context with spans recorded in *other processes* —
    the replication path stamps it onto :class:`~reflow_tpu.wal.ship.
    Shipment` frames so ``ship_segment`` → ``net_send`` →
    ``replica_replay`` stitch into one cross-process chain."""

    __slots__ = ("batch_id", "t0", "sampled", "cause")

    def __init__(self, batch_id: str, t0: float, sampled: bool,
                 cause: Optional[str] = None):
        self.batch_id = batch_id
        self.t0 = t0
        self.sampled = sampled
        self.cause = cause


class Ring:
    """Fixed-size overwrite-oldest event buffer, single-writer (the
    owning thread); snapshots tolerate concurrent writes by copying."""

    __slots__ = ("track", "cap", "buf", "n", "gen")

    def __init__(self, track: str, cap: int, gen: int):
        self.track = track
        self.cap = cap
        self.buf: List[Optional[Event]] = [None] * cap
        self.n = 0
        self.gen = gen

    def put(self, ev: Event) -> None:
        self.buf[self.n % self.cap] = ev
        self.n += 1

    def events(self) -> List[Event]:
        """Buffered events, oldest first (an approximate snapshot if the
        owner is still writing — fine for export)."""
        n, cap = self.n, self.cap
        if n <= cap:
            return [e for e in self.buf[:n] if e is not None]
        i = n % cap
        return [e for e in self.buf[i:] + self.buf[:i] if e is not None]


def _ring() -> Ring:
    r = getattr(_tls, "ring", None)
    if r is None or r.gen != _gen:
        r = Ring(threading.current_thread().name, RING_CAPACITY, _gen)
        _tls.ring = r
        with _rings_lock:
            _rings.append(r)
    return r


def enable() -> None:
    """Turn tracing on (idempotent)."""
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def reset() -> None:
    """Drop all buffered events and detach every thread's ring (they
    re-register lazily via a generation bump). Tests / bench baselines."""
    global _gen
    with _rings_lock:
        _gen += 1
        _rings.clear()


def evt(name: str, ts: float, dur: float, track: Optional[str] = None,
        args: Optional[Dict[str, Any]] = None) -> None:
    """Record one complete span: ``ts`` is a ``time.perf_counter()``
    start, ``dur`` seconds. ``track`` overrides the export row (default:
    the recording thread's name)."""
    if not ENABLED:
        return
    _ring().put((name, ts, dur, track, args))
    if _flight_hook is not None:
        _flight_hook(name, ts, dur, track, args)


def mint(batch_id: str, t0: float) -> TraceCtx:
    """Mint the trace context for one submission (call under ENABLED)."""
    return TraceCtx(batch_id, t0,
                    next(_mint_n) % SAMPLE_EVERY == 0)


def sample() -> bool:
    """One draw from the global 1-in-``SAMPLE_EVERY`` sampler — the
    same counter :func:`mint` uses, for callers (the remote producer)
    that decide sampling *before* a ticket exists. The decision then
    rides the minted causality token over the wire so every downstream
    process records the same writes without re-rolling."""
    return next(_mint_n) % SAMPLE_EVERY == 0


def mint_cause(origin: str, epoch: int) -> str:
    """Mint one causality token: ``<origin>#<epoch>#<seq>``.

    ``origin`` is the minting node's fleet id, ``epoch`` the WAL epoch
    the work belongs to, ``seq`` a process-local monotonic counter.
    The token is an opaque string on purpose: it rides span ``args``
    (JSON) and the pickled ``Shipment`` wire frame unchanged, and every
    process that re-records it under its own clock still joins on exact
    string equality — no cross-host clock trust required."""
    return f"{origin}#{epoch}#{next(_cause_n)}"


def ticket_stages(ctx: TraceCtx, *, t_adm: float, t_ready: float,
                  t_exec0: float, t_exec1: float, t_dur: float,
                  t_res: float) -> None:
    """Emit the six-stage end-to-end timeline of one sampled ticket onto
    its own ``ticket/<batch_id>`` track. ``t_dur`` is the durability
    point — when the ticket's LSN passed ``wal.wait_durable`` (equal to
    ``t_exec1`` on a non-durable scheduler, so the fsync stage collapses
    to zero). Boundaries are clamped into pipeline order so the stages
    tile ``[ctx.t0, t_res]`` exactly."""
    if not ENABLED:
        return
    track = f"ticket/{ctx.batch_id}"
    t_adm = max(ctx.t0, min(t_adm, t_exec0))
    c1 = max(t_adm, min(t_ready, t_exec0))      # coalesce end
    t_res = max(t_exec1, t_res)
    d = max(t_exec1, min(t_dur, t_res))         # durability point
    spans = (("admission", ctx.t0, t_adm),
             ("coalesce", t_adm, c1),
             ("sched_delay", c1, t_exec0),
             ("execute", t_exec0, t_exec1),
             ("fsync", t_exec1, d),
             ("resolve", d, t_res))
    args: Dict[str, Any] = {"batch_id": ctx.batch_id}
    if ctx.cause:
        args["cause"] = ctx.cause
    for name, s, e in spans:
        evt(name, s, e - s, track=track, args=args)


# -- WAL time accumulator (legacy) -------------------------------------------
# Pre-pipeline tiling carved WAL append+fsync wall time out of the
# execute span via this thread-local; with the asynchronous committer
# the fsync stage is measured directly as the durability wait
# ([t_exec1, t_dur]), so the frontend no longer feeds it. Kept for
# external instrumentation that still accumulates per-thread WAL time.

def wal_accum_reset() -> None:
    _tls.wal_s = 0.0


def wal_accum_add(s: float) -> None:
    _tls.wal_s = getattr(_tls, "wal_s", 0.0) + s


def wal_accum_take() -> float:
    s = getattr(_tls, "wal_s", 0.0)
    _tls.wal_s = 0.0
    return s


if env_flag("REFLOW_TRACE"):
    enable()
