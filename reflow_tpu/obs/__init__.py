"""reflow_tpu.obs — tracing + live metrics for the serving stack.

Two halves, one import:

- **Trace spans** (:mod:`.trace` / :mod:`.export`): per-thread ring
  buffers of timed stage spans, off by default (``REFLOW_TRACE=1`` or
  :func:`enable`), exported as Chrome trace-event JSON for Perfetto.
  Sampled tickets get a six-stage end-to-end timeline (admission /
  coalesce / sched_delay / execute / fsync / resolve) that tiles the
  measured ticket latency exactly.
- **Live registry** (:mod:`.registry`): named counters/gauges plus
  ``register_source`` bridges to the existing ``summarize_*().to_dict()``
  schemas; :class:`SnapshotEmitter` appends periodic JSONL snapshots.
- **Fleet telemetry** (:mod:`.fleet` / :mod:`.wire`): each node's
  :class:`TelemetryShipper` streams registry snapshots over the
  ``net/`` transports to a :class:`FleetAggregator` (behind a
  :class:`TelemetryServer`), which derives cross-node gauges — lag
  spread, link health, epoch agreement — and stale-marks nodes whose
  telemetry link drops. Loss is always tolerated, never blocking.

Quickstart::

    from reflow_tpu import obs
    obs.enable()                       # or REFLOW_TRACE=1
    fe.publish_metrics()               # frontend/tier/wal/sched/budget
    with obs.SnapshotEmitter("telemetry.jsonl", interval_s=2.0):
        ...serve traffic...
    obs.export_chrome_trace("trace.json")   # open in ui.perfetto.dev
"""

from . import export, registry, trace  # noqa: F401
from .export import chrome_events, export_chrome_trace, ticket_timelines
from .registry import (REGISTRY, SNAPSHOT_SCHEMA, Counter, Gauge,
                       MetricsRegistry, SnapshotEmitter)
from .trace import (STAGES, TraceCtx, disable, enable, enabled, evt,
                    mint, mint_cause, ticket_stages)

__all__ = ["chrome_events", "export_chrome_trace", "ticket_timelines",
           "REGISTRY", "SNAPSHOT_SCHEMA", "Counter", "Gauge",
           "MetricsRegistry", "SnapshotEmitter", "STAGES", "TraceCtx",
           "disable", "enable", "enabled", "evt", "mint", "mint_cause",
           "ticket_stages", "FLEET_SCHEMA", "FleetAggregator",
           "TelemetryShipper", "TelemetryLink", "TelemetryServer",
           "clock_anchor", "node_id"]

# The fleet plane rides the net/ transports, and net/ itself traces
# through this package — resolve the cycle by loading fleet/wire names
# lazily (PEP 562) instead of at obs import time.
_FLEET_NAMES = {"FLEET_SCHEMA": "fleet", "FleetAggregator": "fleet",
                "TelemetryShipper": "fleet", "TelemetryLink": "wire",
                "TelemetryServer": "wire", "clock_anchor": "wire",
                "node_id": "wire", "fleet": None, "wire": None}


def __getattr__(name):
    mod = _FLEET_NAMES.get(name, "")
    if mod == "":
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    if mod is None:
        return importlib.import_module(f".{name}", __name__)
    return getattr(importlib.import_module(f".{mod}", __name__), name)
