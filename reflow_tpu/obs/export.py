"""Exporters: trace rings → Chrome trace-event JSON (Perfetto-viewable).

``export_chrome_trace()`` snapshots every thread's ring and writes the
standard ``{"traceEvents": [...]}`` object: one ``"X"`` complete event
per span (``ts``/``dur`` in microseconds relative to the earliest
buffered event), one *track* per recording thread — pump workers,
producers — plus override tracks (``wal``, ``ticket/<batch_id>``)
surfaced as their own rows via ``thread_name`` metadata events. Open
the file at https://ui.perfetto.dev or ``chrome://tracing``.

``ticket_timelines()`` is the shared reader: given a chrome event list
it reconstructs each sampled ticket's stage durations and end-to-end
span — ``tools/trace_inspect.py`` and the ``REFLOW_BENCH_OBS=1`` bench
both consume it, so the decomposition check and the human report can
never drift apart.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from . import trace

__all__ = ["chrome_events", "export_chrome_trace", "ticket_timelines"]


def chrome_events() -> List[Dict[str, Any]]:
    """Snapshot all rings into a chrome trace-event list (metadata
    events first, then ``"X"`` spans). Empty when nothing was traced.

    A ring that wrapped has silently overwritten its oldest events —
    which can truncate a causal chain mid-window — so each wrapped
    ring's track carries a ``dropped_events`` metadata event with the
    exact overwrite count (``Ring.n`` counts every put ever, so drops
    are ``n - cap``); readers must treat such tracks as incomplete
    rather than assuming the window starts at the first surviving
    event."""
    return _snapshot()[0]


def _snapshot() -> tuple:
    """``(chrome_events, base_time_s)`` from one ring snapshot — the
    base is computed from the same events, so ``baseTimeS`` in the
    exported file can never drift from the ``ts`` values."""
    with trace._rings_lock:
        rings = list(trace._rings)
    raw = []
    dropped: Dict[str, int] = {}
    for r in rings:
        n = r.n
        if n > r.cap:
            dropped[r.track] = dropped.get(r.track, 0) + (n - r.cap)
        for ev in r.events():
            raw.append((r.track, ev))
    if not raw:
        return [], 0.0
    base = min(ev[1] for _t, ev in raw)
    tids: Dict[str, int] = {}
    for t in dropped:
        tids[t] = len(tids) + 1
    spans = []
    for ring_track, (name, ts, dur, track, args) in raw:
        t = track or ring_track
        tid = tids.get(t)
        if tid is None:
            tid = tids[t] = len(tids) + 1
        e = {"name": name, "ph": "X", "cat": "reflow",
             "ts": round((ts - base) * 1e6, 3),
             "dur": round(dur * 1e6, 3), "pid": 1, "tid": tid}
        if args:
            e["args"] = args
        spans.append(e)
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "reflow"}}]
    for t, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append({"ph": "M", "name": "thread_name", "pid": 1,
                     "tid": tid, "args": {"name": t}})
    for t, count in sorted(dropped.items()):
        meta.append({"ph": "M", "name": "dropped_events", "pid": 1,
                     "tid": tids[t], "args": {"track": t,
                                              "count": count}})
    return meta + spans, base


def export_chrome_trace(path: Optional[str] = None) -> str:
    """Write the chrome trace JSON; returns the path written
    (``REFLOW_TRACE_OUT`` or ``reflow_trace.json`` by default).

    Besides the standard ``traceEvents``, the file carries two
    top-level keys that make multi-process merging possible:
    ``baseTimeS`` — the ``perf_counter()`` value every ``ts`` is
    relative to (processes on one host share the monotonic clock, so
    ``baseTimeS + ts/1e6`` is directly comparable across files) — and
    ``node`` — this process's fleet node id. Chrome/Perfetto ignore
    unknown top-level keys, so the file stays viewer-compatible."""
    from reflow_tpu.obs.wire import node_id
    from reflow_tpu.utils.config import env_str
    path = path or env_str("REFLOW_TRACE_OUT")
    events, base = _snapshot()
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "baseTimeS": base,
                   "node": node_id()}, f)
    return path


def ticket_timelines(events: List[Dict[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    """Reconstruct per-ticket stage timelines from a chrome event list:
    ``{batch_id: {"stages": {name: dur_us}, "e2e_us": .., "sum_us": ..}}``
    where ``e2e_us`` spans the earliest start to the latest end of the
    ticket's events and ``sum_us`` totals its stage durations."""
    names: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev.get("tid", -1)] = ev.get("args", {}).get("name", "")
    out: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        track = names.get(ev.get("tid", -1), "")
        if not track.startswith("ticket/"):
            continue
        bid = track[len("ticket/"):]
        t = out.setdefault(bid, {"stages": {}, "_t0": None, "_t1": None})
        dur = float(ev.get("dur", 0.0))
        name = ev.get("name", "?")
        t["stages"][name] = t["stages"].get(name, 0.0) + dur
        s = float(ev.get("ts", 0.0))
        t["_t0"] = s if t["_t0"] is None else min(t["_t0"], s)
        t["_t1"] = (s + dur if t["_t1"] is None
                    else max(t["_t1"], s + dur))
    for t in out.values():
        t["e2e_us"] = (t.pop("_t1") or 0.0) - (t.pop("_t0") or 0.0)
        t["sum_us"] = sum(t["stages"].values())
    return out
