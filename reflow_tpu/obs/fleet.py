"""Fleet telemetry: per-node snapshot shipping + cross-node gauges
(docs/guide.md "Fleet telemetry").

Per-process observability (PR 4) answers "what is *this* node doing";
this module answers "what is the *fleet* doing" without ssh-ing into
every process. Each node runs a :class:`TelemetryShipper` that tails
its :class:`~reflow_tpu.obs.registry.MetricsRegistry` and streams
``reflow.obs.snapshot/1`` lines over the ``net/`` transports to a
:class:`FleetAggregator`, which keeps a retention-bounded per-node
time-series ring and derives the gauges no single node can compute:

- **replication lag spread** — max−min follower horizon across nodes;
- **per-link health** — every ``*.conn_state`` gauge in the fleet;
- **epoch agreement** — any node still behind the failover fence;
- **compaction debt** — summed ``compact.reclaimable_bytes``;
- **aggregate read QPS** — summed per-node read rates (from
  consecutive snapshots of the cumulative read counters).

Loss semantics: telemetry is *advisory*. A dropped snapshot, a
partitioned telemetry link, or a dead aggregator degrades to stale
gauges (each node entry carries ``age_s``/``stale``) — never an
exception, and never back-pressure on the data path. The shipper runs
on its own daemon thread with the same fixed-rate deadline re-arm as
:class:`~reflow_tpu.obs.registry.SnapshotEmitter`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from reflow_tpu.net.backoff import ReconnectPolicy
from reflow_tpu.net.transport import Transport
from reflow_tpu.obs.registry import (REGISTRY, SNAPSHOT_SCHEMA,
                                     MetricsRegistry)
from reflow_tpu.obs.wire import TelemetryLink, node_id
from reflow_tpu.utils.config import env_float, env_int
from reflow_tpu.utils.runtime import named_lock

__all__ = ["FLEET_SCHEMA", "FleetAggregator", "TelemetryShipper"]

FLEET_SCHEMA = "reflow.fleet/1"


def _num(v: Any) -> Optional[float]:
    """A gauge value as a float, or None for the non-numeric ones
    (conn-state strings, degraded ``"error: ..."`` entries)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _suffix_values(gauges: Dict[str, Any], suffix: str
                   ) -> Dict[str, float]:
    out = {}
    for k, v in gauges.items():
        if k.endswith(suffix):
            n = _num(v)
            if n is not None:
                out[k] = n
    return out


class TelemetryShipper:
    """Tail one registry and stream its snapshots to the aggregator.

    Every ``interval_s`` (``REFLOW_FLEET_INTERVAL_S``) the shipper
    snapshots ``registry`` and pushes it over its
    :class:`~reflow_tpu.obs.wire.TelemetryLink`. A failed push is
    *dropped* (counted in :attr:`dropped`) — the link's
    :class:`ReconnectPolicy` backs off and later beats retry with
    fresh data; stale snapshots are never queued, because the newest
    one supersedes everything a dead link missed."""

    def __init__(self, registry: Optional[MetricsRegistry],
                 transport: Transport, address, *,
                 node: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 policy: Optional[ReconnectPolicy] = None,
                 io_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.node = node if node is not None else node_id()
        self.interval_s = interval_s if interval_s is not None \
            else env_float("REFLOW_FLEET_INTERVAL_S")
        self.link = TelemetryLink(transport, address, node=self.node,
                                  policy=policy,
                                  io_timeout_s=io_timeout_s)
        self.shipped = 0
        self.dropped = 0
        self._clock = clock
        self._deadline: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._metric_names: List[Tuple[MetricsRegistry, str]] = []

    def build_snapshot(self) -> Dict[str, Any]:
        return {"schema": SNAPSHOT_SCHEMA, "node": self.node,
                "ts_wall": time.time(), "ts_mono": time.monotonic(),
                **self.registry.snapshot()}

    def ship_once(self) -> bool:
        """Snapshot + push one beat; False when the push was dropped.
        Never raises — telemetry failures are stale gauges, not
        errors."""
        try:
            ok = self.link.send_snapshot(self.build_snapshot())
        except Exception:  # noqa: BLE001 - loss is always tolerated
            ok = False
        if ok:
            self.shipped += 1
        else:
            self.dropped += 1
        return ok

    # -- thread loop (fixed-rate, same re-arm as SnapshotEmitter) ------

    def _sleep_s(self) -> float:
        return max(0.0, self._deadline - self._clock())

    def _rearm(self) -> None:
        self._deadline += self.interval_s
        now = self._clock()
        if self._deadline <= now:
            self._deadline = now + self.interval_s

    def _loop(self) -> None:
        self._deadline = self._clock() + self.interval_s
        while not self._stop.wait(self._sleep_s()):
            self.ship_once()
            self._rearm()

    def start(self) -> "TelemetryShipper":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"telemetry-ship/{self.node}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def close(self) -> None:
        self.stop()
        self.link.close()
        for reg, name in self._metric_names:
            reg.unregister_prefix(name)
        self._metric_names.clear()

    # -- observability (the shipper observes itself too) ---------------

    def publish_metrics(self, registry: Optional[MetricsRegistry]
                        = None, name: str = "telemetry") -> None:
        reg = registry if registry is not None else self.registry
        reg.gauge(f"{name}.shipped", lambda: self.shipped)
        reg.gauge(f"{name}.dropped", lambda: self.dropped)
        reg.gauge(f"{name}.conn_state", lambda: self.link.conn_state)
        self._metric_names.append((reg, name))


class FleetAggregator:
    """Retention-bounded per-node snapshot rings + derived fleet
    gauges. Thread-safe: ingest happens on telemetry handler threads
    while consumers (``fleet_inspect`` / ``reflow_top`` /
    ``ControlPlane``) read :meth:`fleet_snapshot` concurrently.

    A node whose newest snapshot is older than ``stale_after_s``
    (``REFLOW_FLEET_STALE_S``) is *stale-marked*, not evicted: during
    a telemetry-link partition the fleet view keeps serving the last
    known state with an honest age on it."""

    def __init__(self, *, retention: Optional[int] = None,
                 stale_after_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time) -> None:
        self.retention = retention if retention is not None \
            else env_int("REFLOW_FLEET_RETENTION")
        self.stale_after_s = stale_after_s if stale_after_s is not None \
            else env_float("REFLOW_FLEET_STALE_S")
        self.lag_spread_max = env_int("REFLOW_FLEET_LAG_SPREAD_MAX")
        self._clock = clock
        self._wall = wall
        self._lock = named_lock("obs.fleet")
        self._rings: Dict[str, deque] = {}   # node -> (recv_mono, snap)
        self._anchors: Dict[str, Dict[str, Any]] = {}
        self.snapshots_total = 0
        self._metric_names: List[Tuple[MetricsRegistry, str]] = []

    # -- ingest (called from TelemetryServer handler threads) ----------

    def ingest(self, node: str, snapshot: Dict[str, Any]) -> None:
        now = self._clock()
        with self._lock:
            ring = self._rings.get(node)
            if ring is None:
                ring = self._rings[node] = deque(maxlen=self.retention)
            ring.append((now, snapshot))
            self.snapshots_total += 1

    def record_anchor(self, node: str, anchor: Dict[str, Any]) -> None:
        with self._lock:
            self._anchors[node] = dict(anchor)

    def node_count(self) -> int:
        with self._lock:
            return len(self._rings)

    def await_nodes(self, n: int, timeout_s: float = 10.0,
                    poll_s: float = 0.02) -> bool:
        """Block until at least ``n`` distinct nodes have shipped a
        snapshot (the process harness's "fleet is up" gate: a child
        counts as joined once its first telemetry beat lands). Returns
        False on timeout — telemetry loss is tolerated by design, so
        callers decide whether an incomplete fleet is an error."""
        deadline = self._clock() + timeout_s
        while self.node_count() < n:
            if self._clock() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    # -- per-node derivation -------------------------------------------

    def _node_entry(self, ring: deque, now: float) -> Dict[str, Any]:
        recv_mono, snap = ring[-1]
        gauges = snap.get("gauges", {}) or {}
        age = max(0.0, now - recv_mono)
        horizons = _suffix_values(gauges, ".horizon")
        lags = _suffix_values(gauges, ".lag_ticks")
        epochs = _suffix_values(gauges, ".epoch")
        conn = {k: v for k, v in gauges.items()
                if k.endswith(".conn_state") and isinstance(v, str)}
        entry: Dict[str, Any] = {
            "age_s": round(age, 4),
            "stale": age > self.stale_after_s,
            "snapshots": len(ring),
            "ts_wall": snap.get("ts_wall"),
            "horizon": max(horizons.values()) if horizons else None,
            "lag_ticks": max(lags.values()) if lags else None,
            "epoch": max(epochs.values()) if epochs else None,
            "conn_states": conn,
            "reads_total": self._reads_total(gauges),
            "read_qps": self._read_qps(ring),
            "compact_debt_bytes": _num(
                gauges.get("compact.reclaimable_bytes")),
            "ship_backlog_segments": _num(
                gauges.get("ship.backlog_segments")),
            "subs_active": _num(gauges.get("subs.active")),
            "sub_rows_s": self._sub_rows_s(ring),
            "sub_conflations": self._sub_conflations(gauges),
            "sub_lag_windows": _num(gauges.get("subs.slowest_lag")),
            # None on snapshots from pre-upgrade nodes (gauge absent)
            "sub_freshness_p50": _num(gauges.get("subs.freshness_p50")),
            "sub_freshness_p99": _num(gauges.get("subs.freshness_p99")),
            "flight_events": _num(gauges.get("flight.events_total")),
            # tiled maintenance (REFLOW_TILE_BYTES > 0): worst resident
            # tile across this node's compactor/chain, published
            # snapshot tiles across its replicas
            "tile_peak_bytes": (max(_suffix_values(
                gauges, ".peak_tile_bytes").values())
                if _suffix_values(gauges, ".peak_tile_bytes")
                else None),
            "snapshot_tiles": (int(sum(_suffix_values(
                gauges, ".snapshot_tiles").values()))
                if _suffix_values(gauges, ".snapshot_tiles")
                else None),
        }
        brownout = {k: v for k, v in gauges.items() if "brownout" in k}
        if brownout:
            entry["brownout"] = brownout
        return entry

    @staticmethod
    def _reads_total(gauges: Dict[str, Any]) -> Optional[float]:
        total, seen = 0.0, False
        for suffix in (".replica_reads", ".leader_fallbacks"):
            for v in _suffix_values(gauges, suffix).values():
                total += v
                seen = True
        return total if seen else None

    def _read_qps(self, ring: deque) -> Optional[float]:
        """Read rate across the retention window: newest minus oldest
        cumulative read counter, over the *sender's* monotonic clock
        (one process, so the delta is trustworthy; wall clocks never
        enter it)."""
        if len(ring) < 2:
            return None
        new, old = ring[-1][1], ring[0][1]
        rn = self._reads_total(new.get("gauges", {}) or {})
        ro = self._reads_total(old.get("gauges", {}) or {})
        tn, to = _num(new.get("ts_mono")), _num(old.get("ts_mono"))
        if rn is None or ro is None or tn is None or to is None \
                or tn <= to:
            return None
        return max(0.0, (rn - ro) / (tn - to))

    @staticmethod
    def _sub_conflations(gauges: Dict[str, Any]) -> Optional[float]:
        total, seen = 0.0, False
        for key in ("subs.conflations_total", "subs.sheds_total"):
            v = _num(gauges.get(key))
            if v is not None:
                total += v
                seen = True
        return total if seen else None

    def _sub_rows_s(self, ring: deque) -> Optional[float]:
        """Fan-out row rate, derived exactly like ``_read_qps``: the
        cumulative ``subs.fanout_rows_total`` counter differenced over
        the sender's monotonic clock. None until a node ships two
        snapshots that carry the gauge (old fleets never do)."""
        if len(ring) < 2:
            return None
        new, old = ring[-1][1], ring[0][1]
        rn = _num((new.get("gauges", {}) or {}).get(
            "subs.fanout_rows_total"))
        ro = _num((old.get("gauges", {}) or {}).get(
            "subs.fanout_rows_total"))
        tn, to = _num(new.get("ts_mono")), _num(old.get("ts_mono"))
        if rn is None or ro is None or tn is None or to is None \
                or tn <= to:
            return None
        return max(0.0, (rn - ro) / (tn - to))

    # -- the fleet view -------------------------------------------------

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The whole fleet as one dict (schema ``reflow.fleet/1``):
        per-node entries plus the derived cross-node gauges and the
        alert lines both consoles render."""
        now = self._clock()
        with self._lock:
            rings = {n: ring for n, ring in self._rings.items() if ring}
            nodes = {n: self._node_entry(ring, now)
                     for n, ring in rings.items()}
            anchors = {n: dict(a) for n, a in self._anchors.items()}
            total = self.snapshots_total
        horizons = [e["horizon"] for e in nodes.values()
                    if e["horizon"] is not None]
        epochs = sorted({int(e["epoch"]) for e in nodes.values()
                         if e["epoch"] is not None})
        qps = [e["read_qps"] for e in nodes.values()
               if e["read_qps"] is not None]
        debt = [e["compact_debt_bytes"] for e in nodes.values()
                if e["compact_debt_bytes"] is not None]
        backlog = [e["ship_backlog_segments"] for e in nodes.values()
                   if e["ship_backlog_segments"] is not None]
        subs = [e["subs_active"] for e in nodes.values()
                if e["subs_active"] is not None]
        sub_rows = [e["sub_rows_s"] for e in nodes.values()
                    if e["sub_rows_s"] is not None]
        sub_lag = [e["sub_lag_windows"] for e in nodes.values()
                   if e["sub_lag_windows"] is not None]
        sub_f50 = [e["sub_freshness_p50"] for e in nodes.values()
                   if e["sub_freshness_p50"] is not None]
        sub_f99 = [e["sub_freshness_p99"] for e in nodes.values()
                   if e["sub_freshness_p99"] is not None]
        flight_ev = [e["flight_events"] for e in nodes.values()
                     if e["flight_events"] is not None]
        tile_peaks = [e["tile_peak_bytes"] for e in nodes.values()
                      if e["tile_peak_bytes"] is not None]
        snap_tiles = [e["snapshot_tiles"] for e in nodes.values()
                      if e["snapshot_tiles"] is not None]
        link_states: Dict[str, int] = {}
        for e in nodes.values():
            for state in e["conn_states"].values():
                link_states[state] = link_states.get(state, 0) + 1
        stale = sorted(n for n, e in nodes.items() if e["stale"])
        lag_spread = (max(horizons) - min(horizons)) if horizons \
            else None
        gauges: Dict[str, Any] = {
            "nodes_total": len(nodes),
            "nodes_stale": len(stale),
            "lag_spread": lag_spread,
            "epochs": epochs,
            "epoch_agree": len(epochs) <= 1,
            "aggregate_read_qps": round(sum(qps), 3) if qps else None,
            "compact_debt_bytes": sum(debt) if debt else None,
            "ship_backlog_segments": max(backlog) if backlog else None,
            "subs_active": int(sum(subs)) if subs else None,
            "sub_rows_s": round(sum(sub_rows), 3) if sub_rows else None,
            "sub_lag_windows": max(sub_lag) if sub_lag else None,
            # worst push freshness across the fleet (seconds); None
            # until some node ships the gauge (pre-upgrade snapshots)
            "subs.freshness_p50": (round(max(sub_f50), 6)
                                   if sub_f50 else None),
            "subs.freshness_p99": (round(max(sub_f99), 6)
                                   if sub_f99 else None),
            "flight.events_total": (int(sum(flight_ev))
                                    if flight_ev else None),
            "tile_peak_bytes": max(tile_peaks) if tile_peaks else None,
            "snapshot_tiles": (int(sum(snap_tiles))
                               if snap_tiles else None),
            "link_states": link_states,
            "max_age_s": round(max(
                (e["age_s"] for e in nodes.values()), default=0.0), 4),
            "snapshots_total": total,
        }
        alerts: List[str] = []
        for n in stale:
            alerts.append(f"stale: {n} last seen "
                          f"{nodes[n]['age_s']:.1f}s ago")
        if len(epochs) > 1:
            alerts.append(f"epoch disagreement: {epochs}")
        if lag_spread is not None \
                and lag_spread > self.lag_spread_max:
            alerts.append(f"lag spread {int(lag_spread)} ticks exceeds "
                          f"{self.lag_spread_max}")
        return {"schema": FLEET_SCHEMA, "ts_wall": self._wall(),
                "nodes": nodes, "gauges": gauges, "alerts": alerts,
                "anchors": anchors}

    # -- point reads (ControlPlane / gauges) ----------------------------

    def lag_spread(self) -> Optional[float]:
        return self.fleet_snapshot()["gauges"]["lag_spread"]

    def stale_nodes(self) -> List[str]:
        snap = self.fleet_snapshot()
        return sorted(n for n, e in snap["nodes"].items()
                      if e["stale"])

    # -- observability --------------------------------------------------

    def publish_metrics(self, registry: Optional[MetricsRegistry]
                        = None, name: str = "fleet") -> None:
        reg = registry if registry is not None else REGISTRY

        def _gauge(key):
            return lambda: self.fleet_snapshot()["gauges"][key]

        reg.gauge(f"{name}.nodes_total", _gauge("nodes_total"))
        reg.gauge(f"{name}.nodes_stale", _gauge("nodes_stale"))
        reg.gauge(f"{name}.lag_spread", _gauge("lag_spread"))
        reg.gauge(f"{name}.epoch_agree", _gauge("epoch_agree"))
        reg.gauge(f"{name}.aggregate_read_qps",
                  _gauge("aggregate_read_qps"))
        reg.gauge(f"{name}.compact_debt_bytes",
                  _gauge("compact_debt_bytes"))
        reg.gauge(f"{name}.snapshots_total",
                  lambda: self.snapshots_total)
        self._metric_names.append((reg, name))

    def close(self) -> None:
        for reg, name in self._metric_names:
            reg.unregister_prefix(name)
        self._metric_names.clear()
