"""reflow_tpu — a TPU-native incremental (change-driven) dataflow framework.

Capability parity target: LDuderino/reflow (see SURVEY.md — the reference
mount was empty at survey time, so parity is against the reconstructed
capability spec in SURVEY.md §0–§2, derived from trusted driver metadata in
BASELINE.json).

Model
-----
Users build a :class:`~reflow_tpu.graph.FlowGraph` of keyed dataflow
operators (Map, Filter, GroupBy, Reduce, Join) over *collections*: multisets
of ``(key, value)`` rows with signed integer multiplicities (weights).
Changes enter the graph as *deltas* — batches of ``(key, value, weight)``
rows where ``weight > 0`` inserts and ``weight < 0`` retracts — and a
:class:`~reflow_tpu.scheduler.DirtyScheduler` recomputes only the invalidated
nodes each tick. Execution is pluggable behind the
:class:`~reflow_tpu.executors.Executor` interface: the NumPy
:class:`~reflow_tpu.executors.CpuExecutor` is the default correctness oracle,
and the JAX :class:`~reflow_tpu.executors.TpuExecutor` lowers each tick's
dirty batch to a single jit-compiled XLA step over device-resident, padded,
optionally mesh-sharded delta buffers.
"""

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.executors import CpuExecutor, Executor, get_executor
from reflow_tpu.serve import IngestFrontend
from reflow_tpu.utils.config import ReflowConfig
from reflow_tpu.wal import DurableScheduler, recover

__version__ = "0.1.0"

__all__ = [
    "DeltaBatch",
    "Spec",
    "FlowGraph",
    "DirtyScheduler",
    "DurableScheduler",
    "Executor",
    "CpuExecutor",
    "IngestFrontend",
    "get_executor",
    "recover",
    "ReflowConfig",
    "__version__",
]
