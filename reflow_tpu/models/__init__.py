"""Model zoo for model-in-the-loop workloads (SURVEY.md §2 item 12,
config 5: ViT feature extraction embedded as a Map function)."""

from reflow_tpu.models.vit import VIT_B_16, VIT_TINY, init_vit, vit_forward

__all__ = ["init_vit", "vit_forward", "VIT_B_16", "VIT_TINY"]
