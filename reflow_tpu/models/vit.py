"""Vision Transformer feature extractor, pure JAX (config 5's Map model).

A deliberately flat implementation: params are a plain pytree, the forward
is a jit-able pure function, so it embeds directly as a vectorized Map
function in a FlowGraph and shards data-parallel under ``shard_map`` (the
per-shard batch just flows through the same pure function). bfloat16
matmul inputs with float32 accumulation — the MXU-native regime.

Structure (standard pre-LN ViT): patchify -> linear proj + learned pos
embedding -> depth x [LN, MSA, residual, LN, MLP(gelu), residual] -> final
LN -> mean pool over patches. Feature dim = ``dim``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["init_vit", "vit_forward", "vit_forward_tp", "vit_param_specs",
           "vit_flops", "VIT_B_16", "VIT_TINY"]

#: ViT-B/16 (the reference workload's extractor)
VIT_B_16 = dict(img=224, chans=3, patch=16, dim=768, depth=12, heads=12,
                mlp_dim=3072)
#: tiny config for CI (CPU-mesh differential tests)
VIT_TINY = dict(img=16, chans=3, patch=8, dim=32, depth=2, heads=4,
                mlp_dim=64)


def init_vit(seed: int, *, img: int, chans: int, patch: int, dim: int,
             depth: int, heads: int, mlp_dim: int,
             dtype=jnp.float32) -> Dict:
    rng = np.random.default_rng(seed)
    n_patches = (img // patch) ** 2
    pdim = patch * patch * chans

    def dense(*shape):
        w = rng.normal(0, shape[0] ** -0.5, shape).astype(np.float32)
        return jnp.asarray(w, dtype)

    params = {
        "proj_w": dense(pdim, dim),
        "proj_b": jnp.zeros((dim,), dtype),
        "pos": jnp.asarray(
            rng.normal(0, 0.02, (n_patches, dim)).astype(np.float32), dtype),
        "ln_f": {"g": jnp.ones((dim,), dtype), "b": jnp.zeros((dim,), dtype)},
        "blocks": [],
    }
    for _ in range(depth):
        params["blocks"].append({
            "ln1": {"g": jnp.ones((dim,), dtype),
                    "b": jnp.zeros((dim,), dtype)},
            "ln2": {"g": jnp.ones((dim,), dtype),
                    "b": jnp.zeros((dim,), dtype)},
            "wq": dense(dim, dim), "wk": dense(dim, dim),
            "wv": dense(dim, dim), "wo": dense(dim, dim),
            "w1": dense(dim, mlp_dim),
            "b1": jnp.zeros((mlp_dim,), dtype),
            "w2": dense(mlp_dim, dim),
            "b2": jnp.zeros((dim,), dtype),
        })
    params["_cfg"] = dict(img=img, chans=chans, patch=patch, dim=dim,
                          depth=depth, heads=heads, mlp_dim=mlp_dim)
    return params


def _ln(x, p):
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.var(x, -1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-6) * p["g"] + p["b"]


def _dot(a, b):
    # bf16 inputs, f32 accumulation: the MXU-native matmul regime
    return jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)


def _attn(x, blk, heads):
    n, d = x.shape[-2], x.shape[-1]
    hd = d // heads

    def split(w):
        y = _dot(x, w)
        return y.reshape(*y.shape[:-1], heads, hd)

    q, k, v = split(blk["wq"]), split(blk["wk"]), split(blk["wv"])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    a = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("...hqk,...khd->...qhd", a, v,
                   preferred_element_type=jnp.float32)
    return _dot(o.reshape(*o.shape[:-2], d), blk["wo"])


def vit_flops(*, img: int, chans: int, patch: int, dim: int, depth: int,
              heads: int, mlp_dim: int) -> float:
    """Matmul FLOPs per image at the FMA=2 convention (the one chip peak
    numbers use, so achieved/peak is a true MFU). Patch projection + per
    block (QKVO projections, attention scores/apply, MLP); LN/gelu/pool
    vector work is negligible and excluded. ViT-B/16 @224: ~35 GFLOP
    (tables quoting ~17.6 'GFLOPs' count MACs)."""
    n = (img // patch) ** 2
    pdim = patch * patch * chans
    per_block = 8 * n * dim * dim + 4 * n * n * dim + 4 * n * dim * mlp_dim
    return float(2 * n * pdim * dim + depth * per_block)


def vit_forward(params: Dict, images: jax.Array) -> jax.Array:
    """images [B, H, W, C] (or [B, H*W*C] flat) -> features [B, dim]."""
    cfg = params["_cfg"]
    img, chans, patch = cfg["img"], cfg["chans"], cfg["patch"]
    b = images.shape[0]
    x = images.reshape(b, img, img, chans).astype(jnp.float32)
    g = img // patch
    # patchify: [B, g, p, g, p, C] -> [B, g*g, p*p*C]
    x = x.reshape(b, g, patch, g, patch, chans)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, patch * patch * chans)
    x = _dot(x, params["proj_w"]) + params["proj_b"] + params["pos"]
    for blk in params["blocks"]:
        x = x + _attn(_ln(x, blk["ln1"]), blk, cfg["heads"])
        h = _dot(_ln(x, blk["ln2"]), blk["w1"]) + blk["b1"]
        x = x + _dot(jax.nn.gelu(h), blk["w2"]) + blk["b2"]
    x = _ln(x, params["ln_f"])
    return jnp.mean(x, axis=-2)


# -- tensor-parallel forward (2-D delta x model mesh, VERDICT r4 #8) -------

def vit_param_specs(cfg: Dict, model_axis: str = "model"):
    """Per-leaf PartitionSpecs for Megatron-style tensor parallelism:
    QKV and MLP-in column-sharded (heads / hidden split over the model
    axis), attention-out and MLP-out row-sharded (their products
    ``psum`` over the model axis in :func:`vit_forward_tp`); LNs,
    biases of row-sharded layers, projection and positional tables
    replicate. Matches the ``init_vit`` pytree minus ``_cfg``."""
    from jax.sharding import PartitionSpec as P

    col_w = P(None, model_axis)      # [in, out/m]
    row_w = P(model_axis, None)      # [in/m, out]
    rep = P()
    block = {
        "ln1": {"g": rep, "b": rep}, "ln2": {"g": rep, "b": rep},
        "wq": col_w, "wk": col_w, "wv": col_w, "wo": row_w,
        "w1": col_w, "b1": P(model_axis), "w2": row_w, "b2": rep,
    }
    return {
        "proj_w": rep, "proj_b": rep, "pos": rep,
        "ln_f": {"g": rep, "b": rep},
        "blocks": [dict(block) for _ in range(cfg["depth"])],
    }


def _attn_tp(x, blk, heads_local, axis):
    n, d = x.shape[-2], x.shape[-1]
    dl = blk["wq"].shape[-1]                 # d/m local projection width
    hd = dl // heads_local

    def split(w):
        y = _dot(x, w)                       # [.., n, d/m]
        return y.reshape(*y.shape[:-1], heads_local, hd)

    q, k, v = split(blk["wq"]), split(blk["wk"]), split(blk["wv"])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    a = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("...hqk,...khd->...qhd", a, v,
                   preferred_element_type=jnp.float32)
    # row-sharded output projection: partial products sum over the mesh
    part = _dot(o.reshape(*o.shape[:-2], dl), blk["wo"])
    return jax.lax.psum(part, axis)


def vit_forward_tp(params: Dict, images: jax.Array,
                   axis: str = "model") -> jax.Array:
    """Per-shard tensor-parallel forward: ``params`` holds this model
    shard's leaves (``vit_param_specs`` layout — local head/hidden
    slices), activations are replicated over the model axis, and each
    block pays exactly two ``psum``s (attention-out, MLP-out) — the
    Megatron schedule. Call inside ``shard_map`` over a mesh carrying
    ``axis``; numerics match :func:`vit_forward` to f32 reduction-order
    noise."""
    cfg = params["_cfg"]
    img, chans, patch = cfg["img"], cfg["chans"], cfg["patch"]
    m = jax.lax.psum(1, axis)
    if cfg["heads"] % m or cfg["dim"] % m or cfg["mlp_dim"] % m:
        # silently-wrong attention otherwise: e.g. heads=12 over m=8
        # passes every SHAPE check (dim 768 % 8 == 0) but fuses 1.5 true
        # heads into each local one
        raise ValueError(
            f"model axis size {m} must divide heads={cfg['heads']}, "
            f"dim={cfg['dim']}, and mlp_dim={cfg['mlp_dim']}")
    heads_local = cfg["heads"] // m
    b = images.shape[0]
    x = images.reshape(b, img, img, chans).astype(jnp.float32)
    g = img // patch
    x = x.reshape(b, g, patch, g, patch, chans)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, patch * patch * chans)
    x = _dot(x, params["proj_w"]) + params["proj_b"] + params["pos"]
    for blk in params["blocks"]:
        x = x + _attn_tp(_ln(x, blk["ln1"]), blk, heads_local, axis)
        h = _dot(_ln(x, blk["ln2"]), blk["w1"]) + blk["b1"]
        x = x + jax.lax.psum(_dot(jax.nn.gelu(h), blk["w2"]), axis) \
            + blk["b2"]
    x = _ln(x, params["ln_f"])
    return jnp.mean(x, axis=-2)
