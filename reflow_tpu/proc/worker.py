"""Child-process role runners for the multi-process harness.

``tools/reflow_proc.py`` parses argv and hands a plain options dict to
one of :func:`run_leader` / :func:`run_replica` / :func:`run_producer`.
Each runner owns its role's whole in-process stack (the same classes
the single-process tests drive — nothing is forked *logic*, only
forked *processes*), speaks a line protocol with the parent, and
returns a status dict the CLI prints as its exit JSON:

- **stdout**: one JSON object per line. The first is the ready line
  (``{"event": "ready", "name", "pid", addresses...}``) — the parent
  learns the OS-assigned ports from it. The last is the exit status.
- **stdin**: JSON commands — ``{"cmd": "stop"}`` everywhere;
  ``{"cmd": "attach", "replicas": [[name, [host, port]], ...]}`` on a
  leader; ``{"cmd": "connect", "address": [host, port]}`` retargets a
  producer at a promoted leader. EOF on stdin counts as ``stop``: a
  child whose parent vanished drains and exits instead of leaking.

The replica's control surface (``status`` / ``reanchor`` /
``promote``) rides its existing :class:`ReplicaServer` wire protocol
(:class:`ControlledReplicaServer` below) rather than stdin, because
the failover coordinator in the *parent* drives those per-candidate
during an election — request/response over the same framed transport
the shipper already uses, so a promotion works even if the parent's
pipe buffers are wedged.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time
from typing import Dict, List, Optional

from reflow_tpu.net.client import RemoteFollower
from reflow_tpu.net.framing import TransportError
from reflow_tpu.net.server import ReplicaServer
from reflow_tpu.net.transport import TcpTransport
from reflow_tpu.obs import flight as _flight
from reflow_tpu.obs import trace as _trace
from reflow_tpu.obs.fleet import TelemetryShipper
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.utils.config import env_flag, env_str
from reflow_tpu.serve import (APPLIED, DEDUPED, IngestFrontend,
                              RemoteProducer, ReplicaScheduler,
                              RpcIngestServer)
from reflow_tpu.subs.hub import SubscriptionHub
from reflow_tpu.subs.wire import SubscriptionServer
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.wal.durable import DurableScheduler
from reflow_tpu.wal.ship import SegmentShipper
from reflow_tpu.workloads import wordcount

__all__ = ["ControlledReplicaServer", "run_leader", "run_replica",
           "run_producer", "producer_batch_words", "emit"]

#: producer batch shape: words per batch, vocabulary size — small
#: enough that dedup/coalescing paths all engage, deterministic so the
#: bench oracle can regenerate any batch from (producer, seq) alone
_BATCH_WORDS = 8
_BATCH_VOCAB = 50


def emit(obj: dict) -> None:
    """One protocol line on stdout (flushed — the parent blocks on
    it). Anything else the child prints must go to stderr."""
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _stdin_commands() -> "queue.Queue[Optional[dict]]":
    """Background reader: parsed JSON commands, ``None`` once on EOF.
    Non-JSON lines are ignored (a shell poking at the child is not a
    protocol error)."""
    q: "queue.Queue[Optional[dict]]" = queue.Queue()

    def read() -> None:
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                cmd = json.loads(line)
            except ValueError:
                continue
            if isinstance(cmd, dict):
                q.put(cmd)
        q.put(None)

    threading.Thread(target=read, name="proc-stdin", daemon=True).start()
    return q


def _graph(workload: str):
    if workload != "wordcount":
        raise ValueError(f"unknown workload {workload!r}")
    return wordcount.build_graph()


def producer_batch_words(index: int, seq: int) -> List[str]:
    """The batch a producer child submits for (producer ``index``,
    ``seq``) — a pure function, shared with the bench oracle so acked
    ``batch_id``s alone reconstruct the exact submitted content."""
    base = (index + 1) * 100003 + seq * 9176
    return [f"w{(base + i * 31) % _BATCH_VOCAB}"
            for i in range(_BATCH_WORDS)]


def _obs_install(opts: dict, name: str):
    """Per-child observability: when ``REFLOW_FLIGHT`` is set, install
    the flight recorder in this node's disk corner
    (``REFLOW_FLIGHT_DIR`` or ``<root>/flight``) — the bounded on-disk
    recording a kill -9 leaves behind for ``tools/reflow_flight.py``."""
    if not env_flag("REFLOW_FLIGHT"):
        return None
    directory = env_str("REFLOW_FLIGHT_DIR")
    if not directory:
        root = opts.get("root")
        directory = os.path.join(root, "flight") if root else "flight"
    rec = _flight.install(directory, node=name)
    rec.publish_metrics(REGISTRY)
    return rec


def _obs_exit(opts: dict) -> None:
    """Clean-exit observability: flush the flight ring and export this
    child's trace rings to ``<root>/trace.json`` so the parent can
    merge per-process traces post-run. Killed children never get here
    — their evidence is the flight recording."""
    _flight.flush_now()
    if _trace.ENABLED and opts.get("root"):
        try:
            from reflow_tpu.obs.export import export_chrome_trace
            export_chrome_trace(
                os.path.join(opts["root"], "trace.json"))
        except OSError:
            pass


def _telemetry(opts: dict, name: str) -> Optional[TelemetryShipper]:
    addr = opts.get("telemetry")
    if not addr:
        return None
    shipper = TelemetryShipper(REGISTRY, TcpTransport(), tuple(addr),
                               node=name)
    shipper.start()
    return shipper


# -- replica -----------------------------------------------------------


class ControlledReplicaServer(ReplicaServer):
    """A replica child's endpoint: the shipping protocol plus the
    parent-driven control ops an election needs::

        ("status",)                    -> ("ok", {..ping.., promoted,
                                                  ingest})
        ("reanchor", epoch)            -> ("ok", cursor)
        ("promote", epoch, attach,
                    durable_kw)        -> ("ok", {ingest, epoch})

    ``promote`` runs the full in-child promotion: the replica opens
    its mirror as its own WAL (``ReplicaScheduler.promote``), a fresh
    ``IngestFrontend`` + ``RpcIngestServer`` start serving producers,
    and a new ``SegmentShipper`` attaches the surviving replicas
    (``attach`` = ``[[name, [host, port]], ...]``; an unreachable
    survivor is skipped and counted, not fatal — it reanchors and
    resubscribes when it comes back).
    """

    def __init__(self, node: "ReplicaNode", transport) -> None:
        super().__init__(node.rep, transport)
        self.node = node

    def _dispatch(self, msg):
        if isinstance(msg, tuple) and msg:
            op, args = msg[0], msg[1:]
            if op == "status":
                return ("ok", self.node.status())
            if op == "reanchor":
                return ("ok", tuple(self.node.rep.reanchor(args[0])))
            if op == "promote":
                epoch, attach = args[0], args[1]
                kw = args[2] if len(args) > 2 and args[2] else {}
                return ("ok", self.node.promote(epoch, attach, kw))
        return super()._dispatch(msg)


class ReplicaNode:
    """Everything one replica process runs; promotable in place."""

    def __init__(self, name: str, root: str, *, host: str = "127.0.0.1",
                 workload: str = "wordcount") -> None:
        self.name = name
        self.host = host
        self.graph, self.src, self.sink = _graph(workload)
        self.rep = ReplicaScheduler(self.graph, root, name=name)
        self.server = ControlledReplicaServer(self, TcpTransport(host))
        #: standing-query fan-out: every replica child serves
        #: subscriptions beside the shipping endpoint
        self.hub = SubscriptionHub(self.rep, name=name, start=False)
        self.subs_server = SubscriptionServer(self.hub,
                                              TcpTransport(host))
        # cached at start: status() must keep answering on the exit
        # path, after the listener (and its getsockname) is gone
        self.subs_address: Optional[tuple] = None
        self.frontend: Optional[IngestFrontend] = None
        self.ingest: Optional[RpcIngestServer] = None
        self.ingest_address: Optional[tuple] = None
        self.shipper: Optional[SegmentShipper] = None
        self.attach_skipped = 0
        self._lock = named_lock(f"proc.node.{name}")

    def start(self) -> "ReplicaNode":
        self.rep.publish_metrics(REGISTRY)
        self.rep.attach_hub(self.hub)
        self.hub.start()
        self.hub.publish_metrics(REGISTRY)
        self.server.start()
        self.subs_server.start()
        self.subs_address = tuple(self.subs_server.address)
        return self

    def status(self) -> dict:
        r = self.rep
        return {
            "name": self.name,
            "horizon": r.published_horizon(),
            "epoch": r.epoch,
            "lag_ticks": r.lag_ticks(),
            "promoted": r.promoted,
            "ingest": (list(self.ingest_address)
                       if self.ingest_address is not None else None),
            "subs": (list(self.subs_address)
                     if self.subs_address is not None else None),
            "subs_active": self.hub.active_subs(),
        }

    def promote(self, epoch: int, attach, durable_kw: dict) -> dict:
        with self._lock:
            sched = self.rep.promote(epoch=epoch, **durable_kw)
            if self.frontend is None:
                self.frontend = IngestFrontend(sched, name=self.name)
                self.frontend.publish_metrics(REGISTRY)
                self.ingest = RpcIngestServer(
                    self.frontend, TcpTransport(self.host)).start()
                self.ingest_address = tuple(self.ingest.address)
                self.shipper = SegmentShipper(
                    sched.wal, ckpt_dir=self.rep.ckpt_dir,
                    leader_tick=lambda: sched._tick)
                self.shipper.publish_metrics(REGISTRY)
            for nm, addr in (attach or ()):
                try:
                    self.shipper.detach(nm)
                    self.shipper.attach(RemoteFollower(
                        TcpTransport(), tuple(addr), name=nm))
                except TransportError:
                    # survivor unreachable right now: it rejoins by
                    # reanchoring when respawned; never block promotion
                    self.attach_skipped += 1
            self.shipper.start()
            return {"ingest": list(self.ingest_address), "epoch": epoch}

    def close(self) -> None:
        if self.frontend is not None:
            self.frontend.close()
        if self.shipper is not None:
            self.shipper.stop()
        if self.ingest is not None:
            self.ingest.close()
        self.subs_server.close()
        self.hub.close()
        self.server.close()


def run_replica(opts: dict) -> dict:
    node = ReplicaNode(opts["name"], opts["root"],
                       host=opts.get("host", "127.0.0.1"),
                       workload=opts.get("workload", "wordcount"))
    node.start()
    _obs_install(opts, opts["name"])
    telemetry = _telemetry(opts, opts["name"])
    emit({"event": "ready", "role": "replica", "name": node.name,
          "pid": os.getpid(), "addr": list(node.server.address),
          "subs": list(node.subs_address)})
    cmds = _stdin_commands()
    try:
        while True:
            cmd = cmds.get()
            if cmd is None or cmd.get("cmd") == "stop":
                break
    finally:
        if telemetry is not None:
            telemetry.stop()
        node.close()
        _obs_exit(opts)
    st = node.status()
    st.update({"event": "exit", "role": "replica", "ok": True})
    return st


# -- leader ------------------------------------------------------------


def run_leader(opts: dict) -> dict:
    name = opts["name"]
    root = opts["root"]
    wal_dir = os.path.join(root, "wal")
    ckpt_dir = os.path.join(root, "ckpt")
    os.makedirs(wal_dir, exist_ok=True)
    os.makedirs(ckpt_dir, exist_ok=True)
    host = opts.get("host", "127.0.0.1")
    g, src, sink = _graph(opts.get("workload", "wordcount"))
    sched = DurableScheduler(g, wal_dir=wal_dir,
                             fsync=opts.get("fsync", "tick"),
                             epoch=int(opts.get("epoch", 0)))
    fe = IngestFrontend(sched, name=name)
    fe.publish_metrics(REGISTRY)
    ingest = RpcIngestServer(fe, TcpTransport(host)).start()
    shipper = SegmentShipper(sched.wal, ckpt_dir=ckpt_dir,
                             leader_tick=lambda: sched._tick)
    shipper.publish_metrics(REGISTRY)
    shipper.start()
    _obs_install(opts, name)
    telemetry = _telemetry(opts, name)
    emit({"event": "ready", "role": "leader", "name": name,
          "pid": os.getpid(), "ingest": list(ingest.address),
          "wal_dir": wal_dir, "ckpt_dir": ckpt_dir})
    cmds = _stdin_commands()
    attached: List[str] = []
    try:
        while True:
            cmd = cmds.get()
            if cmd is None or cmd.get("cmd") == "stop":
                break
            if cmd.get("cmd") == "attach":
                for nm, addr in cmd.get("replicas", ()):
                    # re-attach semantics: a respawned replica keeps
                    # its name but gets a fresh port — drop the stale
                    # link before the new subscribe handshake
                    shipper.detach(nm)
                    shipper.attach(RemoteFollower(
                        TcpTransport(), tuple(addr), name=nm))
                    attached.append(nm)
                emit({"event": "attached", "replicas": attached})
    finally:
        try:
            fe.close()
        except Exception:  # noqa: BLE001 - a crashed pump still exits
            pass
        shipper.stop()
        ingest.close()
        if telemetry is not None:
            telemetry.stop()
        _obs_exit(opts)
    wal = sched.wal
    return {"event": "exit", "role": "leader", "name": name, "ok": True,
            "tick": sched._tick, "lsn": wal.last_lsn(),
            "attached": attached}


# -- producer ----------------------------------------------------------


def run_producer(opts: dict) -> dict:
    """Submit deterministic batches until told to stop; resubmit until
    acked. The exit JSON carries every acked ``(seq, status)`` so the
    harness oracle can refold exactly what was acknowledged."""
    name = opts["name"]
    index = int(opts.get("index", 0))
    pace_s = float(opts.get("pace_s", 0.0) or 0.0)
    src_name = opts.get("source", "words")
    prod = RemoteProducer(TcpTransport(), tuple(opts["connect"]),
                          name=name)
    _obs_install(opts, name)
    telemetry = _telemetry(opts, name)
    emit({"event": "ready", "role": "producer", "name": name,
          "pid": os.getpid(), "connect": list(opts["connect"])})
    cmds = _stdin_commands()
    acked: List[List] = []          # [seq, status]
    stop = False
    drain_deadline: Optional[float] = None
    seq = 0

    def poll_cmds() -> None:
        nonlocal stop, drain_deadline
        while True:
            try:
                cmd = cmds.get_nowait()
            except queue.Empty:
                return
            if cmd is None or cmd.get("cmd") == "stop":
                if not stop:
                    stop = True
                    # stop means "finish the in-flight batch, then
                    # exit": abandoning an admitted batch would leave
                    # a fold no ack accounts for. Bounded — a dead
                    # leader can't wedge the exit.
                    drain_deadline = time.monotonic() + float(
                        cmd.get("drain_s", 10.0) if cmd else 10.0)
            elif cmd.get("cmd") == "connect":
                prod.retarget(tuple(cmd["address"]))

    try:
        while True:
            poll_cmds()
            if stop:
                break
            bid = f"{name}-{seq}"
            batch = wordcount.ingest_lines(
                [" ".join(producer_batch_words(index, seq))])
            ticket = prod.submit(src_name, batch, batch_id=bid)
            while True:
                poll_cmds()
                if stop and time.monotonic() >= drain_deadline:
                    break  # give up: the id stays in in_doubt below
                try:
                    res = ticket.result(timeout=0.3)
                except TimeoutError:
                    continue  # link down / mid-failover: keep driving
                if res.status in (APPLIED, DEDUPED):
                    acked.append([seq, res.status])
                    seq += 1
                    if pace_s > 0 and not stop:
                        # pacing keeps a many-process fleet from
                        # starving a recovering child on a small box
                        time.sleep(pace_s)
                    break
                # REJECTED (backpressure) or SHED: the contract says
                # re-send; same id keeps the fold exactly-once
                time.sleep(0.01)
                ticket = prod.submit(src_name, batch, batch_id=bid)
    finally:
        if telemetry is not None:
            telemetry.stop()
        prod.close()
        _obs_exit(opts)
    return {"event": "exit", "role": "producer", "name": name,
            "ok": True, "index": index, "acked": acked,
            "submits": prod.submits_total,
            "resubmits": prod.resubmits_total,
            "reconnects": prod.reconnects_total,
            "deduped": prod.deduped_total,
            "in_doubt": list(prod.in_doubt_ids())}
