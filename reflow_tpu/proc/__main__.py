"""``python -m reflow_tpu.proc`` — run one harness child role.

The process harness (``proc/harness.py``) spawns every child as this
module, so a "replica process" in a test is *exactly* what an operator
would start by hand::

    python -m reflow_tpu.proc --role replica --name r0 --root /data/r0
    python -m reflow_tpu.proc --role leader  --name leader --root /data/L
    python -m reflow_tpu.proc --role producer --name p0 --index 0 \\
        --connect 127.0.0.1:45123

Protocol: JSON lines on stdout (first = ready line with the
OS-assigned addresses, last = exit status when ``--json``), JSON
commands on stdin (``{"cmd": "stop"}`` / ``attach`` / ``connect`` —
see ``proc/worker.py``). ``tools/reflow_proc.py`` wraps this module
for checkout-relative invocation.
"""

from __future__ import annotations

import argparse
import sys


def _addr(text: str):
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1", int(port))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m reflow_tpu.proc",
        description="one multi-process deployment role "
                    "(docs/guide.md 'Multi-process deployment')")
    ap.add_argument("--role", required=True,
                    choices=("leader", "replica", "producer"))
    ap.add_argument("--name", required=True,
                    help="node name (fleet telemetry id, replica name, "
                         "producer batch-id prefix)")
    ap.add_argument("--root", default=None,
                    help="this node's state directory (WAL/mirror/ckpt; "
                         "leader and replica only)")
    ap.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="ingest endpoint to submit to (producer only)")
    ap.add_argument("--telemetry", default=None, metavar="HOST:PORT",
                    help="TelemetryServer to ship fleet snapshots to")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address for this node's listeners "
                         "(port 0: the OS assigns, the ready line "
                         "reports)")
    ap.add_argument("--workload", default="wordcount")
    ap.add_argument("--source", default=None,
                    help="source node to submit to (producer; default "
                         "the workload's)")
    ap.add_argument("--index", type=int, default=0,
                    help="producer index: seeds the deterministic "
                         "batch stream")
    ap.add_argument("--pace", type=float, default=0.0,
                    help="producer inter-batch sleep (s); paces a "
                         "many-process fleet on a small host")
    ap.add_argument("--fsync", default="tick",
                    help="leader WAL fsync policy (tick/record/...)")
    ap.add_argument("--epoch", type=int, default=0,
                    help="starting epoch (a promoted-elsewhere fleet "
                         "restarts above the fenced one)")
    ap.add_argument("--json", action="store_true",
                    help="print the exit-status JSON on clean shutdown")
    args = ap.parse_args(argv)

    if args.role in ("leader", "replica") and not args.root:
        ap.error(f"--role {args.role} requires --root")
    if args.role == "producer" and not args.connect:
        ap.error("--role producer requires --connect")

    from reflow_tpu.proc import worker

    opts = {
        "name": args.name, "root": args.root, "host": args.host,
        "workload": args.workload, "index": args.index,
        "pace_s": args.pace,
        "fsync": args.fsync, "epoch": args.epoch,
        "telemetry": _addr(args.telemetry) if args.telemetry else None,
        "connect": _addr(args.connect) if args.connect else None,
    }
    if args.source:
        opts["source"] = args.source
    run = {"leader": worker.run_leader, "replica": worker.run_replica,
           "producer": worker.run_producer}[args.role]
    status = run(opts)
    if args.json:
        worker.emit(status)
    return 0 if status.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
