"""Source ownership and the cross-process tick-horizon barrier.

Two small pieces of shared vocabulary for the process harness:

- :class:`OwnershipMap` pins every graph source to an owning node and
  gives each node its own on-disk corner under one root — WAL, mirror
  and checkpoint directories that survive a ``kill -9`` and are found
  again by a respawn of the *same* node name. Ownership is what makes
  a "local mirrored WAL keyed by source ownership" well-defined: the
  batch ids a producer mints are scoped by its source, the source is
  scoped by its owner, so two nodes never contend for one id space.
- :func:`horizon_barrier` is the consistent-cut gate: given a horizon
  probe per node (a ``ping`` over the wire, usually), it waits until
  every node's applied horizon reaches a common target tick. A
  restarted process calls this to *rejoin* — its recovery replay is
  only complete once it stands at the same cut as the peers that never
  died, and parity checks across processes are only meaningful at such
  a cut.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

__all__ = ["OwnershipMap", "horizon_barrier", "BarrierTimeout"]


class BarrierTimeout(TimeoutError):
    """The fleet never converged on a common horizon: ``.horizons``
    holds the last observed per-node values (None = unreachable)."""

    def __init__(self, msg: str, horizons: Dict[str, Optional[int]]):
        super().__init__(msg)
        self.horizons = dict(horizons)


class OwnershipMap:
    """Deterministic source→node assignment + per-node disk layout.

    ``nodes`` are the owning process names (replicas, or the leader for
    an unreplicated source); ``sources`` the graph's source/loop node
    names. Assignment is round-robin in the given order — pure data, so
    a harness parent and a respawned child derive the identical map
    from the identical spec (see :meth:`spec` / :meth:`from_spec`).
    """

    def __init__(self, root: str, nodes: List[str],
                 sources: List[str] = ()) -> None:
        if not nodes:
            raise ValueError("OwnershipMap needs at least one node")
        self.root = root
        self.nodes = list(nodes)
        self.sources = list(sources)
        self._owner = {s: self.nodes[i % len(self.nodes)]
                       for i, s in enumerate(self.sources)}

    def owner(self, source: str) -> str:
        return self._owner[source]

    def sources_of(self, node: str) -> List[str]:
        return [s for s, n in self._owner.items() if n == node]

    # -- disk layout ---------------------------------------------------

    def node_dir(self, node: str) -> str:
        d = os.path.join(self.root, node)
        os.makedirs(d, exist_ok=True)
        return d

    def wal_dir(self, node: str) -> str:
        d = os.path.join(self.node_dir(node), "wal")
        os.makedirs(d, exist_ok=True)
        return d

    def mirror_dir(self, node: str) -> str:
        # a ReplicaScheduler takes the node dir and lays out wal/ +
        # ckpt/ itself; this names where its mirror lands
        return os.path.join(self.node_dir(node), "wal")

    def ckpt_dir(self, node: str) -> str:
        d = os.path.join(self.node_dir(node), "ckpt")
        os.makedirs(d, exist_ok=True)
        return d

    # -- shipping across the process boundary --------------------------

    def spec(self) -> dict:
        return {"root": self.root, "nodes": list(self.nodes),
                "sources": list(self.sources)}

    @classmethod
    def from_spec(cls, d: dict) -> "OwnershipMap":
        return cls(d["root"], d["nodes"], d.get("sources", ()))


def horizon_barrier(probes: Dict[str, Callable[[], Optional[int]]], *,
                    min_horizon: Optional[int] = None,
                    timeout_s: float = 10.0,
                    poll_s: float = 0.05) -> Dict[str, int]:
    """Wait until every probed node's applied horizon reaches a common
    cut; returns the per-node horizons observed at the moment the
    barrier opened.

    ``probes`` maps node name to a callable returning its current
    horizon, or ``None`` while the node is unreachable (mid-restart —
    that is precisely the window the barrier exists to wait out). The
    target cut is ``min_horizon`` when given; otherwise the highest
    horizon seen on the first full pass — "everyone catches up to the
    most advanced survivor", which is the rejoin contract after a
    ``kill -9``: the respawned node replays its mirror and re-ships
    the tail until it stands where the fleet stands.

    Raises :class:`BarrierTimeout` (with the last observations) if the
    fleet does not converge in ``timeout_s``.
    """
    deadline = time.monotonic() + timeout_s
    target = min_horizon
    last: Dict[str, Optional[int]] = {n: None for n in probes}
    while True:
        horizons: Dict[str, Optional[int]] = {}
        for node, probe in probes.items():
            try:
                horizons[node] = probe()
            except Exception:  # noqa: BLE001 - unreachable == not yet
                horizons[node] = None
        last = horizons
        seen = [h for h in horizons.values() if h is not None]
        if target is None and len(seen) == len(probes):
            target = max(seen) if seen else 0
        if (target is not None and len(seen) == len(probes)
                and all(h >= target for h in seen)):
            return {n: int(h) for n, h in horizons.items()}
        if time.monotonic() >= deadline:
            raise BarrierTimeout(
                f"horizon barrier (target {target}) still open after "
                f"{timeout_s}s: {horizons}", horizons)
        time.sleep(poll_s)
