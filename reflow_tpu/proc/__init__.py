"""Multi-process deployment: real OS processes for every role.

The single-process simulation becomes a deployable system here: the
ingestion RPC (``serve/rpc.py``) lets producers live off-process, and
this package supplies the other half — replica/leader/producer
*processes* (``python -m reflow_tpu.proc``), a harness that spawns and
kill -9s them, source-ownership + per-node disk layout, and the
cross-process tick-horizon barrier a restarted process rejoins
through. See docs/guide.md "Multi-process deployment".
"""

from .harness import (ChildProc, ControlClient, ProcHarness,
                      RemoteReplicaProxy)
from .ownership import BarrierTimeout, OwnershipMap, horizon_barrier

__all__ = [
    "BarrierTimeout", "ChildProc", "ControlClient", "OwnershipMap",
    "ProcHarness", "RemoteReplicaProxy", "horizon_barrier",
]
