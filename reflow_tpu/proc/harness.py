"""The multi-process harness: spawn, kill -9, respawn, promote.

``ProcHarness`` is the parent-side control plane for a fleet of real
OS processes (``python -m reflow_tpu.proc`` children — see
``proc/worker.py`` for what each role runs):

- **Spawn**: children bind port 0 and report their OS-assigned
  addresses on a JSON ready line; the parent never pre-picks ports, so
  any number of fleets run in parallel. The parent hosts the fleet's
  :class:`~reflow_tpu.obs.wire.TelemetryServer`; every child ships
  registry snapshots to it, so ``fleet_snapshot()`` shows the whole
  multi-process topology from one place.
- **Chaos**: :meth:`kill9` is a real ``SIGKILL`` — no atexit, no
  flush, the process is simply gone, which is the only honest way to
  test the durability story. :meth:`respawn` restarts the same node
  name over the same state directory; the child recovers from its
  local mirrored WAL and the caller uses :func:`~reflow_tpu.proc
  .ownership.horizon_barrier` to wait for it to rejoin at a
  consistent cut. Both are crash seams (``proc_kill9@<node>`` /
  ``proc_respawn@<node>`` / ``proc_spawn@<node>``) so recovery tests
  can cut the *harness* mid-operation too.
- **Failover**: :meth:`coordinator` wires a stock
  :class:`~reflow_tpu.serve.failover.FailoverCoordinator` across the
  process boundary — candidates are :class:`RemoteReplicaProxy`
  objects speaking the replica children's control protocol, the final
  drain runs off a *cold-log* :class:`~reflow_tpu.wal.ship
  .SegmentShipper` over the dead leader's on-disk WAL (synced bytes
  are plain file bytes; the leader being kill -9'd does not make its
  disk unreadable), and the promotion itself executes inside the
  winning replica *process*, which starts serving ingestion on a
  fresh ``RpcIngestServer``. Producers are then retargeted and their
  in-doubt resubmissions stay exactly-once against the recovered
  dedup mirror.

Every blocking child interaction is deadline-bounded
(``REFLOW_PROC_READY_TIMEOUT_S`` / ``REFLOW_PROC_REAP_TIMEOUT_S``): a
hung child is killed and reported, never waited on forever — the CI
suite must survive the worst child, that being the point of the
exercise.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import reflow_tpu
from reflow_tpu.net.client import RemoteFollower
from reflow_tpu.net.framing import TransportError
from reflow_tpu.net.transport import TcpTransport
from reflow_tpu.obs.fleet import FleetAggregator
from reflow_tpu.obs.wire import TelemetryServer
from reflow_tpu.proc.ownership import horizon_barrier
from reflow_tpu.serve.failover import FailoverCoordinator
from reflow_tpu.utils.config import env_float, env_str
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.wal.ship import SegmentShipper

__all__ = ["ChildProc", "ControlClient", "RemoteReplicaProxy",
           "ProcHarness"]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(reflow_tpu.__file__)))


class ChildProc:
    """One spawned role process: pipes, ready line, reaping.

    A reader thread turns the child's stdout JSON lines into
    :attr:`ready` / :attr:`exit_status` / :attr:`events`; stderr
    passes through (child tracebacks must land somewhere a human
    looks). ``kill9()`` is SIGKILL; ``stop()`` asks politely first and
    escalates on the reap deadline.
    """

    def __init__(self, name: str, role: str, argv: List[str],
                 env: Optional[dict] = None,
                 cwd: str = _REPO_ROOT) -> None:
        self.name = name
        self.role = role
        self.argv = list(argv)
        self.env = dict(env) if env is not None else None
        self.cwd = cwd
        self.proc: Optional[subprocess.Popen] = None
        self.ready: Optional[dict] = None
        self.exit_status: Optional[dict] = None
        self.events: List[dict] = []
        self._ready_evt = threading.Event()
        self._lock = named_lock(f"proc.child.{name}")

    def start(self) -> "ChildProc":
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        env["PYTHONPATH"] = self.cwd + os.pathsep + \
            env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            self.argv, cwd=self.cwd, env=env, text=True,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=None, bufsize=1)
        threading.Thread(target=self._read_stdout,
                         name=f"proc-out/{self.name}",
                         daemon=True).start()
        return self

    def _read_stdout(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue  # library noise on stdout is not protocol
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            with self._lock:
                self.events.append(obj)
                if obj.get("event") == "ready":
                    self.ready = obj
                    self._ready_evt.set()
                elif obj.get("event") == "exit":
                    self.exit_status = obj

    def wait_ready(self, timeout_s: Optional[float] = None) -> dict:
        timeout_s = (env_float("REFLOW_PROC_READY_TIMEOUT_S")
                     if timeout_s is None else timeout_s)
        if not self._ready_evt.wait(timeout_s):
            rc = self.proc.poll() if self.proc is not None else None
            self.kill9()
            raise TimeoutError(
                f"child {self.name} ({self.role}) not ready after "
                f"{timeout_s}s (rc={rc})")
        return self.ready

    def await_event(self, event: str,
                    timeout_s: float = 10.0) -> Optional[dict]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                for obj in self.events:
                    if obj.get("event") == event:
                        return obj
            if not self.alive:
                return None
            time.sleep(0.02)
        return None

    def send(self, obj: dict) -> bool:
        p = self.proc
        if p is None or p.poll() is not None or p.stdin is None:
            return False
        try:
            p.stdin.write(json.dumps(obj) + "\n")
            p.stdin.flush()
            return True
        except (OSError, ValueError):
            return False

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def kill9(self) -> None:
        """SIGKILL — the process gets no chance to flush anything."""
        p = self.proc
        if p is not None and p.poll() is None:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass
        self.reap(5.0)

    def reap(self, timeout_s: Optional[float] = None) -> Optional[int]:
        """Wait for exit with a deadline; escalate to SIGKILL on it.
        Always bounded — a hung child cannot wedge the caller."""
        timeout_s = (env_float("REFLOW_PROC_REAP_TIMEOUT_S")
                     if timeout_s is None else timeout_s)
        p = self.proc
        if p is None:
            return None
        try:
            return p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass
            try:
                return p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                return None

    def stop(self, timeout_s: Optional[float] = None) -> Optional[dict]:
        """Graceful stop: send the command, reap on a deadline, return
        the child's exit-status JSON (None if it never printed one —
        e.g. it had to be killed)."""
        self.send({"cmd": "stop"})
        if self.proc is not None and self.proc.stdin is not None:
            try:
                self.proc.stdin.close()  # EOF doubles as stop
            except OSError:
                pass
        self.reap(timeout_s)
        return self.exit_status


class ControlClient:
    """Dial-per-call client for a replica child's control endpoint
    (:class:`~reflow_tpu.proc.worker.ControlledReplicaServer`). No
    connection state survives between calls, so a child restart (new
    port, new process) needs nothing but the refreshed address."""

    def __init__(self, address, *, host: str = "127.0.0.1",
                 io_timeout_s: Optional[float] = None) -> None:
        self.address = tuple(address)
        self.transport = TcpTransport(host)
        self.io_timeout_s = (io_timeout_s if io_timeout_s is not None
                             else env_float("REFLOW_RPC_IO_TIMEOUT_S"))

    def call(self, *msg):
        """One request-response; raises TransportError on any link or
        protocol failure."""
        conn = self.transport.connect(self.address)
        try:
            conn.send_msg(tuple(msg), self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        finally:
            conn.close()
        if not (isinstance(resp, tuple) and resp
                and resp[0] in ("ok", "ack", "nack")):
            raise TransportError(f"control {msg[0]!r} failed: {resp!r}")
        return resp

    def try_call(self, *msg):
        try:
            return self.call(*msg)
        except TransportError:
            return None

    def status(self) -> Optional[dict]:
        resp = self.try_call("status")
        return resp[1] if resp is not None else None

    def horizon(self) -> Optional[int]:
        st = self.status()
        return int(st["horizon"]) if st is not None else None


class RemoteReplicaProxy:
    """A replica *process* as a failover candidate.

    Duck-types what :class:`FailoverCoordinator` and
    :class:`HighestHorizonElection` read — ``name``,
    ``published_horizon()``, ``promoted``, ``epoch``, ``reanchor()``,
    ``promote()`` — over the child's control protocol. An unreachable
    candidate reports horizon ``-1`` (it loses any election against a
    live peer rather than raising mid-promotion).

    ``promote()`` runs the whole cross-process step 5: survivors are
    re-anchored to the new epoch first, then the winner child promotes
    in place and attaches them to its fresh shipper. The returned
    leader object carries the child's new ingest address and — by
    design — no ``.wal``, so the coordinator's in-process re-shipping
    block stays idle (the child already did it where the WAL lives).
    """

    def __init__(self, harness: "ProcHarness", name: str) -> None:
        self.harness = harness
        self.name = name

    def _control(self) -> ControlClient:
        return self.harness.control(self.name)

    def published_horizon(self) -> int:
        h = self._control().horizon()
        return -1 if h is None else h

    def lag_ticks(self) -> int:
        st = self._control().status()
        return int(st["lag_ticks"]) if st else 0

    @property
    def promoted(self) -> bool:
        st = self._control().status()
        return bool(st and st["promoted"])

    @property
    def epoch(self) -> int:
        st = self._control().status()
        return int(st["epoch"]) if st else 0

    def reanchor(self, epoch: int):
        resp = self._control().try_call("reanchor", epoch)
        return tuple(resp[1]) if resp is not None else None

    def promote(self, *, epoch: int, **durable_kw):
        h = self.harness
        survivors = [(nm, list(h.replica_address(nm)))
                     for nm in h.replica_names()
                     if nm != self.name and h.child(nm).alive]
        for nm, _addr in survivors:
            h.control(nm).try_call("reanchor", epoch)
        resp = self._control().call("promote", epoch, survivors,
                                    dict(durable_kw))
        info = resp[1]
        return h._promoted(self.name, tuple(info["ingest"]), epoch)


class PromotedLeader:
    """What a cross-process promotion returns: where the new leader
    serves ingestion. Deliberately ``.wal``-less (see
    :meth:`RemoteReplicaProxy.promote`)."""

    def __init__(self, name: str, ingest, epoch: int) -> None:
        self.name = name
        self.ingest = tuple(ingest)
        self.epoch = epoch


class ProcHarness:
    """Spawn and torment a leader + replicas + producers fleet."""

    def __init__(self, root: str, *, host: str = "127.0.0.1",
                 crash=None, fleet: bool = True,
                 child_env: Optional[dict] = None,
                 python: Optional[str] = None,
                 workload: str = "wordcount") -> None:
        self.root = root
        self.host = host
        self.workload = workload
        self._crash = crash
        self._python = (python or env_str("REFLOW_PROC_PYTHON")
                        or sys.executable)
        self._child_env = dict(child_env or {})
        self.children: Dict[str, ChildProc] = {}
        self._specs: Dict[str, dict] = {}
        self.leader_name: Optional[str] = None
        self.ingest_address: Optional[Tuple[str, int]] = None
        self.kills = 0
        self.respawns = 0
        self.aggregator: Optional[FleetAggregator] = None
        self.telemetry: Optional[TelemetryServer] = None
        if fleet:
            self.aggregator = FleetAggregator()
            self.telemetry = TelemetryServer(
                self.aggregator, TcpTransport(host), node="harness")
            self.telemetry.start()

    # -- seams ---------------------------------------------------------

    def _chaos_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- spawning ------------------------------------------------------

    def _argv(self, spec: dict) -> List[str]:
        argv = [self._python, "-m", "reflow_tpu.proc",
                "--role", spec["role"], "--name", spec["name"],
                "--host", self.host, "--workload", self.workload,
                "--json"]
        if spec.get("root"):
            argv += ["--root", spec["root"]]
        if spec.get("connect"):
            host, port = spec["connect"]
            argv += ["--connect", f"{host}:{port}"]
        if self.telemetry is not None:
            host, port = self.telemetry.address
            argv += ["--telemetry", f"{host}:{port}"]
        if "index" in spec:
            argv += ["--index", str(spec["index"])]
        if spec.get("pace"):
            argv += ["--pace", str(spec["pace"])]
        if spec.get("fsync"):
            argv += ["--fsync", spec["fsync"]]
        if spec.get("epoch"):
            argv += ["--epoch", str(spec["epoch"])]
        return argv

    def _spawn(self, spec: dict) -> dict:
        name = spec["name"]
        self._chaos_point(f"proc_spawn@{name}")
        child = ChildProc(name, spec["role"], self._argv(spec),
                          env=self._child_env)
        self.children[name] = child
        self._specs[name] = dict(spec)
        child.start()
        ready = child.wait_ready()
        if spec["role"] == "leader":
            self.leader_name = name
            self.ingest_address = tuple(ready["ingest"])
        return ready

    def spawn_leader(self, name: str = "leader", *,
                     fsync: str = "tick", epoch: int = 0) -> dict:
        return self._spawn({
            "role": "leader", "name": name, "fsync": fsync,
            "epoch": epoch,
            "root": os.path.join(self.root, name)})

    def spawn_replica(self, name: str) -> dict:
        return self._spawn({
            "role": "replica", "name": name,
            "root": os.path.join(self.root, name)})

    def spawn_producer(self, name: str, *, index: int = 0,
                       connect: Optional[Tuple[str, int]] = None,
                       pace_s: float = 0.0) -> dict:
        if connect is None:
            connect = self.ingest_address
        if connect is None:
            raise RuntimeError("no leader to connect the producer to")
        return self._spawn({
            "role": "producer", "name": name, "index": index,
            "connect": tuple(connect), "pace": pace_s,
            # producers get a disk corner too: the flight recorder and
            # exit-time trace export land there, same as server roles
            "root": os.path.join(self.root, name)})

    # -- topology ------------------------------------------------------

    def child(self, name: str) -> ChildProc:
        return self.children[name]

    def replica_names(self) -> List[str]:
        return [n for n, s in self._specs.items()
                if s["role"] == "replica"]

    def producer_names(self) -> List[str]:
        return [n for n, s in self._specs.items()
                if s["role"] == "producer"]

    def replica_address(self, name: str) -> Tuple[str, int]:
        return tuple(self.children[name].ready["addr"])

    def control(self, name: str) -> ControlClient:
        return ControlClient(self.replica_address(name), host=self.host)

    def leader_wal_dir(self) -> str:
        return self.children[self.leader_name].ready["wal_dir"]

    def leader_ckpt_dir(self) -> str:
        return self.children[self.leader_name].ready["ckpt_dir"]

    def attach_replicas(self, names: Optional[List[str]] = None,
                        timeout_s: float = 10.0) -> None:
        """Tell the leader child to attach (or re-attach) replicas to
        its shipper."""
        names = self.replica_names() if names is None else names
        leader = self.children[self.leader_name]
        leader.send({"cmd": "attach",
                     "replicas": [[nm, list(self.replica_address(nm))]
                                  for nm in names]})
        leader.await_event("attached", timeout_s)

    def retarget_producers(self, address: Tuple[str, int]) -> None:
        for nm in self.producer_names():
            self.children[nm].send({"cmd": "connect",
                                    "address": list(address)})

    # -- chaos ---------------------------------------------------------

    def kill9(self, name: str) -> None:
        """SIGKILL one child, mid-whatever-it-was-doing."""
        self._chaos_point(f"proc_kill9@{name}")
        self.children[name].kill9()
        self.kills += 1

    def respawn(self, name: str) -> dict:
        """Restart a killed child under its original spec — same name,
        same state directory; a replica recovers from its mirrored WAL
        and rejoins through the horizon barrier."""
        self._chaos_point(f"proc_respawn@{name}")
        spec = self._specs[name]
        old = self.children.get(name)
        if old is not None and old.alive:
            raise RuntimeError(f"respawn of live child {name!r}; "
                               f"kill9 it first")
        if spec["role"] == "producer" and self.ingest_address:
            spec = dict(spec, connect=tuple(self.ingest_address))
        ready = self._spawn(spec)
        self.respawns += 1
        return ready

    # -- the consistent cut --------------------------------------------

    def barrier(self, *, timeout_s: float = 15.0,
                min_horizon: Optional[int] = None,
                names: Optional[List[str]] = None) -> Dict[str, int]:
        """Cross-process tick-horizon barrier over the replica fleet
        (a respawned process rejoins by passing this)."""
        names = self.replica_names() if names is None else names
        probes = {nm: self.control(nm).horizon for nm in names}
        return horizon_barrier(probes, min_horizon=min_horizon,
                               timeout_s=timeout_s)

    # -- failover ------------------------------------------------------

    def _promoted(self, name: str, ingest: Tuple[str, int],
                  epoch: int) -> PromotedLeader:
        """Called from the winning proxy once its child serves
        ingestion: swing the harness's view and the producers."""
        self.leader_name = name
        self.ingest_address = tuple(ingest)
        self.retarget_producers(self.ingest_address)
        for nm, spec in self._specs.items():
            if spec["role"] == "producer":
                spec["connect"] = tuple(ingest)
        return PromotedLeader(name, ingest, epoch)

    def coordinator(self, *, confirm_intervals: int = 2,
                    drain_timeout_s: float = 5.0,
                    epoch: int = 0,
                    **kw) -> FailoverCoordinator:
        """A stock FailoverCoordinator spanning the process boundary.

        The drain shipper is a cold-log SegmentShipper over the (about
        to be dead) leader's on-disk WAL; candidates are control-
        protocol proxies; the sampler reports ``committer_dead`` from
        the leader child's exit status. Drive it with ``step()`` in a
        loop, exactly like the in-process coordinator.
        """
        leader = self.children[self.leader_name]
        shipper = SegmentShipper(
            wal_dir=self.leader_wal_dir(),
            ckpt_dir=self.leader_ckpt_dir(), epoch=epoch)
        for nm in self.replica_names():
            if not self.children[nm].alive:
                continue
            try:
                shipper.attach(RemoteFollower(
                    TcpTransport(), self.replica_address(nm), name=nm))
            except TransportError:
                pass  # a dead candidate just isn't drained into

        def sampler(now: float) -> dict:
            return {"committer_dead": not leader.alive,
                    "pump_failed": False, "beat": None,
                    "partitioned": False}

        coord = FailoverCoordinator(
            [RemoteReplicaProxy(self, nm)
             for nm in self.replica_names()],
            shipper=shipper, sampler=sampler,
            confirm_intervals=confirm_intervals,
            drain_timeout_s=drain_timeout_s, **kw)
        coord._epoch = epoch
        return coord

    # -- teardown ------------------------------------------------------

    def close(self) -> None:
        """Stop everyone, bounded: producers, leader, replicas — any
        child missing its reap deadline is SIGKILLed."""
        order = (self.producer_names()
                 + ([self.leader_name] if self.leader_name else [])
                 + self.replica_names())
        seen = set()
        for nm in order + list(self.children):
            if nm in seen or nm not in self.children:
                continue
            seen.add(nm)
            self.children[nm].stop()
        if self.telemetry is not None:
            self.telemetry.close()
