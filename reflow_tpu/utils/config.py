"""Config/flag system (SURVEY.md §5): one dataclass, one env registry.

Two layers live here:

- :class:`ReflowConfig` — the load-bearing executor choice plus the
  scheduler knobs every entry point was already threading by hand
  (``from_env`` reads the ``REFLOW_*`` environment so a driver can flip
  the executor or loop bounds without code changes).
- the **knob registry** — every ``REFLOW_*`` environment variable the
  project reads is :func:`declare`-d here once, with its type, default
  and a one-line docstring, and read through the typed accessors
  (:func:`env_flag` / :func:`env_int` / :func:`env_float` /
  :func:`env_str`). ``tools/reflow_lint.py``'s env-knob pass enforces
  the funnel: a literal ``os.environ.get("REFLOW_...")`` anywhere else
  in the tree is a lint finding, an accessor read of an undeclared name
  raises :class:`KeyError` at runtime, and every declared knob must
  appear in docs/guide.md's knob catalog.

Why a funnel: six serving-tier PRs accreted ~50 knobs read at ~40 call
sites; an operator had no single place to discover them and a typo'd
name silently read its default forever. Now discovery is
``python -c "from reflow_tpu.utils.config import knob_table;
print(knob_table())"`` and typos fail loudly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

__all__ = ["Knob", "KNOBS", "ReflowConfig", "declare", "env_flag",
           "env_float", "env_int", "env_str", "knob_table"]


# -- knob registry ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob: its type tag (``flag`` / ``int``
    / ``float`` / ``str``), documented default, and one-line doc."""

    name: str
    kind: str
    default: object
    doc: str


#: name -> Knob for every REFLOW_* variable the project reads
KNOBS: Dict[str, Knob] = {}

_KINDS = ("flag", "int", "float", "str")
_UNSET = object()


def declare(name: str, kind: str, default, doc: str) -> str:
    """Register one knob (module import time). Idempotent re-declares
    with identical fields are allowed (reload safety); a conflicting
    re-declare raises."""
    if kind not in _KINDS:
        raise ValueError(f"knob kind {kind!r} not in {_KINDS}")
    if not name.startswith("REFLOW_"):
        raise ValueError(f"knob {name!r} must start with REFLOW_")
    prev = KNOBS.get(name)
    k = Knob(name, kind, default, doc)
    if prev is not None and prev != k:
        raise ValueError(f"knob {name!r} re-declared with different "
                         f"fields: {prev} vs {k}")
    KNOBS[name] = k
    return name


def _raw(name: str, env) -> Optional[str]:
    if name not in KNOBS:
        raise KeyError(
            f"{name!r} is not a declared knob; declare() it in "
            f"reflow_tpu/utils/config.py (docs/guide.md 'Environment "
            f"knobs')")
    v = (os.environ if env is None else env).get(name)
    return None if v is None or v == "" else v


def env_flag(name: str, default=_UNSET, *, env=None) -> bool:
    """Boolean knob: unset/empty -> default; else any value but "0" is
    True (so ``REFLOW_X=1`` enables, ``REFLOW_X=0`` disables)."""
    v = _raw(name, env)
    if v is None:
        d = KNOBS[name].default if default is _UNSET else default
        return bool(d)
    return v != "0"


def env_int(name: str, default=_UNSET, *, env=None) -> Optional[int]:
    v = _raw(name, env)
    if v is None:
        d = KNOBS[name].default if default is _UNSET else default
        return None if d is None else int(d)
    return int(v)


def env_float(name: str, default=_UNSET, *, env=None) -> Optional[float]:
    v = _raw(name, env)
    if v is None:
        d = KNOBS[name].default if default is _UNSET else default
        return None if d is None else float(d)
    return float(v)


def env_str(name: str, default=_UNSET, *, env=None) -> Optional[str]:
    v = _raw(name, env)
    if v is None:
        d = KNOBS[name].default if default is _UNSET else default
        return None if d is None else str(d)
    return v


def knob_table() -> str:
    """The knob catalog as a markdown table (docs/guide.md embeds the
    same rows; the lint's env-knob pass keeps them in sync by name)."""
    rows = ["| knob | type | default | what it does |",
            "|---|---|---|---|"]
    for k in sorted(KNOBS.values(), key=lambda k: k.name):
        rows.append(f"| `{k.name}` | {k.kind} | `{k.default}` | "
                    f"{k.doc} |")
    return "\n".join(rows)


# -- core runtime knobs -----------------------------------------------------

declare("REFLOW_EXECUTOR", "str", "cpu",
        "executor registry name: cpu (oracle) / tpu / sharded / staged")
declare("REFLOW_MAX_LOOP_ITERS", "int", 10_000,
        "fixpoint pass bound per tick (DirtyScheduler.max_loop_iters)")
declare("REFLOW_DEDUP_WINDOW", "int", 1 << 20,
        "idempotent-push dedup horizon (batch ids remembered)")
declare("REFLOW_MESH_DEVICES", "int", None,
        "mesh size for the sharded executor (unset = all local devices)")
declare("REFLOW_LINEAR_FIXPOINT", "flag", True,
        "fused delta-vector loop on tpu/sharded executors (0 disables)")
declare("REFLOW_WINDOW_DEPTH", "int", 2,
        "pipelined window depth (1 = serial stage->dispatch->retire)")
declare("REFLOW_MEGATICK_WASTE", "float", 0.5,
        "max padded-slot fraction before a fused window falls back")
declare("REFLOW_MEGATICK_MAX_ROWS", "int", 1 << 16,
        "max rows per fused mega-tick window before fallback")
declare("REFLOW_TOPK_PALLAS", "str", None,
        "force the Pallas top-k kernel on (1) or off (0); unset = "
        "auto-detect")
declare("REFLOW_LOCKCHECK", "flag", False,
        "wrap named locks with the runtime lock-order detector; a "
        "held-before cycle raises LockOrderError (docs/guide.md "
        "'Static analysis & lockcheck')")

# -- observability ----------------------------------------------------------

declare("REFLOW_TRACE", "flag", False,
        "enable per-ticket trace spans at import time (obs.enable())")
declare("REFLOW_TRACE_RING", "int", 65536,
        "per-thread trace ring-buffer capacity (spans)")
declare("REFLOW_TRACE_SAMPLE", "int", 16,
        "ticket sampling stride: 1-in-N tickets get a span timeline")
declare("REFLOW_TRACE_OUT", "str", None,
        "chrome-trace export path (bench obs mode / export default)")

# -- bench protocol ---------------------------------------------------------

declare("REFLOW_BENCH_ALL", "flag", True,
        "run the full config sweep in the default bench mode "
        "(0 = config-3 only)")
declare("REFLOW_BENCH_SMOKE", "flag", False,
        "CI-scale every bench mode (small graphs, short windows)")
declare("REFLOW_BENCH_CHILD", "str", None,
        "internal: which single config a bench child process runs")
declare("REFLOW_BENCH_NODES", "int", None,
        "pagerank bench graph nodes (default 100k, smoke 1k)")
declare("REFLOW_BENCH_EDGES", "int", None,
        "pagerank bench graph edges (default 1M, smoke 10k)")
declare("REFLOW_BENCH_CHURN", "float", 0.01,
        "per-tick churn fraction in the streaming benches")
declare("REFLOW_BENCH_STREAM_TICKS", "int", None,
        "pipelined window length (default 16, smoke 4)")
declare("REFLOW_BENCH_CPU_FULL", "flag", False,
        "run the CPU oracle at full scale instead of the capped sweep")
declare("REFLOW_BENCH_CPU_EDGES_CAP", "int", None,
        "CPU oracle measured at <= this many edges (default 200k)")
declare("REFLOW_BENCH_DEFER", "str", "1",
        "deferred-fixpoint mode for the bench loop (1/0/auto)")
declare("REFLOW_BENCH_TRACE", "str", None,
        "directory for an xprof device trace of one churn tick")
declare("REFLOW_BENCH_MODEL_AXIS", "int", 0,
        "model-parallel axis size for the image_embed config")
declare("REFLOW_BENCH_IMG_PER_TICK", "int", 256,
        "image_embed bench: images folded per tick")
declare("REFLOW_BENCH_KNN_DTYPE", "str", "int8",
        "knn bench wire dtype for document uploads")
declare("REFLOW_BENCH_KNN_SETTLE", "int", 60,
        "knn bench settle ticks before measuring")
declare("REFLOW_BENCH_KNN_PRELOAD", "int", None,
        "knn bench preloaded document count cap")
declare("REFLOW_BENCH_RECOVERY", "flag", False,
        "bench mode: WAL crash-recovery walls")
declare("REFLOW_BENCH_RECOVERY_TICKS", "int", 1000,
        "recovery bench crash-backlog size (ticks)")
declare("REFLOW_BENCH_RECOVERY_TPU_TICKS", "int", None,
        "recovery bench device-path backlog (default backlog/10)")
declare("REFLOW_BENCH_SERVE", "flag", False,
        "bench mode: streaming ingestion frontend throughput")
declare("REFLOW_BENCH_SERVE_BATCHES", "int", None,
        "serve bench micro-batches per producer (default 250, smoke 40)")
declare("REFLOW_BENCH_TIER", "flag", False,
        "bench mode: multi-graph serving tier")
declare("REFLOW_BENCH_TIER_BATCHES", "int", None,
        "tier bench micro-batches per producer (default 200, smoke 30)")
declare("REFLOW_BENCH_CONTROL", "flag", False,
        "bench mode: control-plane step-load surge/heal")
declare("REFLOW_BENCH_OBS", "flag", False,
        "bench mode: tracing + telemetry overhead and decomposition")
declare("REFLOW_BENCH_OBS_BATCHES", "int", None,
        "obs bench micro-batches per producer (default 250, smoke 40)")
declare("REFLOW_BENCH_WALPIPE", "flag", False,
        "bench mode: asynchronous durability pipeline")
declare("REFLOW_BENCH_WALPIPE_BATCHES", "int", None,
        "walpipe bench batches per producer at 16p (default 4, smoke 2)")
declare("REFLOW_BENCH_MEGATICK", "flag", False,
        "bench mode: compiled mega-tick windows vs the per-tick twin")
declare("REFLOW_BENCH_PIPELINE", "flag", False,
        "bench mode: pipelined window execution depth 2 vs depth 1")
declare("REFLOW_BENCH_SHARDSERVE", "flag", False,
        "bench mode: pod-scale spread/sharded serving")
declare("REFLOW_BENCH_SHARDSERVE_BATCHES", "int", None,
        "shardserve bench batches per producer (default 48, smoke 8)")
declare("REFLOW_BENCH_REPLICA", "flag", False,
        "bench mode: WAL shipping + read-replica scaling")
declare("REFLOW_BENCH_REPLICA_N", "int", 4,
        "replica bench follower count")
declare("REFLOW_BENCH_REPLICA_READ_S", "float", None,
        "replica bench per-leg read window seconds (default 2.0, "
        "smoke 0.6)")
declare("REFLOW_BENCH_FAILOVER", "flag", False,
        "bench mode: leader kill + epoch-fenced promotion")
declare("REFLOW_BENCH_FAILOVER_N", "int", 2,
        "failover bench follower count")
declare("REFLOW_BENCH_FAILOVER_RUN_S", "float", None,
        "failover bench per-phase write window seconds (default 1.0, "
        "smoke 0.3)")
declare("REFLOW_BENCH_CHAOS", "flag", False,
        "bench mode: chaos soak — faulty shipping links + leader kill")
declare("REFLOW_BENCH_CHAOS_N", "int", 3,
        "chaos bench follower count")
declare("REFLOW_BENCH_CHAOS_RUN_S", "float", None,
        "chaos bench per-phase write window seconds (default 1.2, "
        "smoke 0.4)")

# -- replication transport (docs/guide.md 'Replication over the wire') ------

declare("REFLOW_NET_IO_TIMEOUT_S", "float", 5.0,
        "per-operation send/recv/accept timeout on transport "
        "connections; no blocking wire call may wait longer")
declare("REFLOW_NET_CONNECT_TIMEOUT_S", "float", 2.0,
        "TCP connect() deadline when dialing a replica endpoint")
declare("REFLOW_NET_BACKOFF_BASE_S", "float", 0.05,
        "first reconnect delay; doubles per consecutive failure")
declare("REFLOW_NET_BACKOFF_CAP_S", "float", 2.0,
        "ceiling on the exponential reconnect delay")
declare("REFLOW_NET_BACKOFF_JITTER", "float", 0.25,
        "jitter fraction: each delay is scaled by a deterministic "
        "factor in [1-j, 1+j] from the seeded per-link RNG")
declare("REFLOW_NET_DEGRADED_AFTER", "int", 1,
        "consecutive link failures before a follower's connection "
        "state drops healthy -> degraded")
declare("REFLOW_NET_UNREACHABLE_AFTER", "int", 4,
        "consecutive link failures before degraded -> unreachable "
        "(ReadTier ejects the replica; failover may count a "
        "partition)")
declare("REFLOW_NET_FAULT_SEED", "int", 0,
        "seed for the wire fault-injection schedule (WireFaults); "
        "same seed = same drops/corruptions/partitions")

# -- bounded history (docs/guide.md 'Bounded history') ----------------------

declare("REFLOW_CKPT_DELTA_EVERY", "int", 8,
        "CheckpointChain cadence: every Nth save is promoted to a full "
        "checkpoint; the saves between are cheap delta elements "
        "(1 = every save full, i.e. deltas disabled)")
declare("REFLOW_COMPACT_INTERVAL_S", "float", 2.0,
        "background WAL compactor pass period (seconds)")
declare("REFLOW_COMPACT_MIN_SEGMENTS", "int", 3,
        "minimum eligible sealed segments before a compaction pass "
        "rewrites (smaller ranges are not worth the fold)")
declare("REFLOW_COMPACT_KEEP_SEGMENTS", "int", 1,
        "newest sealed segments a compaction pass leaves untouched "
        "(headroom between the fold and the committer's write head)")
declare("REFLOW_BENCH_COMPACT", "flag", False,
        "bench mode: bounded-history recovery/bootstrap — full-history "
        "replay vs {checkpoint chain + compacted tail}")
declare("REFLOW_BENCH_COMPACT_TICKS", "int", None,
        "compact bench batches per producer per leg "
        "(default 480, smoke 160)")

# -- tiled maintenance (docs/guide.md 'Tiled maintenance') ------------------

declare("REFLOW_TILE_BYTES", "int", 0,
        "key-range tile budget (bytes) for O(state) maintenance: "
        "compaction folds, checkpoint base/delta elements, and replica "
        "snapshots process one tile of roughly this many resident "
        "bytes at a time (enforced peak is 2x: estimate slop plus one "
        "oversized bucket). 0 (default) disables tiling — all three "
        "paths run their monolithic code byte-for-byte unchanged")
declare("REFLOW_TILE_SHIP_RETRIES", "int", 3,
        "per-tile resend attempts when a bootstrap tile unit is "
        "NACKed (CRC mismatch on the follower) before the shipper "
        "falls back to a whole-chain bootstrap")
declare("REFLOW_BENCH_TILES", "flag", False,
        "bench mode: tiled maintenance — two identically-fed legs at "
        "state >= 8x the tile budget; tiled leg must bound compaction "
        "and checkpoint/restore peak under 2x budget, recover + "
        "bootstrap with exact parity vs the monolithic leg, survive "
        "kill -9 at every per-tile crash seam, and match top_k/lookup "
        "against the untiled snapshot oracle")
declare("REFLOW_BENCH_TILES_TICKS", "int", None,
        "tiles bench batches per producer per leg "
        "(default 320, smoke 120)")

# -- fleet telemetry (docs/guide.md 'Fleet telemetry') ----------------------

declare("REFLOW_FLEET_NODE", "str", None,
        "this process's node id on the telemetry plane "
        "(default node-<pid>)")
declare("REFLOW_FLEET_INTERVAL_S", "float", 0.25,
        "telemetry shipper beat: seconds between registry-snapshot "
        "pushes to the fleet aggregator")
declare("REFLOW_FLEET_RETENTION", "int", 256,
        "fleet aggregator per-node time-series ring length "
        "(snapshots kept)")
declare("REFLOW_FLEET_STALE_S", "float", 2.0,
        "aggregator stale-marks a node whose newest snapshot is older "
        "than this (telemetry-loss display, never an error)")
declare("REFLOW_FLEET_LAG_SPREAD_MAX", "int", 64,
        "fleet lag-spread gauge (max-min follower horizon, ticks) "
        "above which the control plane logs an advisory action")
declare("REFLOW_BENCH_FLEETOBS", "flag", False,
        "bench mode: fleet telemetry plane — overhead A/B + causal "
        "chains + stale-marking on the chaos topology")
declare("REFLOW_BENCH_FLEETOBS_BATCHES", "int", None,
        "fleetobs bench batches per producer per A/B leg "
        "(default 320, smoke 160)")

# -- ingestion RPC + process harness ('Multi-process deployment') -----------

declare("REFLOW_RPC_IO_TIMEOUT_S", "float", 5.0,
        "per-operation send/recv timeout on ingestion RPC "
        "connections (RemoteProducer <-> RpcIngestServer)")
declare("REFLOW_RPC_SUBMIT_TIMEOUT_S", "float", 30.0,
        "server-side cap on how long one RPC submit may block in "
        "frontend admission (policy='block' backpressure) before "
        "the producer is told to retry")
declare("REFLOW_RPC_RESOLVE_WAIT_S", "float", 0.2,
        "server-side cap on one resolve poll's wait for a ticket to "
        "turn terminal (client long-polls in slices of this)")
declare("REFLOW_RPC_TICKETS", "int", 4096,
        "ingest server ticket-table bound; oldest resolved tickets "
        "are evicted first (an evicted in-flight ticket resolves as "
        "'unknown' and the producer resubmits — dedup keeps it "
        "exactly-once)")
declare("REFLOW_PROC_READY_TIMEOUT_S", "float", 30.0,
        "harness deadline for a spawned child process to print its "
        "ready line (addresses + pid)")
declare("REFLOW_PROC_REAP_TIMEOUT_S", "float", 10.0,
        "harness deadline for a stopping child to exit before it is "
        "SIGKILLed (a hung child can't wedge the suite)")
declare("REFLOW_PROC_POLL_S", "float", 0.05,
        "harness poll slice for child liveness / barrier probes")
declare("REFLOW_PROC_PYTHON", "str", None,
        "interpreter used to spawn harness children "
        "(default sys.executable)")
declare("REFLOW_BENCH_MULTIPROC", "flag", False,
        "bench mode: multi-process chaos — producer + replica OS "
        "processes, kill -9 storm, leader kill + cross-process "
        "promotion, exactly-once resubmit over the RPC")
declare("REFLOW_BENCH_MULTIPROC_N", "int", 3,
        "multiproc bench replica process count")
declare("REFLOW_BENCH_MULTIPROC_PRODUCERS", "int", 4,
        "multiproc bench producer process count")
declare("REFLOW_BENCH_MULTIPROC_RUN_S", "float", None,
        "multiproc bench per-phase write window seconds "
        "(default 1.5, smoke 0.6)")

# -- reactive reads ('Reactive reads') --------------------------------------

declare("REFLOW_SUB_OUTBOX", "int", 64,
        "per-subscriber outbox bound (frames); overflow conflates the "
        "backlog into one merged frame, and a backlog too large even "
        "to conflate sheds the subscriber to snapshot semantics")
declare("REFLOW_SUB_CONFLATE_MAX_ROWS", "int", 65536,
        "row bound on a conflated frame; beyond it the subscriber is "
        "shed (outbox cleared, fresh snapshot on the next round)")
declare("REFLOW_SUB_IDLE_POLL_S", "float", 0.05,
        "fan-out thread idle wakeup — the latency floor for reaping "
        "expired subscribers when no windows arrive")
declare("REFLOW_SUB_EXPIRE_S", "float", 30.0,
        "wire subscriptions not polled for this long are reaped (a "
        "reconnecting client re-registers and resumes by cursor)")
declare("REFLOW_SUB_POLL_WAIT_S", "float", 0.2,
        "server-side cap on one subscription long-poll's wait for "
        "frames (clients long-poll in slices of this)")
declare("REFLOW_SUB_MAX_FRAMES", "int", 256,
        "max frames returned by one subscription poll")
declare("REFLOW_SUB_IO_TIMEOUT_S", "float", 5.0,
        "per-operation send/recv timeout on subscription "
        "connections (Subscriber <-> SubscriptionServer)")
declare("REFLOW_BENCH_SUBS", "flag", False,
        "bench mode: reactive reads — one replica fans deltas to "
        "100k simulated subscribers under 16-producer write load; "
        "write-path p99 overhead, exact delta-vs-pull parity, "
        "partition/heal resume with zero gaps and zero duplicates")
declare("REFLOW_BENCH_SUBS_N", "int", None,
        "subs bench simulated subscriber count "
        "(default 100_000, smoke 2000)")
declare("REFLOW_BENCH_SUBS_RUN_S", "float", None,
        "subs bench per-leg write window seconds "
        "(default 2.0, smoke 0.6)")

# -- end-to-end tracing & flight recorder ('Follow-the-write') ---------------

declare("REFLOW_FLIGHT", "flag", False,
        "per-process flight recorder: tee sampled spans and "
        "control-plane events into a bounded on-disk ring in the "
        "node's disk corner, kill -9 recoverable "
        "(tools/reflow_flight.py merges the corners post-mortem)")
declare("REFLOW_FLIGHT_DIR", "str", None,
        "flight recorder directory override (default: <node "
        "root>/flight when run under proc/, else ./flight)")
declare("REFLOW_FLIGHT_BYTES", "int", 1 << 20,
        "flight recorder on-disk budget in bytes, split across two "
        "alternating generation files — the ring rotates, it never "
        "grows")
declare("REFLOW_FLIGHT_FLUSH_EVERY", "int", 64,
        "flight recorder flushes after this many buffered events "
        "(control-plane events — fence/promote/breaker — always "
        "flush eagerly)")
declare("REFLOW_BENCH_E2ETRACE", "flag", False,
        "bench mode: follow-the-write — multiproc topology under "
        "16-producer load with live wire subscribers and tracing on; "
        "kill -9 a replica and the leader mid-run, then assert "
        "sampled writes show complete submit→deliver chains, the "
        "freshness decomposition tiles ack→deliver, and every killed "
        "child's flight recording is recovered from its disk corner")
declare("REFLOW_BENCH_E2ETRACE_RUN_S", "float", None,
        "e2etrace bench per-leg write window seconds "
        "(default 1.5, smoke 0.6)")
declare("REFLOW_BENCH_E2ETRACE_PRODUCERS", "int", 16,
        "e2etrace bench producer process count")


# -- the config dataclass ---------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ReflowConfig:
    #: executor registry name: cpu (default path / oracle), tpu, sharded,
    #: staged
    executor: str = "cpu"
    #: fixpoint pass bound per tick (DirtyScheduler.max_loop_iters)
    max_loop_iters: int = 10_000
    #: idempotent-push dedup horizon (batch ids remembered)
    dedup_window: int = 1 << 20
    #: mesh size for the sharded executor (None = all local devices)
    mesh_devices: Optional[int] = None
    #: disable the fused delta-vector loop (tpu/sharded executors)
    linear_fixpoint: bool = True

    @staticmethod
    def from_env(env=None) -> "ReflowConfig":
        return ReflowConfig(
            executor=env_str("REFLOW_EXECUTOR", env=env),
            max_loop_iters=env_int("REFLOW_MAX_LOOP_ITERS", env=env),
            dedup_window=env_int("REFLOW_DEDUP_WINDOW", env=env),
            mesh_devices=env_int("REFLOW_MESH_DEVICES", env=env),
            linear_fixpoint=env_flag("REFLOW_LINEAR_FIXPOINT", env=env),
        )

    def make_executor(self):
        from reflow_tpu.executors import get_executor

        if self.executor == "sharded":
            from reflow_tpu.parallel import make_mesh
            from reflow_tpu.parallel.shard import ShardedTpuExecutor

            mesh = make_mesh(self.mesh_devices)
            ex = ShardedTpuExecutor(mesh)
        else:
            ex = get_executor(self.executor)
        if hasattr(ex, "linear_fixpoint") and not self.linear_fixpoint:
            ex.linear_fixpoint = False
            ex._linear_fixpoint = False
        return ex

    def scheduler(self, graph):
        from reflow_tpu.scheduler import DirtyScheduler

        return DirtyScheduler(graph, self.make_executor(),
                              max_loop_iters=self.max_loop_iters,
                              dedup_window=self.dedup_window)
