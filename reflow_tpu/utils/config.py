"""Config/flag system (SURVEY.md §5): one dataclass, one env mapping.

The load-bearing flag is the executor choice (cpu | tpu | sharded |
staged — SURVEY.md §5 names it explicitly); the rest are the scheduler
knobs every entry point was already threading by hand. ``from_env`` reads
the ``REFLOW_*`` environment (the convention bench.py established), so a
driver can flip the executor or loop bounds without code changes::

    cfg = ReflowConfig.from_env()          # REFLOW_EXECUTOR=sharded ...
    sched = cfg.scheduler(graph)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

__all__ = ["ReflowConfig"]


@dataclasses.dataclass(frozen=True)
class ReflowConfig:
    #: executor registry name: cpu (default path / oracle), tpu, sharded,
    #: staged
    executor: str = "cpu"
    #: fixpoint pass bound per tick (DirtyScheduler.max_loop_iters)
    max_loop_iters: int = 10_000
    #: idempotent-push dedup horizon (batch ids remembered)
    dedup_window: int = 1 << 20
    #: mesh size for the sharded executor (None = all local devices)
    mesh_devices: Optional[int] = None
    #: disable the fused delta-vector loop (tpu/sharded executors)
    linear_fixpoint: bool = True

    @staticmethod
    def from_env(env=os.environ) -> "ReflowConfig":
        md = env.get("REFLOW_MESH_DEVICES")
        return ReflowConfig(
            executor=env.get("REFLOW_EXECUTOR", "cpu"),
            max_loop_iters=int(env.get("REFLOW_MAX_LOOP_ITERS", 10_000)),
            dedup_window=int(env.get("REFLOW_DEDUP_WINDOW", 1 << 20)),
            mesh_devices=int(md) if md else None,
            linear_fixpoint=env.get("REFLOW_LINEAR_FIXPOINT", "1") != "0",
        )

    def make_executor(self):
        from reflow_tpu.executors import get_executor

        if self.executor == "sharded":
            from reflow_tpu.parallel import make_mesh
            from reflow_tpu.parallel.shard import ShardedTpuExecutor

            mesh = make_mesh(self.mesh_devices)
            ex = ShardedTpuExecutor(mesh)
        else:
            ex = get_executor(self.executor)
        if hasattr(ex, "linear_fixpoint") and not self.linear_fixpoint:
            ex.linear_fixpoint = False
            ex._linear_fixpoint = False
        return ex

    def scheduler(self, graph):
        from reflow_tpu.scheduler import DirtyScheduler

        return DirtyScheduler(graph, self.make_executor(),
                              max_loop_iters=self.max_loop_iters,
                              dedup_window=self.dedup_window)
