"""Key-range tiling for O(state) maintenance paths.

Compaction, checkpointing, and replica snapshot publication all walk
the full keyed state of a graph.  Monolithically that is O(state) peak
host memory — fine for demos, fatal at "millions of users" sizes.  The
shared move (the same one LSM compaction and sharded checkpoint
restore make) is to partition the key space into contiguous *tiles*
and process one tile at a time under a byte budget.

The partition must be stable across processes and across time: the
compactor, the checkpoint writer, a restoring replica, and the tile
shipper all need to agree on which tile owns a row key without
exchanging state.  So tiling is two-level:

- every row key hashes to one of ``N_BUCKETS`` fixed *buckets*
  (``bucket_of``) — deterministic, process-independent, and
  insensitive to insertion order;
- contiguous bucket runs are greedily grouped into *tiles* whose
  estimated resident bytes fit the ``REFLOW_TILE_BYTES`` budget
  (``plan_tiles``), from a cheap histogram pass the caller supplies.

A tile is then just a ``(lo, hi)`` half-open bucket range; ownership
is ``lo <= bucket_of(key) < hi``.  Budget 0 (the default) disables
tiling everywhere — callers fall back to their monolithic paths
byte-for-byte unchanged.
"""

from __future__ import annotations

import sys
import zlib
from typing import Any, List, Sequence, Tuple

import numpy as np

#: fixed bucket count — the histogram resolution.  Small enough that a
#: per-bucket byte histogram is trivially cheap, large enough that a
#: budget forcing dozens of tiles still gets balanced cuts.
N_BUCKETS = 64


def _scalarize(x: Any) -> Any:
    """Hashable, value-stable form of a row key (mirrors the WAL
    compactor's scalarization so folded and live rows agree)."""
    if isinstance(x, np.ndarray):
        return (x.dtype.str, x.shape, x.tobytes())
    if isinstance(x, np.generic):
        return x.item()
    return x


def bucket_of(rowkey: Any, n_buckets: int = N_BUCKETS) -> int:
    """Deterministic bucket for a row key.

    crc32 over the repr of the scalarized key: stable across
    processes and Python hash randomization (``hash()`` is salted per
    process, which would scatter a replica's tiles away from its
    leader's).
    """
    return zlib.crc32(repr(_scalarize(rowkey)).encode()) % n_buckets


def approx_row_bytes(key: Any, value: Any) -> int:
    """Cheap per-row resident-size estimate for the histogram pass.

    Exactness does not matter — tiles only need to land near the
    budget; the enforced bound is 2x budget, sized for estimate slop
    plus one oversized bucket.
    """
    n = 0
    for x in (key, value):
        if isinstance(x, np.ndarray):
            n += x.nbytes
        elif isinstance(x, (bytes, str)):
            n += len(x)
        elif x is not None:
            n += sys.getsizeof(x)
    return n + 16  # dict-slot / weight overhead


def plan_tiles(bucket_bytes: Sequence[float],
               budget: int) -> List[Tuple[int, int]]:
    """Group contiguous buckets into half-open ``(lo, hi)`` tiles.

    Greedy: extend the current tile while it stays under ``budget``;
    a single bucket over budget becomes its own tile (the plan never
    splits a bucket, so one hot bucket can exceed the budget — that is
    why the enforced peak bound is 2x, and why callers replan when a
    tile blows past it).  Returns at least one tile covering the whole
    bucket space; ``budget <= 0`` yields the single monolithic tile.
    """
    n = len(bucket_bytes)
    if budget <= 0 or n == 0:
        return [(0, max(n, 1))]
    tiles: List[Tuple[int, int]] = []
    lo = 0
    acc = 0.0
    for i, b in enumerate(bucket_bytes):
        if i > lo and acc + b > budget:
            tiles.append((lo, i))
            lo = i
            acc = 0.0
        acc += b
    tiles.append((lo, n))
    return tiles


def owning_tile(tiles: Sequence[Tuple[int, int]], bucket: int) -> int:
    """Index of the tile whose ``[lo, hi)`` range holds ``bucket``."""
    for i, (lo, hi) in enumerate(tiles):
        if lo <= bucket < hi:
            return i
    raise KeyError(f"bucket {bucket} outside tile plan {list(tiles)}")
