"""Metrics/observability (SURVEY.md §5): aggregate the scheduler's
per-tick records into the BASELINE metrics, and profile a tick on device.

``TickResult`` (scheduler.py) is the raw per-tick record: deltas in/out,
dirty-set size, pass count, wall time. This module turns a run's history
into the headline numbers (delta-ops/sec, percentile tick walls) and
offers a ``jax.profiler`` context for capturing a device trace of a tick.
"""

from __future__ import annotations

import contextlib
import dataclasses
import warnings
from typing import List, Sequence

import numpy as np

__all__ = ["MetricsSummary", "ServeMetrics", "TierMetrics", "WalMetrics",
           "percentile", "summarize", "summarize_serve", "summarize_tier",
           "summarize_wal", "profile_trace"]


def percentile(xs, q: float) -> float:
    """Shared percentile over any sample sequence (list, tuple, deque,
    ndarray): the one helper every ``summarize_*`` and the obs tooling
    use. Empty input answers 0.0 (a run that never exercised the path
    reports a zero latency, not a crash); a single sample answers
    itself at every q."""
    xs = np.asarray(xs, dtype=float)
    if xs.size == 0:
        return 0.0
    return float(np.percentile(xs, q))


def _jsonify(obj):
    """Recursively coerce numpy scalars/arrays to plain Python so the
    result survives ``json.dumps`` — the bench writes metric records to
    JSON so runs can be diffed across PRs."""
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return obj


@dataclasses.dataclass
class MetricsSummary:
    ticks: int
    delta_ops: int
    wall_s: float
    delta_ops_per_s: float
    tick_p50_s: float
    tick_p95_s: float
    passes_mean: float
    quiesced_all: bool
    #: ticks that forced a mid-stream device readback (the
    #: tunnel-degrading event — see utils/runtime.note_forced_sync);
    #: a streaming-shaped run should show 0 here until its sync point
    forced_syncs: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def summarize(history: Sequence) -> MetricsSummary:
    """Aggregate a scheduler's ``history`` (list of TickResult).

    Streaming ticks' scalar fields may still be device-resident (and
    ``quiesced`` a deferred callable); force each record to host values
    first — ``block()`` is idempotent and this is a sync point anyway.
    """
    if not history:
        # keyword-only on purpose: positional construction is exactly
        # how a field addition silently shifts every later field
        return MetricsSummary(
            ticks=0, delta_ops=0, wall_s=0.0, delta_ops_per_s=0.0,
            tick_p50_s=0.0, tick_p95_s=0.0, passes_mean=0.0,
            quiesced_all=True, forced_syncs=0)
    # ONE batched device_get of every device-resident scalar first: the
    # per-record block() then hits each jax.Array's cached host value
    # instead of issuing O(ticks x fields) sequential round trips (a
    # real cost on tunnel-attached runtimes; callable-wrapped parts
    # stay lazy and are forced by block itself)
    leaves = []
    for r in history:
        for f in (getattr(r, "passes", None), getattr(r, "deltas_in", None),
                  getattr(r, "deltas_out", None),
                  getattr(r, "quiesced", None)):
            parts = f.parts if hasattr(f, "parts") else (f,)
            leaves += [p for p in parts
                       if hasattr(p, "dtype") and hasattr(p, "addressable_shards")]
    if leaves:
        import jax

        jax.device_get(leaves)
    for r in history:
        if hasattr(r, "block"):
            r.block()
    walls = np.array([r.wall_s for r in history])
    dops = sum(r.delta_ops for r in history)
    return MetricsSummary(
        ticks=len(history),
        delta_ops=int(dops),
        wall_s=float(walls.sum()),
        delta_ops_per_s=float(dops / max(walls.sum(), 1e-12)),
        tick_p50_s=float(np.percentile(walls, 50)),
        tick_p95_s=float(np.percentile(walls, 95)),
        passes_mean=float(np.mean([r.passes for r in history])),
        quiesced_all=all(r.quiesced for r in history),
        forced_syncs=sum(bool(getattr(r, "forced_sync", False))
                         for r in history),
    )


@dataclasses.dataclass
class WalMetrics:
    """Durable-ingestion observability (``reflow_tpu.wal``): append and
    fsync latency percentiles from the log's recorded walls, plus the
    replay counters of a ``recovery.recover()`` run when one happened.
    """

    fsync_policy: str
    appends: int
    bytes_written: int
    fsyncs: int
    append_p50_s: float
    append_p95_s: float
    fsync_p50_s: float
    fsync_p95_s: float
    replayed_pushes: int
    deduped_pushes: int
    replayed_ticks: int
    #: group-commit shape under ``fsync="record"``: appends covered per
    #: fsync (1.0 everywhere = no batching happened; the serve frontend's
    #: coalesced appends should push these well above 1)
    group_commits: int = 0
    group_p50: float = 0.0
    group_max: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_dict(self) -> dict:
        """``as_dict`` with every value JSON-serializable (numpy
        scalars coerced) — the cross-PR diffable export."""
        return _jsonify(dataclasses.asdict(self))


def summarize_wal(wal, recovery=None) -> WalMetrics:
    """Aggregate a ``wal.WriteAheadLog``'s counters (and optionally a
    ``wal.RecoveryReport``'s replay counters) into one record."""
    pct = percentile
    return WalMetrics(
        fsync_policy=wal.fsync_policy,
        appends=wal.appends,
        bytes_written=wal.bytes_written,
        fsyncs=wal.fsyncs,
        append_p50_s=pct(wal.append_s, 50),
        append_p95_s=pct(wal.append_s, 95),
        fsync_p50_s=pct(wal.fsync_s, 50),
        fsync_p95_s=pct(wal.fsync_s, 95),
        replayed_pushes=getattr(recovery, "replayed_pushes", 0),
        deduped_pushes=getattr(recovery, "deduped_pushes", 0),
        replayed_ticks=getattr(recovery, "replayed_ticks", 0),
        group_commits=len(getattr(wal, "group_sizes", [])),
        group_p50=pct(getattr(wal, "group_sizes", []), 50),
        group_max=float(max(getattr(wal, "group_sizes", []) or [0.0])),
    )


@dataclasses.dataclass
class ServeMetrics:
    """Ingestion-frontend observability (``reflow_tpu.serve``): admission
    outcomes, coalescing effectiveness, and producer-visible latency.

    ``coalesce_factor`` is the headline: micro-batches applied per
    scheduler tick. 1.0 means the window never merged anything (light
    traffic); the serve bench asserts > 1 under 16 producers.
    """

    policy: str
    submitted: int
    admitted: int
    applied: int
    deduped: int
    rejected: int
    shed: int
    ticks: int
    pump_iterations: int
    coalesce_factor: float
    ticks_per_pump_mean: float
    admission_p50_s: float
    admission_p95_s: float
    queue_depth_p95: float
    inflight_bytes_peak: int
    #: pipelined-pump view: configured in-flight window depth, windows
    #: that took the stage/dispatch/retire path, how many of those
    #: staged while a previous window was still in flight, and the
    #: fraction of host staging wall that overlapped device compute
    #: (0.0 at depth 1 — staging and execution strictly alternate)
    window_depth: int = 1
    windows_staged: int = 0
    windows_pipelined: int = 0
    stage_overlap_frac: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_dict(self) -> dict:
        """``as_dict`` with every value JSON-serializable (numpy
        scalars coerced) — the cross-PR diffable export."""
        return _jsonify(dataclasses.asdict(self))


def summarize_serve(frontend) -> ServeMetrics:
    """Aggregate an ``IngestFrontend``'s counters into one record."""
    pct = percentile
    tp = frontend.ticks_per_pump
    return ServeMetrics(
        policy=frontend.policy,
        submitted=frontend.submitted,
        admitted=frontend.admitted,
        applied=frontend.applied,
        deduped=frontend.deduped,
        rejected=frontend.rejected,
        shed=frontend.shed,
        ticks=frontend.ticks,
        pump_iterations=frontend.pump_iterations,
        coalesce_factor=frontend.applied / max(frontend.ticks, 1),
        ticks_per_pump_mean=float(np.mean(tp)) if tp else 0.0,
        admission_p50_s=pct(frontend.admission_s, 50),
        admission_p95_s=pct(frontend.admission_s, 95),
        queue_depth_p95=pct(frontend.queue_depth_samples, 95),
        inflight_bytes_peak=frontend.inflight_bytes_peak,
        window_depth=getattr(frontend, "depth", 1),
        windows_staged=getattr(frontend, "windows_staged", 0),
        windows_pipelined=getattr(frontend, "windows_pipelined", 0),
        stage_overlap_frac=getattr(frontend, "stage_overlap_frac", 0.0),
    )


@dataclasses.dataclass
class TierMetrics:
    """Multi-graph serving-tier observability (``serve.tier``): pool
    health (utilization, windows, crash count), shared-budget occupancy,
    and cross-graph scheduling delay — the time a ready graph waited for
    a pool thread, the number QoS weighting is supposed to keep bounded
    for quiet tenants under a hot sibling.

    ``per_graph`` nests each live graph's ``ServeMetrics.to_dict()``
    plus its QoS/budget/pool view (weight, floor/ceiling, bytes used and
    peak, windows served, rows applied, scheduling-delay and admission
    p99, frontend state).
    """

    graphs: int
    pump_threads: int
    windows: int
    pool_crashes: int
    pump_utilization: float
    budget_total_bytes: int
    budget_used_bytes: int
    budget_peak_bytes: int
    #: high-water shared-budget occupancy fraction (peak/total)
    budget_occupancy_peak: float
    sched_delay_p50_s: float
    sched_delay_p99_s: float
    per_graph: dict
    #: pool supervision view: workers alive now vs the scale target,
    #: deaths recorded and respawns performed (control-plane healing)
    live_workers: int = 0
    worker_deaths: int = 0
    worker_respawns: int = 0
    #: picks where every positive-deficit candidate's bound device
    #: already had a window in flight (placement-aware DWRR could not
    #: avoid stacking; persistent growth = graphs-per-device skew)
    device_collisions: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_dict(self) -> dict:
        """``as_dict`` with every value JSON-serializable (numpy
        scalars coerced) — the cross-PR diffable export."""
        return _jsonify(dataclasses.asdict(self))


def summarize_tier(tier) -> TierMetrics:
    """Aggregate a ``serve.ServeTier``'s pool/budget counters and every
    live graph's frontend counters into one record."""
    pct = percentile
    handles = tier.graphs()
    shares = tier.budget.shares()
    per_graph = {}
    all_delays: List[float] = []
    for name, h in handles.items():
        fe = h.frontend
        g = summarize_serve(fe).to_dict()
        share = shares.get(name)
        g.update(
            weight=h.config.weight,
            floor_bytes=h.config.floor_bytes,
            ceiling_bytes=(share.ceiling if share is not None
                           else h.config.ceiling_bytes),
            bytes_used=share.used if share is not None else 0,
            bytes_peak=share.peak if share is not None else 0,
            windows=h.windows,
            rows_applied=h.rows_applied,
            sched_delay_p50_s=pct(h.sched_delay_s, 50),
            sched_delay_p99_s=pct(h.sched_delay_s, 99),
            admission_p99_s=pct(fe.admission_s, 99),
            state=fe._state,
            policy=fe.policy,
            crashes=h.crashes,
            revives=fe.revives,
            device=h.device_label,
        )
        per_graph[name] = g
        all_delays.extend(h.sched_delay_s)
    return TierMetrics(
        graphs=len(handles),
        pump_threads=tier.pump_threads,
        windows=tier.windows,
        pool_crashes=tier.pool_crashes,
        pump_utilization=tier.pump_utilization,
        budget_total_bytes=tier.budget.total_bytes,
        budget_used_bytes=tier.budget.used,
        budget_peak_bytes=tier.budget.peak,
        budget_occupancy_peak=tier.budget.peak / tier.budget.total_bytes,
        sched_delay_p50_s=pct(all_delays, 50),
        sched_delay_p99_s=pct(all_delays, 99),
        per_graph=per_graph,
        live_workers=tier.live_workers,
        worker_deaths=tier.worker_deaths,
        worker_respawns=tier.worker_respawns,
        device_collisions=tier.device_collisions,
    )


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a ``jax.profiler`` device trace around a block of ticks::

        with profile_trace("/tmp/trace"):
            sched.tick()

    View with TensorBoard / xprof against the produced log dir.

    Degrades gracefully: when ``jax.profiler`` is unavailable (CPU-only
    builds, stripped wheels) or refuses to start, the context runs the
    block untraced and warns instead of raising — profiling is
    observability, never correctness.
    """
    try:
        import jax

        start, stop = jax.profiler.start_trace, jax.profiler.stop_trace
        start(log_dir)
    except Exception as e:  # noqa: BLE001 - degrade to a no-op trace
        warnings.warn(
            f"jax.profiler unavailable ({e!r}); profile_trace is a "
            f"no-op for this block", RuntimeWarning, stacklevel=3)
        yield
        return
    try:
        yield
    finally:
        stop()


#: warn once, then stay silent: dispatch-path annotation failures must
#: not spam a log line per window
_annotation_warned = False


@contextlib.contextmanager
def profile_annotation(name: str, *, enabled: bool = True):
    """Label a block with a ``jax.profiler.TraceAnnotation`` so a device
    trace (``profile_trace`` / xprof) lines it up against host spans —
    one annotation per mega-tick window dispatch correlates the obs
    ``device_dispatch`` span with device occupancy in Perfetto.

    ``enabled=False`` (and any profiler failure) degrades to running the
    block unannotated; like :func:`profile_trace`, annotation is
    observability, never correctness. Failures warn once per process.
    """
    global _annotation_warned
    if not enabled:
        yield
        return
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception as e:  # noqa: BLE001 - degrade to a no-op label
        if not _annotation_warned:
            _annotation_warned = True
            warnings.warn(
                f"jax.profiler unavailable ({e!r}); profile_annotation "
                f"is a no-op", RuntimeWarning, stacklevel=3)
        yield
        return
    with ctx:
        yield
