"""Fault injection for source delivery (SURVEY.md §5: failure testing).

Models a lossy at-least-once transport between an upstream producer and a
graph source: batches can be **dropped** (and retransmitted later),
**duplicated** (retransmitted although already delivered), and
**reordered** (delivered out of send order within a bounded window).

The scheduler's idempotent ``push(batch_id=...)`` dedup plus the
transport's retransmission makes the composition exactly-once: after
``flush()`` every batch has been folded into the graph exactly once, so a
faulty run's sink views must equal a clean run's — the property the
fault-injection tests assert.

Beyond the lossy transport, this module injects **process death**:
:class:`CrashInjector` raises :class:`CrashPoint` at the WAL's
instrumented seams (before/after the append, between push and tick, at
the tick marker — ``wal/durable.py``), and :func:`tear_wal_tail`
truncates the log mid-record after the fact, simulating a write torn by
the kill. The differential property extends accordingly: a crashed,
torn, recovered run's sink views must equal an uninterrupted clean
run's (``tests/test_wal.py``).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import Node
from reflow_tpu.utils.runtime import named_lock

__all__ = ["CrashInjector", "CrashPoint", "DeliveryError", "FaultyChannel",
           "StormInjector", "WireFaults", "tear_wal_tail"]


class DeliveryError(RuntimeError):
    """The transport observed the scheduler violating the delivery
    contract (a duplicate accepted, or a first delivery rejected)."""


class CrashPoint(BaseException):
    """Simulated process death. Derives from BaseException so generic
    ``except Exception`` recovery paths can't accidentally 'survive'
    the kill — only the test harness catches it."""


class CrashInjector:
    """Raise :class:`CrashPoint` at the N-th instrumented crash seam.

    ``at`` counts every visited seam; ``only`` restricts counting to
    seams whose name contains the substring (e.g. ``"append"`` to die
    inside the WAL write path, ``"after_push"`` to die between push and
    tick, ``"pump"`` to kill the serve frontend's pump thread).
    ``fired`` records whether the kill happened; ``fired_seam`` which
    seam it happened at.

    A tier-hosted frontend (``serve.tier.ServeTier``) scopes every seam
    name with its graph: ``pump_before_tick@analytics``, plus the
    pool's own pre-window seam ``pool_window@analytics``. So
    ``only="@analytics"`` kills exactly one graph's macro-tick on a
    shared pump pool — the fault-isolation property the tier tests
    assert (that graph's tickets fail ``PumpCrashed``; the worker
    thread survives and siblings keep ticking).

    Seam visits are counted under a lock: the serve frontend fires its
    seams from N producer threads (``producer_submit`` /
    ``producer_admitted``) and the pump thread (``pump_coalesce`` /
    ``pump_before_tick`` / ``pump_after_tick``) concurrently, and
    exactly ONE of them must die — a racy double-fire would kill a
    producer *and* the pump, breaking the single-process-death model.
    """

    def __init__(self, at: int, *, only: Optional[str] = None):
        self.remaining = at
        self.only = only
        self.fired = False
        self.fired_seam: Optional[str] = None
        self.seams: List[str] = []
        self._lock = named_lock("faults.crash")

    def point(self, name: str) -> None:
        with self._lock:
            if self.fired or (self.only is not None
                              and self.only not in name):
                return
            self.seams.append(name)
            self.remaining -= 1
            if self.remaining <= 0:
                self.fired = True
                self.fired_seam = name
                raise CrashPoint(name)


class StormInjector:
    """Raise :class:`CrashPoint` at EVERY visit of matching seams while
    armed — a repeating crash storm, where :class:`CrashInjector` models
    exactly one process death.

    This is the circuit-breaker scenario: a graph whose every revival
    crashes again (a poisoned batch, a broken kernel) must trip the
    control plane's breaker instead of burning the pool in a
    crash-respawn loop; :meth:`disarm` ends the storm so the breaker's
    half-open probe can prove the graph healthy again. ``crashes``
    counts the kills actually delivered."""

    def __init__(self, only: str):
        self.only = only
        self.armed = True
        self.crashes = 0
        self.seams: List[str] = []
        self._lock = named_lock("faults.storm")

    def point(self, name: str) -> None:
        with self._lock:
            if not self.armed or self.only not in name:
                return
            self.crashes += 1
            self.seams.append(name)
        raise CrashPoint(name)

    def disarm(self) -> None:
        self.armed = False

    def rearm(self) -> None:
        self.armed = True


def tear_wal_tail(wal_dir: str, cut_bytes: int) -> Optional[str]:
    """Tear the WAL's final record as a mid-write kill would: strictly
    in the LAST segment (the only one a live writer ever touches). A
    segment with records loses its last ``cut_bytes`` (clamped to the
    8-byte magic header, so the tear models a torn *record*, not a
    missing segment); a freshly-rotated empty segment instead gains a
    partial frame (a header whose payload never landed). Returns the
    torn segment's path, or None for an empty log."""
    from reflow_tpu.wal.log import _MAGIC, list_segments

    segs = list_segments(wal_dir)
    if not segs:
        return None
    _seq, path = segs[-1]
    size = os.path.getsize(path)
    if size > len(_MAGIC):
        with open(path, "rb+") as f:
            f.truncate(max(len(_MAGIC), size - cut_bytes))
    else:
        with open(path, "ab") as f:
            f.write((64).to_bytes(4, "little") + b"\0\0\0\0" + b"\xde\xad")
    return path


class WireFaults:
    """Seeded fault schedule for one replication link — the *policy*
    half of wire fault injection (``net/faults.py``'s
    ``FaultyTransport`` is the mechanism that acts on these rolls).

    Extends the :class:`CrashInjector` seam idiom to the network: the
    transport asks this object what happens to each message, and the
    answer is a pure function of the seed plus the scripted partition /
    reset state — same seed, same storm. Per-message faults are rolled
    by :meth:`decide` (mutually exclusive outcomes, probabilities are
    independent weights normalized against staying healthy); scripted
    faults (:meth:`partition` / :meth:`heal` / :meth:`reset_once`) are
    imperative switches the chaos bench throws on a timeline.

    Thread-safe: one link's client may be probed from the shipper pump
    and a read-tier prober concurrently, and counters must not tear.
    :meth:`quiesce` zeroes every probability and heals partitions — the
    bench's "faults stop" moment, after which replicas must converge.
    """

    #: per-message outcomes decide() can roll, in roll order
    OUTCOMES = ("drop_c2s", "drop_s2c", "dup", "reorder",
                "corrupt_frame", "corrupt_payload", "reset")

    def __init__(self, *, seed: int = 0, drop_c2s_p: float = 0.0,
                 drop_s2c_p: float = 0.0, dup_p: float = 0.0,
                 reorder_p: float = 0.0, corrupt_frame_p: float = 0.0,
                 corrupt_payload_p: float = 0.0, reset_p: float = 0.0,
                 delay_p: float = 0.0, delay_s: float = 0.0):
        self.p = {"drop_c2s": drop_c2s_p, "drop_s2c": drop_s2c_p,
                  "dup": dup_p, "reorder": reorder_p,
                  "corrupt_frame": corrupt_frame_p,
                  "corrupt_payload": corrupt_payload_p,
                  "reset": reset_p}
        self.delay_p = delay_p
        self.delay_s = delay_s
        self.rng = np.random.default_rng(seed)
        self._lock = named_lock("faults.wire")
        self._partition = set()  # subset of {"c2s", "s2c"}
        self._resets_pending = 0
        self.stats = {k: 0 for k in self.OUTCOMES}
        self.stats.update(ok=0, delays=0, partitioned=0,
                          scripted_resets=0)

    # -- scripted timeline controls ------------------------------------

    def partition(self, direction: str = "both") -> None:
        """Open a partition: ``"c2s"`` (requests vanish), ``"s2c"``
        (responses vanish — the server still applies!), or ``"both"``."""
        with self._lock:
            dirs = {"c2s", "s2c"} if direction == "both" else {direction}
            bad = dirs - {"c2s", "s2c"}
            if bad:
                raise ValueError(f"unknown partition direction {bad}")
            self._partition |= dirs

    def heal(self) -> None:
        with self._lock:
            self._partition.clear()

    def reset_once(self, n: int = 1) -> None:
        """Arm ``n`` scripted connection resets: the next ``n``
        messages each kill their connection instead of transmitting."""
        with self._lock:
            self._resets_pending += n

    def set_rates(self, *, delay_p: Optional[float] = None,
                  delay_s: Optional[float] = None,
                  **rates: float) -> None:
        """Rewire per-message probabilities mid-run — the chaos
        bench's 'storm on' switch (:meth:`quiesce` is the off switch,
        so links can attach and handshake over a quiet wire first).
        Keyword names are :data:`OUTCOMES` entries."""
        with self._lock:
            bad = set(rates) - set(self.p)
            if bad:
                raise ValueError(f"unknown fault outcome(s) {bad}")
            self.p.update(rates)
            if delay_p is not None:
                self.delay_p = delay_p
            if delay_s is not None:
                self.delay_s = delay_s

    def quiesce(self) -> None:
        """Stop all faults: zero every probability, heal partitions,
        disarm pending resets. The bench's 'faults stop' switch."""
        with self._lock:
            for k in self.p:
                self.p[k] = 0.0
            self.delay_p = 0.0
            self._partition.clear()
            self._resets_pending = 0

    # -- per-message decisions (called by FaultyTransport) -------------

    def is_partitioned(self, direction: str) -> bool:
        with self._lock:
            return direction in self._partition

    def take_scripted_reset(self) -> bool:
        with self._lock:
            if self._resets_pending > 0:
                self._resets_pending -= 1
                self.stats["scripted_resets"] += 1
                return True
            return False

    def decide(self) -> str:
        """Roll one per-message outcome: an :data:`OUTCOMES` entry or
        ``"ok"``. Outcomes are mutually exclusive per message; the
        first winning roll in fixed order takes it (so probabilities
        compose deterministically under one seed)."""
        with self._lock:
            for k in self.OUTCOMES:
                if self.p[k] > 0.0 and self.rng.random() < self.p[k]:
                    self.stats[k] += 1
                    return k
            self.stats["ok"] += 1
            return "ok"

    def delay_roll(self) -> float:
        """Seconds to stall this message (0.0 almost always)."""
        with self._lock:
            if self.delay_p > 0.0 and self.rng.random() < self.delay_p:
                self.stats["delays"] += 1
                return self.delay_s
            return 0.0

    def count_partitioned(self) -> None:
        with self._lock:
            self.stats["partitioned"] += 1

    def flip(self, data: bytes) -> bytes:
        """Flip one seeded bit somewhere in ``data`` (corruption
        payload for either the frame header or the pickled body)."""
        if not data:
            return data
        with self._lock:
            i = int(self.rng.integers(0, len(data)))
            bit = 1 << int(self.rng.integers(0, 8))
        out = bytearray(data)
        out[i] ^= bit
        return bytes(out)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats, partition=sorted(self._partition))


class FaultyChannel:
    """At-least-once delivery of source batches with injected faults.

    ``send`` enqueues a batch; each call then attempts delivery of some
    enqueued batches with faults applied. A batch stays queued until a
    delivery attempt is "acked" (survives the drop roll), so nothing is
    ever lost — only delayed, repeated, or reordered. Call ``flush()``
    before the final tick to force the tail retransmissions.
    """

    def __init__(self, sched, source: Node, *, drop_p: float = 0.3,
                 dup_p: float = 0.3, reorder_window: int = 4, seed: int = 0):
        self.sched = sched
        self.source = source
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.reorder_window = reorder_window
        self.rng = np.random.default_rng(seed)
        self._unacked: List[Tuple[str, DeltaBatch]] = []
        self._delivered_ids: List[str] = []   # for duplicate injection
        self.stats = {"delivered": 0, "dropped": 0, "duplicated": 0,
                      "reordered": 0}
        self._batches = {}

    def send(self, batch: DeltaBatch, batch_id: str) -> None:
        self._unacked.append((batch_id, batch))
        self._batches[batch_id] = batch
        self._pump()

    def _pump(self) -> None:
        # reorder: deliver from a window at a random position
        while self._unacked:
            w = min(self.reorder_window, len(self._unacked))
            i = int(self.rng.integers(0, w))
            if i != 0:
                self.stats["reordered"] += 1
            bid, batch = self._unacked[i]
            if self.rng.random() < self.drop_p:
                # this transmission is lost in flight; the batch stays
                # queued for retransmission
                self.stats["dropped"] += 1
                if self.rng.random() < 0.5:
                    break  # transport stalls until the next send/flush
                continue
            self.sched.push(self.source, batch, batch_id=bid)
            self.stats["delivered"] += 1
            self._delivered_ids.append(bid)
            del self._unacked[i]
            # duplicate: retransmit an already-delivered batch (the
            # upstream never got the ack); the dedup set must drop it
            if self._delivered_ids and self.rng.random() < self.dup_p:
                dup = self._delivered_ids[
                    int(self.rng.integers(0, len(self._delivered_ids)))]
                accepted = self.sched.push(self.source, self._batches[dup],
                                           batch_id=dup)
                if accepted:
                    # must raise even under python -O: a silently
                    # double-folded batch corrupts every downstream view
                    raise DeliveryError(
                        f"duplicate batch {dup!r} was accepted (folded "
                        f"twice) — the scheduler's dedup window dropped "
                        f"it; widen dedup_window or tighten redelivery")
                self.stats["duplicated"] += 1
            if self.rng.random() < 0.3:
                break  # partial progress per pump

    def flush(self) -> None:
        """Retransmit until every batch has been delivered exactly once."""
        while self._unacked:
            bid, batch = self._unacked.pop(0)
            accepted = self.sched.push(self.source, batch, batch_id=bid)
            if not accepted:
                # a queued batch was by definition never delivered, so a
                # rejection means the dedup window claims an id the
                # transport still holds — at-least-once just became
                # at-most-once for this batch
                raise DeliveryError(
                    f"first delivery of batch {bid!r} was rejected as a "
                    f"duplicate; its rows were never folded")
            self.stats["delivered"] += 1
            self._delivered_ids.append(bid)
