"""Fault injection for source delivery (SURVEY.md §5: failure testing).

Models a lossy at-least-once transport between an upstream producer and a
graph source: batches can be **dropped** (and retransmitted later),
**duplicated** (retransmitted although already delivered), and
**reordered** (delivered out of send order within a bounded window).

The scheduler's idempotent ``push(batch_id=...)`` dedup plus the
transport's retransmission makes the composition exactly-once: after
``flush()`` every batch has been folded into the graph exactly once, so a
faulty run's sink views must equal a clean run's — the property the
fault-injection tests assert.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import Node

__all__ = ["FaultyChannel"]


class FaultyChannel:
    """At-least-once delivery of source batches with injected faults.

    ``send`` enqueues a batch; each call then attempts delivery of some
    enqueued batches with faults applied. A batch stays queued until a
    delivery attempt is "acked" (survives the drop roll), so nothing is
    ever lost — only delayed, repeated, or reordered. Call ``flush()``
    before the final tick to force the tail retransmissions.
    """

    def __init__(self, sched, source: Node, *, drop_p: float = 0.3,
                 dup_p: float = 0.3, reorder_window: int = 4, seed: int = 0):
        self.sched = sched
        self.source = source
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.reorder_window = reorder_window
        self.rng = np.random.default_rng(seed)
        self._unacked: List[Tuple[str, DeltaBatch]] = []
        self._delivered_ids: List[str] = []   # for duplicate injection
        self.stats = {"delivered": 0, "dropped": 0, "duplicated": 0,
                      "reordered": 0}
        self._batches = {}

    def send(self, batch: DeltaBatch, batch_id: str) -> None:
        self._unacked.append((batch_id, batch))
        self._batches[batch_id] = batch
        self._pump()

    def _pump(self) -> None:
        # reorder: deliver from a window at a random position
        while self._unacked:
            w = min(self.reorder_window, len(self._unacked))
            i = int(self.rng.integers(0, w))
            if i != 0:
                self.stats["reordered"] += 1
            bid, batch = self._unacked[i]
            if self.rng.random() < self.drop_p:
                # this transmission is lost in flight; the batch stays
                # queued for retransmission
                self.stats["dropped"] += 1
                if self.rng.random() < 0.5:
                    break  # transport stalls until the next send/flush
                continue
            self.sched.push(self.source, batch, batch_id=bid)
            self.stats["delivered"] += 1
            self._delivered_ids.append(bid)
            del self._unacked[i]
            # duplicate: retransmit an already-delivered batch (the
            # upstream never got the ack); the dedup set must drop it
            if self._delivered_ids and self.rng.random() < self.dup_p:
                dup = self._delivered_ids[
                    int(self.rng.integers(0, len(self._delivered_ids)))]
                accepted = self.sched.push(self.source, self._batches[dup],
                                           batch_id=dup)
                assert not accepted, "duplicate batch was folded twice"
                self.stats["duplicated"] += 1
            if self.rng.random() < 0.3:
                break  # partial progress per pump

    def flush(self) -> None:
        """Retransmit until every batch has been delivered exactly once."""
        while self._unacked:
            bid, batch = self._unacked.pop(0)
            self.sched.push(self.source, batch, batch_id=bid)
            self.stats["delivered"] += 1
            self._delivered_ids.append(bid)
