"""Runtime-environment detection + the forced-sync advisory.

Measured property of tunnel-attached (remote) TPU runtimes that shapes
every latency-sensitive caller in this repo (bench.py's protocol,
kernels/topk.py's Pallas opt-out): the FIRST device->host readback of a
process permanently flips the runtime into a degraded synchronous
dispatch mode (~0.1s per subsequent sync; chained small dispatches
~66ms each). A user who ticks synchronously — the natural first thing
to write — silently pays ~2.5x the streaming rate (VERDICT r3 weak #6).
:func:`note_forced_sync` converts that tribal knowledge into product: a
ONE-TIME warning on the first forced sync on such a runtime, pointing
at the streaming pattern (``tick(sync=False)`` + one ``block()`` per
batch — docs/guide.md "Streaming and the tunnel runtime").
"""

from __future__ import annotations

import os
import warnings

__all__ = ["remote_tunnel_runtime", "note_forced_sync"]


def remote_tunnel_runtime() -> bool:
    """True when the TPU sits behind the axon tunnel runtime (it
    masquerades as platform "tpu"). Detection prefers axon's stable
    ``active_backend()`` accessor; the env sentinel is the fallback (the
    plugin documents it as subject to environ snapshot/restore)."""
    try:
        from axon.register import active_backend
        return active_backend() is not None
    except Exception:  # noqa: BLE001 - no axon installed / API drift
        return os.environ.get("_AXON_REGISTERED") == "1"


_warned = False


def _tunnel_active() -> bool:
    """The computation actually RUNS on the tunnel: the plugin is
    registered AND jax resolved to the tpu backend (the plugin can be
    importable while tests force JAX_PLATFORMS=cpu — no degradation
    happens there, so no warning should either)."""
    if not remote_tunnel_runtime():
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 - backend init failure
        return False


def note_forced_sync(context: str) -> None:
    """Record a mid-stream device readback; warn ONCE per process when
    the runtime is a tunnel (where the first readback permanently
    degrades dispatch). Cheap no-op everywhere else."""
    global _warned
    if _warned:
        return
    _warned = True
    if _tunnel_active():
        warnings.warn(
            f"first device readback ({context}) on a tunnel-attached TPU "
            f"runtime: the runtime now stays in degraded synchronous "
            f"dispatch (~0.1s per sync) for the rest of the process. For "
            f"throughput, stream ticks with tick(sync=False) and call "
            f"block()/read_table once per batch — see docs/guide.md "
            f"('Streaming and the tunnel runtime').",
            stacklevel=3)
