"""Runtime-environment detection, the forced-sync advisory, and the
opt-in lock-order detector.

Measured property of tunnel-attached (remote) TPU runtimes that shapes
every latency-sensitive caller in this repo (bench.py's protocol,
kernels/topk.py's Pallas opt-out): the FIRST device->host readback of a
process permanently flips the runtime into a degraded synchronous
dispatch mode (~0.1s per subsequent sync; chained small dispatches
~66ms each). A user who ticks synchronously — the natural first thing
to write — silently pays ~2.5x the streaming rate (VERDICT r3 weak #6).
:func:`note_forced_sync` converts that tribal knowledge into product: a
ONE-TIME warning on the first forced sync on such a runtime, pointing
at the streaming pattern (``tick(sync=False)`` + one ``block()`` per
batch — docs/guide.md "Streaming and the tunnel runtime").

Lock-order detection (``REFLOW_LOCKCHECK=1``): every lock in the
serving/WAL stack is created through :func:`named_lock`. Off (the
default) that returns a plain ``threading.Lock``/``RLock`` — zero
overhead, byte-identical behavior. On, it returns a :class:`NamedLock`
wrapper that records per-thread acquisition stacks into the global
:data:`LOCK_MONITOR`, merges every acquisition into one held-before
graph, and raises :class:`LockOrderError` the moment an acquisition
would close a cycle (the classic AB/BA deadlock, caught on the FIRST
inverted acquisition, not the eventual hang). The static twin of this
check lives in ``reflow_tpu/analysis/locks.py``; the runtime detector
catches orders the AST can't see (callbacks, cross-module call
chains). ``tools/tier1.sh``'s RUN_BENCH leg runs the serve/tier/
failover suites under it.
"""

from __future__ import annotations

import os
import threading
import traceback
import warnings
from typing import Dict, List, Set, Tuple

__all__ = ["LOCK_MONITOR", "LockOrderError", "LockOrderMonitor",
           "NamedLock", "lockcheck_enabled", "named_lock",
           "remote_tunnel_runtime", "note_forced_sync"]


def remote_tunnel_runtime() -> bool:
    """True when the TPU sits behind the axon tunnel runtime (it
    masquerades as platform "tpu"). Detection prefers axon's stable
    ``active_backend()`` accessor; the env sentinel is the fallback (the
    plugin documents it as subject to environ snapshot/restore)."""
    try:
        from axon.register import active_backend
        return active_backend() is not None
    except Exception:  # noqa: BLE001 - no axon installed / API drift
        return os.environ.get("_AXON_REGISTERED") == "1"


_warned = False


def _tunnel_active() -> bool:
    """The computation actually RUNS on the tunnel: the plugin is
    registered AND jax resolved to the tpu backend (the plugin can be
    importable while tests force JAX_PLATFORMS=cpu — no degradation
    happens there, so no warning should either)."""
    if not remote_tunnel_runtime():
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 - backend init failure
        return False


class LockOrderError(RuntimeError):
    """An acquisition would close a cycle in the held-before graph —
    some other code path acquires the same locks in the opposite order,
    so the two paths can deadlock. Raised at acquire time by the
    ``REFLOW_LOCKCHECK=1`` wrapper, before any blocking happens."""


class LockOrderMonitor:
    """Process-global held-before graph over :class:`NamedLock`s.

    Per-thread state is the ordered list of held locks; each acquisition
    of ``B`` while holding ``A`` merges the edge ``A -> B`` (with a
    sample acquisition stack for diagnostics) into the graph. A new
    edge whose reverse direction is already reachable raises
    :class:`LockOrderError` carrying both acquisition stacks. Same-name
    edges (two *instances* of one named lock nested in a thread) count
    as cycles too: name-level order is the invariant the static pass
    checks, so instance-level inversions must not hide behind a shared
    name — give interacting instances distinct names.

    The monitor's own mutex is a leaf by construction: no callback or
    user code ever runs while it is held.
    """

    def __init__(self) -> None:
        # reflow-lint: waive lock-unnamed -- the monitor's own leaf mutex; a NamedLock here would recurse into the monitor
        self._mu = threading.Lock()
        #: name -> set of names acquired while it was held
        self._edges: Dict[str, Set[str]] = {}
        #: (a, b) -> sample stack (list of "file:line in fn" strings)
        self._sites: Dict[Tuple[str, str], List[str]] = {}
        self._tls = threading.local()
        self.cycles_checked = 0

    # -- per-thread held list ----------------------------------------------

    def _held(self) -> List[list]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held  # entries: [lock, recursion_count]

    def held_names(self) -> List[str]:
        return [e[0].name for e in self._held()]

    @staticmethod
    def _stack(limit: int = 6) -> List[str]:
        # drop the monitor/wrapper frames at the tail; keep callers
        frames = traceback.extract_stack(limit=limit + 3)[:-3]
        return [f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
                for f in frames]

    # -- graph maintenance -------------------------------------------------

    def _reachable(self, src: str, dst: str) -> bool:
        # DFS under self._mu: is dst reachable from src?
        seen = {src}
        stack = [src]
        while stack:
            for nxt in self._edges.get(stack.pop(), ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    def on_acquire(self, lock: "NamedLock") -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:      # RLock re-entry: no new edges
                entry[1] += 1
                return
        stack = self._stack()
        with self._mu:
            for entry in held:
                a, b = entry[0].name, lock.name
                if a == b:
                    # a DIFFERENT instance of the same name (identity
                    # re-entry returned above): name-level order can't
                    # arbitrate instance order, so this is a cycle —
                    # interacting instances need distinct names
                    raise LockOrderError(
                        f"lock-order cycle: acquiring a second "
                        f"{b!r} instance while one is already held "
                        f"({' <- '.join(stack)}); give interacting "
                        f"instances distinct named_lock() names")
                if b in self._edges.get(a, ()):
                    continue
                self.cycles_checked += 1
                if self._reachable(b, a):
                    first = self._sites.get(
                        (b, a)) or self._sites.get((b, b)) or []
                    raise LockOrderError(
                        f"lock-order cycle: acquiring {b!r} while "
                        f"holding {a!r}, but {b!r} -> {a!r} is already "
                        f"an established order.\n"
                        f"  this acquisition: {' <- '.join(stack)}\n"
                        f"  established at:   {' <- '.join(first)}\n"
                        f"  held here: {[e[0].name for e in held]}")
                self._edges.setdefault(a, set()).add(b)
                self._sites.setdefault((a, b), stack)
        held.append([lock, 1])

    def on_release(self, lock: "NamedLock", *, all_levels: bool = False,
                   ) -> int:
        """Pop one recursion level (or the whole entry for a
        Condition's ``_release_save``); returns the popped count."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                if all_levels or held[i][1] <= 1:
                    return held.pop(i)[1]
                held[i][1] -= 1
                return 1
        return 0  # release of a lock acquired before lockcheck wrapped

    # -- introspection (tests, reports) ------------------------------------

    def edges(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._sites.clear()


#: the process-wide monitor every REFLOW_LOCKCHECK=1 NamedLock reports to
LOCK_MONITOR = LockOrderMonitor()


class NamedLock:
    """A named ``threading.Lock``/``RLock`` wrapper that reports every
    acquisition to a :class:`LockOrderMonitor`. Condition-compatible:
    ``threading.Condition(named_lock(...))`` works because the wrapper
    implements ``_release_save`` / ``_acquire_restore`` / ``_is_owned``
    (delegating recursion bookkeeping to the inner RLock when there is
    one, and keeping the monitor's held list balanced across a
    ``Condition.wait``)."""

    __slots__ = ("name", "_inner", "_mon")

    def __init__(self, name: str, inner, mon: LockOrderMonitor) -> None:
        self.name = name
        self._inner = inner
        self._mon = mon

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # order violations are checked BEFORE blocking on the inner
        # lock: a true inversion must raise, not deadlock
        self._mon.on_acquire(self)
        try:
            got = self._inner.acquire(blocking, timeout)
        except BaseException:
            self._mon.on_release(self)
            raise
        if not got:
            self._mon.on_release(self)
        return got

    def release(self) -> None:
        self._mon.on_release(self)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition protocol ------------------------------------------------

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        return any(e[0] is self for e in self._mon._held())

    def _release_save(self):
        count = self._mon.on_release(self, all_levels=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return (count, inner._release_save())
        inner.release()
        return (count, None)

    def _acquire_restore(self, state) -> None:
        count, inner_state = state
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(inner_state)
        else:
            inner.acquire()
        # the wait dropped the lock, so the thread's other held locks
        # (if any) already have their edges recorded; restore the entry
        # without re-walking them (re-recording would be harmless but
        # this is the wait hot path)
        self._mon._held().append([self, max(1, count)])

    def __repr__(self) -> str:
        return f"NamedLock({self.name!r}, {self._inner!r})"


def lockcheck_enabled() -> bool:
    """True when the runtime lock-order detector is on. Read per call
    so a test can construct wrapped locks explicitly; module-level
    locks capture the value at import, so set ``REFLOW_LOCKCHECK=1``
    at process start for full coverage."""
    from reflow_tpu.utils.config import env_flag

    return env_flag("REFLOW_LOCKCHECK")


def named_lock(name: str, *, reentrant: bool = False):
    """The ONE way this project creates a lock on a concurrent path.

    Returns a plain ``threading.Lock`` / ``threading.RLock`` when
    ``REFLOW_LOCKCHECK`` is off (zero overhead, the production shape),
    or a monitor-wrapped :class:`NamedLock` when on. ``name`` is the
    node in the held-before graph; instances that can interact within
    one thread must use distinct names (e.g. ``serve.replica.<n>``).
    The static lint's lock pass keys its graph on the same names."""
    # reflow-lint: waive lock-unnamed -- named_lock() IS the factory; this is the inner lock it wraps
    inner = threading.RLock() if reentrant else threading.Lock()
    if not lockcheck_enabled():
        return inner
    return NamedLock(name, inner, LOCK_MONITOR)


def note_forced_sync(context: str) -> None:
    """Record a mid-stream device readback; warn ONCE per process when
    the runtime is a tunnel (where the first readback permanently
    degrades dispatch). Cheap no-op everywhere else."""
    global _warned
    if _warned:
        return
    _warned = True
    if _tunnel_active():
        warnings.warn(
            f"first device readback ({context}) on a tunnel-attached TPU "
            f"runtime: the runtime now stays in degraded synchronous "
            f"dispatch (~0.1s per sync) for the rest of the process. For "
            f"throughput, stream ticks with tick(sync=False) and call "
            f"block()/read_table once per batch — see docs/guide.md "
            f"('Streaming and the tunnel runtime').",
            stacklevel=3)
