"""Auxiliary subsystems (SURVEY.md §5): checkpoint/resume, metrics,
fault-tolerant ingestion."""

from reflow_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from reflow_tpu.utils.metrics import MetricsSummary, summarize

__all__ = ["save_checkpoint", "load_checkpoint", "summarize",
           "MetricsSummary"]
