"""Durable checkpoint/resume (SURVEY.md §5).

The durable state of an incremental dataflow is small and well-defined:
(per-node operator state, tick counter, materialized sink views). The
checkpoint records ``tick`` so the host driver knows where its cursor
was. On its own, a checkpoint covers ingestion only *at* save points —
everything pushed since the last save is lost on a crash unless the
upstream replays it. ``reflow_tpu.wal`` closes that window: a WAL-backed
scheduler (``wal.DurableScheduler``) logs every accepted batch, the save
records the log replay position (``"wal_pos"``) and truncates the sealed
segments it covers, and ``wal.recovery.recover`` restores checkpoint +
tail for exactly-once ingestion across process death.

Two serialization paths behind one API:

- **array states** (TpuExecutor / ShardedTpuExecutor): the state pytree is
  saved via ``orbax.checkpoint`` — zarr-sharded, async-capable, and on
  restore each leaf is loaded *directly into the executor's current
  sharding* (the live state tree provides the abstract target), so a
  key-sharded table comes back key-sharded without a host gather.
- **host states** (CpuExecutor's dict/Counter oracle state): pickle.

Layout: ``<dir>/meta.pkl`` (tick, sink views, host states) and
``<dir>/states/`` (orbax tree of the array states, if any).

Bounded history (incremental checkpoints)
-----------------------------------------
A full checkpoint is O(state) bytes *every* save, which caps how often
an operator can afford to take one — and the WAL only truncates at
saves, so rare saves mean O(history) replay tails. :class:`CheckpointChain`
fixes the cost side: it manages a directory of one **full** checkpoint
plus a chain of **delta** elements (per-source state snapshots of only
what changed since the previous element, keyed by the macro-tick
horizon), linked by a ``chain.json`` manifest. ``load_checkpoint`` on a
chain directory restores base + deltas in order; a broken link
mid-chain fails loud, while a torn/partial *final* delta falls back one
chain element — exactly the WAL's torn-tail stance. To make that
fallback always recoverable, WAL truncation lags one element: a delta
save truncates only up to the *previous* element's anchor, so the log
still covers the newest element's window if its file is lost.

Delta file framing mirrors the WAL: ``RFCKD001`` magic, then one
``[u32 len][u32 crc32]`` pickled payload — torn bytes are detected the
same way a torn WAL record is.

Tiled elements (``REFLOW_TILE_BYTES`` > 0, docs/guide.md 'Tiled
maintenance')
-------------------------------------------------------------------
A monolithic element pickles the whole keyed state in one payload —
O(state) peak on both the writer and any restoring reader. Above the
tile budget, keyed state (sink views plus host states that are plain
``dict``/``Counter`` maps) is split by key-range tile
(:mod:`reflow_tpu.utils.tiles`): a full checkpoint writes
``tiles/t<tick>-NNN.ckt`` files (``RFCKT001`` magic + one CRC frame
each) next to a small ``meta.pkl`` that lists them, and a delta element
becomes a multi-frame ``.ckd`` — frame 0 carries the small fields plus
a ``"tiles"`` count, then one CRC frame per tile. Restore streams one
frame at a time (peak extra allocation = the largest single frame,
tracked in :data:`TILE_IO_STATS`); a torn frame anywhere in a delta
keeps the ``torn=True`` contract, so a torn *final* tiled delta still
falls back exactly one chain element. Non-map host states and array
pytrees stay monolithic in the residual payload.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import zlib
from typing import Dict, List, Optional

__all__ = ["save_checkpoint", "load_checkpoint", "meta_digest",
           "checkpoint_exists", "CheckpointChain", "CheckpointError",
           "load_chain", "read_chain_manifest", "chain_head_wal_pos",
           "CHAIN_MANIFEST", "CHAIN_SCHEMA"]

CHAIN_MANIFEST = "chain.json"
CHAIN_SCHEMA = "reflow.ckpt_chain/1"
_DELTA_MAGIC = b"RFCKD001"
_DELTA_HEADER = struct.Struct("<II")
_TILE_MAGIC = b"RFCKT001"
_TILE_DIR = "tiles"

#: process-wide high-water marks of tiled checkpoint IO — the largest
#: single frame pickled on a save and unpickled on a restore. The
#: tiles bench asserts both stay under 2x the tile budget; reset with
#: :func:`reset_tile_io_stats` around a measured window.
TILE_IO_STATS = {"writer_peak_frame_bytes": 0,
                 "reader_peak_frame_bytes": 0}


def reset_tile_io_stats() -> None:
    TILE_IO_STATS["writer_peak_frame_bytes"] = 0
    TILE_IO_STATS["reader_peak_frame_bytes"] = 0


def _tile_budget() -> int:
    from reflow_tpu.utils.config import env_int

    return int(env_int("REFLOW_TILE_BYTES") or 0)


class CheckpointError(RuntimeError):
    """A checkpoint/chain element is unreadable or the chain is
    inconsistent (broken parent link, horizon mismatch)."""

    def __init__(self, msg: str, *, torn: bool = False):
        super().__init__(msg)
        #: True when the element's *bytes* are torn/short/corrupt (the
        #: WAL-torn-tail analogue) as opposed to a structural link break
        self.torn = torn


def checkpoint_exists(path: Optional[str]) -> bool:
    """True when ``path`` holds a restorable checkpoint — either a
    legacy full checkpoint (``meta.pkl``) or a chain directory
    (``chain.json``)."""
    if path is None:
        return False
    return (os.path.exists(os.path.join(path, CHAIN_MANIFEST))
            or os.path.exists(os.path.join(path, "meta.pkl")))


def _split_states(states: Dict[int, object]):
    """Partition per-node states into (array pytrees, host objects)."""
    import jax

    arr, host = {}, {}
    for nid, st in states.items():
        leaves = jax.tree.leaves(st) if isinstance(st, dict) else []
        if leaves and all(isinstance(v, jax.Array) for v in leaves):
            arr[str(nid)] = st
        else:
            host[nid] = st
    return arr, host


def meta_digest(tick: int, seen_batch_ids) -> int:
    """64-bit digest of the host-side meta that multi-controller saves
    assume SPMD-identical (tick counter + dedup window, in insertion
    order — order divergence is divergence)."""
    import hashlib

    h = hashlib.sha256(repr((tick, list(seen_batch_ids))).encode())
    return int.from_bytes(h.digest()[:8], "big")


# -- key-range tiled elements ----------------------------------------------


def _splittable(st) -> bool:
    """Only plain key->value maps split by key tile; subclasses with
    extra invariants (and non-map states) stay in the residual blob."""
    from collections import Counter

    return type(st) in (dict, Counter)


def _cls_name(st) -> str:
    return "Counter" if type(st).__name__ == "Counter" else "dict"


def _make_cls(name: str):
    from collections import Counter

    return Counter if name == "Counter" else dict


def _plan_keyed(maps: List, budget: int):
    """Tile plan over the union of several key->value maps, or None
    when everything fits one tile (caller stays monolithic)."""
    from reflow_tpu.utils import tiles as _t

    bucket_bytes = [0.0] * _t.N_BUCKETS
    for m in maps:
        for k, v in m.items():
            bucket_bytes[_t.bucket_of(k)] += _t.approx_row_bytes(k, v)
    plan = _t.plan_tiles(bucket_bytes, budget)
    return plan if len(plan) > 1 else None


def _slice_by_tile(maps: Dict, plan) -> List[Dict]:
    """Per-tile slices of several key->value maps in ONE pass — one
    ``bucket_of`` per key. Slicing per tile would rescan every map
    once per tile (quadratic in the tile count: a 64-tile save of an
    8k-key view costs 512k key hashes instead of 8k). The slices hold
    references into the already-resident source maps, so this buys
    time, not memory — the tile bound is on pickled frame bytes."""
    from reflow_tpu.utils import tiles as _t

    tile_of = [0] * _t.N_BUCKETS
    for i, (lo, hi) in enumerate(plan):
        for b in range(lo, hi):
            tile_of[b] = i
    out: List[Dict] = [{name: {} for name in maps} for _ in plan]
    for name, m in maps.items():
        for k, v in m.items():
            out[tile_of[_t.bucket_of(k)]][name][k] = v
    return out


def _write_tile_file(path: str, payload: dict) -> int:
    body = pickle.dumps(payload)
    TILE_IO_STATS["writer_peak_frame_bytes"] = max(
        TILE_IO_STATS["writer_peak_frame_bytes"], len(body))
    frame = (_TILE_MAGIC + _DELTA_HEADER.pack(len(body),
                                              zlib.crc32(body)) + body)
    with open(path, "wb") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    return len(frame)


def _read_tile_file(path: str) -> dict:
    """One tiled-checkpoint frame; raises :class:`CheckpointError`
    (``torn=True``) on missing/short/CRC-torn bytes — a torn base tile
    fails the restore loud (the chain base has no fallback)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointError(f"{path}: missing checkpoint tile ({e})",
                              torn=True) from e
    if data[:len(_TILE_MAGIC)] != _TILE_MAGIC:
        raise CheckpointError(f"{path}: bad tile magic "
                              f"{data[:len(_TILE_MAGIC)]!r}", torn=True)
    off = len(_TILE_MAGIC)
    if off + _DELTA_HEADER.size > len(data):
        raise CheckpointError(f"{path}: truncated tile header",
                              torn=True)
    length, crc = _DELTA_HEADER.unpack_from(data, off)
    body = data[off + _DELTA_HEADER.size: off + _DELTA_HEADER.size
                + length]
    if len(body) < length or zlib.crc32(body) != crc:
        raise CheckpointError(f"{path}: torn checkpoint tile "
                              f"({len(body)}/{length} bytes)", torn=True)
    TILE_IO_STATS["reader_peak_frame_bytes"] = max(
        TILE_IO_STATS["reader_peak_frame_bytes"], len(body))
    try:
        return pickle.loads(body)
    except Exception as e:  # noqa: BLE001 - framed+CRC-clean yet unloadable
        raise CheckpointError(f"{path}: unpicklable tile payload "
                              f"({e})", torn=True) from e


def _write_full_tiles(path: str, sched, host: Dict, budget: int,
                      crash=None) -> Optional[dict]:
    """Write the keyed state of a full checkpoint as per-tile files.
    Returns the ``meta["tiled"]`` descriptor, or None when one tile
    would cover everything (caller stays monolithic). Tile files are
    named by tick so a crashed save never clobbers the files the
    current ``meta.pkl`` references; superseded files are reaped by
    the caller after the new meta lands."""
    import time

    from reflow_tpu.obs import trace as _trace

    views = {name: c for name, c in sched.sink_views.items()}
    split_host = {nid: st for nid, st in host.items()
                  if _splittable(st)}
    plan = _plan_keyed(list(views.values()) + list(split_host.values()),
                       budget)
    if plan is None:
        return None
    tile_dir = os.path.join(path, _TILE_DIR)
    os.makedirs(tile_dir, exist_ok=True)
    view_slices = _slice_by_tile(views, plan)
    host_slices = _slice_by_tile(split_host, plan)
    files: List[str] = []
    peak = 0
    for t, (lo, hi) in enumerate(plan):
        t0 = time.perf_counter()
        payload = {
            "range": [lo, hi],
            "views": view_slices[t],
            "host": host_slices[t],
        }
        rel = os.path.join(_TILE_DIR,
                           f"t{sched._tick:08d}-{t:03d}.ckt")
        nbytes = _write_tile_file(os.path.join(path, rel), payload)
        peak = max(peak, nbytes)
        files.append(rel)
        if crash is not None:
            crash.point("ckpt_tile_full_append")
        if _trace.ENABLED:
            _trace.evt("ckpt_tile", t0, time.perf_counter() - t0,
                       track="checkpoint",
                       args={"tile": t, "of": len(plan),
                             "kind": "full", "bytes": nbytes})
    return {
        "n": len(plan),
        "budget": budget,
        "files": files,
        "peak_tile_bytes": peak,
        "views_cls": {name: "Counter" for name in views},
        "host_cls": {nid: _cls_name(st)
                     for nid, st in split_host.items()},
    }


def save_checkpoint(sched, path: str, *, truncate: bool = True,
                    crash=None) -> Dict:
    """Multi-controller: every process calls this collectively with the
    same (shared-filesystem) path — orbax writes each process's
    addressable shards of the global arrays; the host-side meta (tick
    counter, sink views, dedup set) is written by process 0 alone.
    That meta MUST be SPMD-identical across processes (use
    ``scheduler.SourceCursor`` so batch ids are identical by
    construction); rather than assume it, the save VERIFIES it with one
    digest allgather and fails loudly on divergence — a process whose
    dedup window drifted would otherwise silently restore the wrong
    exactly-once horizon (VERDICT r4 #4a)."""
    import jax

    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        mine = np.uint64(meta_digest(sched._tick, sched._seen_batch_ids))
        digests = np.asarray(multihost_utils.process_allgather(mine))
        if len(set(int(x) for x in digests.ravel())) != 1:
            raise RuntimeError(
                "checkpoint meta diverged across controllers (tick "
                "counter or batch-id dedup window differs between "
                "processes); mint batch ids from a shared "
                "scheduler.SourceCursor so every process dedups "
                "identically")
    os.makedirs(path, exist_ok=True)
    arr, host = _split_states(sched.executor.states)
    meta = {
        "tick": sched._tick,
        "sink_views": {name: dict(c) for name, c in sched.sink_views.items()},
        "seen_batch_ids": dict(sched._seen_batch_ids),
        # accepted-but-unticked batches: without these, a crash between
        # push and tick would lose deltas whose ids the dedup set already
        # claims (exactly-once would silently become at-most-once)
        "pending": {nid: list(batches)
                    for nid, batches in sched._pending.items()},
        "host_states": pickle.dumps(host),
        "has_array_states": bool(arr),
    }
    budget = _tile_budget()
    if budget > 0 and jax.process_index() == 0:
        tiled = _write_full_tiles(path, sched, host, budget,
                                  crash=crash)
        if tiled is not None:
            # keyed state lives in the tile files; meta keeps only the
            # residual (non-map host states) and the descriptor
            meta["sink_views"] = {}
            meta["host_states"] = pickle.dumps(
                {nid: st for nid, st in host.items()
                 if not _splittable(st)})
            meta["tiled"] = tiled
    # a WAL-backed scheduler (wal/durable.py): everything the log holds
    # up to now is covered by this checkpoint. Rotate so the whole
    # covered history sits in sealed segments, record the fresh
    # segment's start as the replay position, and drop the sealed
    # segments once the save has fully landed (never before — a failed
    # save must leave the tail replayable).
    wal = getattr(sched, "wal", None)
    if wal is not None:
        wal.sync()
        wal.rotate()
        meta["wal_pos"] = tuple(wal.position())
        wal.append({"kind": "ckpt", "tick": sched._tick,
                    "path": os.path.abspath(path)})
    if jax.process_index() == 0:
        if meta.get("tiled") is not None:
            # the tiled meta names its tile files: land it atomically,
            # then reap files no meta references any more
            mtmp = os.path.join(path, "meta.pkl.tmp")
            with open(mtmp, "wb") as f:
                pickle.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, os.path.join(path, "meta.pkl"))
            live = set(meta["tiled"]["files"])
            tile_dir = os.path.join(path, _TILE_DIR)
            for fname in os.listdir(tile_dir):
                if os.path.join(_TILE_DIR, fname) not in live:
                    try:
                        os.remove(os.path.join(tile_dir, fname))
                    except OSError:
                        pass
        else:
            with open(os.path.join(path, "meta.pkl"), "wb") as f:
                pickle.dump(meta, f)
    if arr:
        import orbax.checkpoint as ocp

        ckpt = ocp.StandardCheckpointer()
        ckpt.save(os.path.join(os.path.abspath(path), "states"), arr,
                  force=True)
        ckpt.wait_until_finished()
    if wal is not None and truncate:
        from reflow_tpu.wal.log import LogPosition

        wal.truncate_until(LogPosition(*meta["wal_pos"]))
    return meta


def load_checkpoint(sched, path: str) -> Dict:
    """Restore into a scheduler whose graph/executor match the saved one.
    ``path`` may be a legacy full checkpoint directory (``meta.pkl``) or
    a :class:`CheckpointChain` directory (``chain.json``) — a chain is
    restored base-then-deltas. Returns the checkpoint meta dict
    (``wal.recovery.recover`` reads the recorded WAL replay position,
    ``"wal_pos"``, from it)."""
    if os.path.exists(os.path.join(path, CHAIN_MANIFEST)):
        return load_chain(sched, path)
    return _load_full(sched, path)


def _load_full(sched, path: str) -> Dict:
    """The legacy single-directory restore (meta.pkl + orbax states)."""
    from collections import Counter

    try:
        with open(os.path.join(path, "meta.pkl"), "rb") as f:
            meta = pickle.load(f)
    except (OSError, pickle.UnpicklingError, EOFError) as e:
        raise CheckpointError(f"{path}: unreadable checkpoint meta "
                              f"({e})", torn=True) from e
    sched._tick = meta["tick"]
    sched._seen_batch_ids = dict(meta["seen_batch_ids"])
    sched._pending.clear()
    for nid, batches in meta["pending"].items():
        sched._pending[nid].extend(batches)
    for name, d in meta["sink_views"].items():
        sched.sink_views[name] = Counter(d)
    states = dict(pickle.loads(meta["host_states"]))
    tiled = meta.get("tiled")
    if tiled is not None:
        # keyed state streams back one tile frame at a time — peak
        # extra allocation is the largest single frame, not O(state)
        for name in tiled["views_cls"]:
            sched.sink_views[name] = Counter()
        acc: Dict = {nid: {} for nid in tiled["host_cls"]}
        for rel in tiled["files"]:
            payload = _read_tile_file(os.path.join(path, rel))
            for name, kv in payload["views"].items():
                sched.sink_views[name].update(kv)
            for nid, kv in payload["host"].items():
                acc[nid].update(kv)
        for nid, cls in tiled["host_cls"].items():
            states[nid] = _make_cls(cls)(acc[nid])
    if meta["has_array_states"]:
        import orbax.checkpoint as ocp

        live_arr, _ = _split_states(sched.executor.states)
        if not live_arr:
            raise ValueError(
                "checkpoint holds array states but the bound executor has "
                "none — restore onto the same executor kind it was saved "
                "from")
        ckpt = ocp.StandardCheckpointer()
        restored = ckpt.restore(
            os.path.join(os.path.abspath(path), "states"), live_arr)
        for sid, st in restored.items():
            states[int(sid)] = st
    sched.executor.states = states
    # arena occupancy (rcount) and the sticky overflow flag travel inside
    # the checkpointed state pytree itself; the in-program high-water
    # check (lax.cond compaction in join_core) needs no host-side tracker
    # reconstruction after restore. Derived caches keyed to state content
    # (the linear fixpoint's sorted-arena CSR) must drop, though: two
    # lineages can share a (gen, rcount) pair over different arena rows,
    # so the in-program validity predicate alone cannot see the swap.
    sched.executor.on_states_replaced()
    return meta


# -- incremental checkpoint chain ------------------------------------------


def read_chain_manifest(root: str) -> Optional[dict]:
    """The chain manifest as a dict, or None when ``root`` is not a
    chain directory. Raises :class:`CheckpointError` on unparseable
    JSON (a half-written manifest is a broken chain, not an empty one —
    the flip is atomic, so this only happens under real corruption)."""
    path = os.path.join(root, CHAIN_MANIFEST)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: unreadable chain manifest "
                              f"({e})") from e


def chain_head_wal_pos(root: str):
    """The newest chain element's recorded WAL anchor as a
    ``(segment, offset)`` tuple, or None (no chain / no WAL)."""
    m = read_chain_manifest(root)
    if m is None or m.get("wal_pos") is None:
        return None
    return tuple(m["wal_pos"])


def _write_delta_file(path: str, payload: dict) -> int:
    body = pickle.dumps(payload)
    frame = (_DELTA_MAGIC + _DELTA_HEADER.pack(len(body),
                                               zlib.crc32(body)) + body)
    with open(path, "wb") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    return len(frame)


def _scan_delta_frames(path: str) -> List[int]:
    """Validate every frame of a delta element (magic, lengths, CRCs)
    WITHOUT keeping payloads resident; returns the byte offset of each
    frame header. Raises :class:`CheckpointError` (``torn=True``) on
    any torn byte — validation runs before a single frame is applied,
    so a torn element never half-mutates the restoring scheduler."""
    try:
        f = open(path, "rb")
    except OSError as e:
        raise CheckpointError(f"{path}: missing delta element ({e})",
                              torn=True) from e
    with f:
        magic = f.read(len(_DELTA_MAGIC))
        if magic != _DELTA_MAGIC:
            raise CheckpointError(f"{path}: bad delta magic "
                                  f"{magic!r}", torn=True)
        size = os.fstat(f.fileno()).st_size
        off = len(_DELTA_MAGIC)
        offsets: List[int] = []
        while off < size:
            hdr = f.read(_DELTA_HEADER.size)
            if len(hdr) < _DELTA_HEADER.size:
                raise CheckpointError(f"{path}: truncated delta "
                                      f"header", torn=True)
            length, crc = _DELTA_HEADER.unpack(hdr)
            body = f.read(length)
            if len(body) < length:
                raise CheckpointError(
                    f"{path}: truncated delta payload ({len(body)}/"
                    f"{length} bytes)", torn=True)
            if zlib.crc32(body) != crc:
                raise CheckpointError(f"{path}: delta CRC mismatch",
                                      torn=True)
            offsets.append(off)
            off += _DELTA_HEADER.size + length
    if not offsets:
        raise CheckpointError(f"{path}: empty delta element",
                              torn=True)
    return offsets


def _read_frame_at(f, path: str, off: int) -> dict:
    """One already-CRC-validated frame from an open element file."""
    f.seek(off)
    length, _crc = _DELTA_HEADER.unpack(f.read(_DELTA_HEADER.size))
    body = f.read(length)
    TILE_IO_STATS["reader_peak_frame_bytes"] = max(
        TILE_IO_STATS["reader_peak_frame_bytes"], len(body))
    try:
        return pickle.loads(body)
    except Exception as e:  # noqa: BLE001 - framed+CRC-clean yet unloadable
        raise CheckpointError(f"{path}: unpicklable delta payload "
                              f"({e})", torn=True) from e


def _read_delta_file(path: str) -> dict:
    """Parse one framed delta element into a single merged payload
    (non-streaming convenience — tools and inspection; the chain
    loader streams instead). Raises :class:`CheckpointError`
    (``torn=True``) on missing/short/CRC-torn bytes — the condition
    the chain loader answers by falling back one element."""
    offsets = _scan_delta_frames(path)
    with open(path, "rb") as f:
        payload = _read_frame_at(f, path, offsets[0])
        ntiles = int(payload.get("tiles", 0) or 0)
        if ntiles != len(offsets) - 1:
            raise CheckpointError(
                f"{path}: tiled delta frame count mismatch "
                f"({len(offsets) - 1}/{ntiles} tile frames)", torn=True)
        for off in offsets[1:]:
            tp = _read_frame_at(f, path, off)
            for sink, kv in tp["view_deltas"].items():
                payload.setdefault("view_deltas", {}).setdefault(
                    sink, {}).update(kv)
            for nid, ent in tp["host_states"].items():
                cur = payload.setdefault("_tiled_host", {}).setdefault(
                    nid, (ent["cls"], {}))
                cur[1].update(ent["items"])
        for nid, (cls, items) in payload.pop("_tiled_host", {}).items():
            payload["host_states"][nid] = pickle.dumps(
                _make_cls(cls)(items))
    return payload


def _numpyify(tree):
    import jax
    import numpy as np

    return jax.tree.map(lambda a: np.asarray(a), tree)


def _apply_delta(sched, payload: dict) -> None:
    from collections import Counter

    sched._tick = payload["tick"]
    for sink, kv in payload["view_deltas"].items():
        view = sched.sink_views.get(sink)
        if view is None:
            view = sched.sink_views[sink] = Counter()
        for k, v in kv.items():
            if v is None:
                view.pop(k, None)
            else:
                view[k] = v
    states = sched.executor.states
    for nid, blob in payload["host_states"].items():
        states[nid] = pickle.loads(blob)
    if payload.get("array_states"):
        import jax

        for nid, np_tree in payload["array_states"].items():
            live = states.get(nid)
            if live is not None and any(
                    isinstance(leaf, jax.Array)
                    for leaf in jax.tree.leaves(live)):
                # restore each leaf directly into the live leaf's
                # sharding (same stance as the orbax full-restore path)
                states[nid] = jax.tree.map(
                    lambda np_v, lv: jax.device_put(
                        np_v, lv.sharding) if isinstance(lv, jax.Array)
                    else np_v,
                    np_tree, live)
            else:
                states[nid] = np_tree
    for b in payload["ids_added"]:
        sched._seen_batch_ids[b] = None
    for _ in range(payload["ids_dropped"]):
        if not sched._seen_batch_ids:
            break
        sched._seen_batch_ids.pop(next(iter(sched._seen_batch_ids)))
    sched._pending.clear()
    for nid, batches in payload["pending"].items():
        sched._pending[nid].extend(batches)


def _apply_delta_tiles(sched, f, path: str, offsets: List[int]) -> None:
    """Stream a tiled delta's tile frames into the scheduler: view
    deltas merge per frame (tile key ranges are disjoint), changed
    splittable host states accumulate their slices and replace the
    live state whole — the same replace semantics the monolithic
    delta's pickled blob has."""
    from collections import Counter

    acc: Dict = {}
    for off in offsets:
        tp = _read_frame_at(f, path, off)
        for sink, kv in tp["view_deltas"].items():
            view = sched.sink_views.get(sink)
            if view is None:
                view = sched.sink_views[sink] = Counter()
            for k, v in kv.items():
                if v is None:
                    view.pop(k, None)
                else:
                    view[k] = v
        for nid, ent in tp["host_states"].items():
            cur = acc.setdefault(nid, (ent["cls"], {}))
            cur[1].update(ent["items"])
    states = sched.executor.states
    for nid, (cls, items) in acc.items():
        states[nid] = _make_cls(cls)(items)


def load_chain(sched, root: str) -> Dict:
    """Restore a :class:`CheckpointChain` directory: the base full
    checkpoint, then every delta element in manifest order. A broken
    link anywhere mid-chain (missing/corrupt element, parent or horizon
    mismatch) fails loud; a torn/partial *final* delta falls back to
    the previous chain element — the WAL still covers its window
    because truncation lags one element. Returns a meta dict whose
    ``"wal_pos"`` is the last successfully applied element's anchor."""
    manifest = read_chain_manifest(root)
    if manifest is None:
        raise CheckpointError(f"{root}: no chain manifest")
    base = manifest["base"]
    meta = _load_full(sched, os.path.join(root, base))
    wal_pos = meta.get("wal_pos")
    prev_name = base
    applied = 0
    fallback = None
    deltas: List[str] = list(manifest.get("deltas", []))
    for i, dname in enumerate(deltas):
        dpath = os.path.join(root, dname)
        try:
            # whole-file CRC validation first (bounded memory), THEN
            # frame-by-frame apply: a torn element — torn in ANY tile
            # frame — is detected before a single byte is applied, so
            # the final-element fallback leaves clean state
            offsets = _scan_delta_frames(dpath)
            with open(dpath, "rb") as df:
                payload = _read_frame_at(df, dpath, offsets[0])
                ntiles = int(payload.get("tiles", 0) or 0)
                if ntiles != len(offsets) - 1:
                    raise CheckpointError(
                        f"{dpath}: tiled delta frame count mismatch "
                        f"({len(offsets) - 1}/{ntiles} tile frames)",
                        torn=True)
                if payload.get("parent") != prev_name \
                        or payload.get("base_tick") != sched._tick:
                    raise CheckpointError(
                        f"{root}/{dname}: broken chain link (parent "
                        f"{payload.get('parent')!r} @ tick "
                        f"{payload.get('base_tick')!r}, expected "
                        f"{prev_name!r} @ tick {sched._tick})")
                _apply_delta(sched, payload)
                if ntiles:
                    _apply_delta_tiles(sched, df, dpath, offsets[1:])
        except CheckpointError as e:
            if e.torn and i == len(deltas) - 1:
                # torn tail of the chain: fall back one element, the
                # WAL tail (truncation lagged one save) replays the gap
                fallback = str(e)
                break
            raise
        if payload.get("wal_pos") is not None:
            wal_pos = tuple(payload["wal_pos"])
        prev_name = dname
        applied += 1
    sched.executor.on_states_replaced()
    out = {
        "tick": sched._tick,
        "wal_pos": wal_pos,
        "seen_batch_ids": dict(sched._seen_batch_ids),
        "chain": {"base": base, "deltas_applied": applied,
                  "deltas_total": len(deltas), "fallback": fallback},
    }
    if wal_pos is None:
        out.pop("wal_pos")
    return out


class CheckpointChain:
    """Writer side of the bounded-history checkpoint chain.

    ``save(sched)`` takes a cheap **delta** element (only the sinks,
    per-source states, dedup-window entries and pending buffers that
    changed since the previous element), promoting to a **full**
    checkpoint every ``delta_every``-th save (or when forced with
    ``full=True``; the very first save is always full). Every save
    follows the WAL choreography of ``save_checkpoint`` — sync, rotate,
    record the fresh segment start as the element's anchor — and then
    truncates the log up to the *previous* element's anchor (lag-one:
    a torn final delta must leave its window replayable from the WAL).

    The atomic commit point of every save is the ``chain.json``
    manifest flip (write-tmp + fsync + ``os.replace``): a crash before
    the flip leaves the previous chain fully restorable, a crash after
    it leaves the new one. ``crash`` is a
    :class:`~reflow_tpu.utils.faults.CrashInjector` seam hook
    (``ckpt_full_before_flip`` / ``ckpt_delta_before_flip`` /
    ``ckpt_delta_after_flip``, plus the per-tile seams
    ``ckpt_tile_full_append`` / ``ckpt_tile_append`` when
    ``REFLOW_TILE_BYTES`` tiles the elements) for the differential
    crash tests."""

    def __init__(self, root: str, *, delta_every: Optional[int] = None,
                 crash=None):
        from reflow_tpu.utils.config import env_int

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.delta_every = (delta_every if delta_every is not None
                            else env_int("REFLOW_CKPT_DELTA_EVERY"))
        self._crash = crash
        self.saves = 0
        self.fulls = 0
        self.deltas = 0
        self.delta_bytes = 0
        #: tile shape of the newest element (0 = monolithic) and the
        #: largest tile frame any save of this chain ever pickled
        self.tile_count = 0
        self.peak_tile_bytes = 0
        self._metric_names: List = []
        #: what the previous element looked like, for diffing; None
        #: forces the next save to be full (fresh writer, fresh chain)
        self._shadow: Optional[dict] = None

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- shadow bookkeeping ------------------------------------------------

    @staticmethod
    def _classify_states(states: Dict):
        """(host {nid: pickled bytes}, array {nid: numpy pytree}) —
        both forms are digestable/diffable host-side."""
        import jax

        host, arr = {}, {}
        for nid, st in states.items():
            leaves = jax.tree.leaves(st) if isinstance(st, dict) else []
            if leaves and all(isinstance(v, jax.Array) for v in leaves):
                arr[nid] = _numpyify(st)
            else:
                host[nid] = pickle.dumps(st)
        return host, arr

    def _snapshot(self, sched) -> dict:
        host, arr = self._classify_states(sched.executor.states)
        return {
            "tick": sched._tick,
            "views": {name: dict(c)
                      for name, c in sched.sink_views.items()},
            "host": host,
            "arr_blobs": {nid: pickle.dumps(t) for nid, t in arr.items()},
            "arr_trees": arr,
            "ids": dict(sched._seen_batch_ids),
        }

    # -- saves -------------------------------------------------------------

    def _wal_anchor(self, sched):
        """sync+rotate the scheduler's WAL (if any) and return the
        fresh segment start — the element's replay anchor."""
        wal = getattr(sched, "wal", None)
        if wal is None:
            return None
        wal.sync()
        wal.rotate()
        pos = tuple(wal.position())
        wal.append({"kind": "ckpt", "tick": sched._tick,
                    "path": self.root})
        return pos

    def _flip_manifest(self, manifest: dict) -> None:
        path = os.path.join(self.root, CHAIN_MANIFEST)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _truncate_to(self, sched, wal_pos) -> None:
        wal = getattr(sched, "wal", None)
        if wal is None or wal_pos is None:
            return
        from reflow_tpu.wal.log import LogPosition

        wal.truncate_until(LogPosition(*wal_pos))

    def save(self, sched, *, full: Optional[bool] = None) -> dict:
        """Take one chain element; returns an info dict (kind, element
        name, tick horizon, anchor, bytes written)."""
        want_full = (full if full is not None
                     else (self._shadow is None or self.delta_every <= 1
                           or self.saves % self.delta_every == 0))
        if self._shadow is None:
            want_full = True
        info = (self._save_full(sched) if want_full
                else self._save_delta(sched))
        self.saves += 1
        return info

    def _save_full(self, sched) -> dict:
        old = read_chain_manifest(self.root) if os.path.exists(
            os.path.join(self.root, CHAIN_MANIFEST)) else None
        name = f"full-{self.saves:06d}"
        path = os.path.join(self.root, name)
        # truncate=False: the log must stay intact until the manifest
        # names this full as the new chain base — a crash between the
        # save and the flip restores the OLD chain, whose last element
        # still needs its replay tail
        meta = save_checkpoint(sched, path, truncate=False,
                               crash=self._crash)
        tiled = meta.get("tiled")
        self.tile_count = tiled["n"] if tiled else 0
        if tiled:
            self.peak_tile_bytes = max(self.peak_tile_bytes,
                                       tiled["peak_tile_bytes"])
        wal = getattr(sched, "wal", None)
        wal_pos = meta.get("wal_pos") if wal is not None else None
        self._crash_point("ckpt_full_before_flip")
        manifest = {
            "schema": CHAIN_SCHEMA,
            "base": name,
            "deltas": [],
            "horizon": sched._tick,
            "wal_pos": list(wal_pos) if wal_pos is not None else None,
            "saves": self.saves + 1,
        }
        if tiled:
            manifest["tiles"] = {"count": tiled["n"],
                                 "budget": tiled["budget"],
                                 "peak_tile_bytes":
                                     tiled["peak_tile_bytes"]}
        self._flip_manifest(manifest)
        self._truncate_to(sched, wal_pos)
        self._gc(old)
        self._shadow = self._snapshot(sched)
        self._shadow["wal_pos"] = wal_pos
        self._shadow["name"] = name
        self.fulls += 1
        return {"kind": "full", "element": name, "tick": sched._tick,
                "wal_pos": wal_pos}

    def _save_delta(self, sched) -> dict:
        shadow = self._shadow
        host, arr = self._classify_states(sched.executor.states)
        host_changed = {nid: blob for nid, blob in host.items()
                        if shadow["host"].get(nid) != blob}
        arr_changed = {}
        for nid, tree in arr.items():
            blob = pickle.dumps(tree)
            if shadow["arr_blobs"].get(nid) != blob:
                arr_changed[nid] = tree
        view_deltas: Dict[str, Dict] = {}
        for name, c in sched.sink_views.items():
            old = shadow["views"].get(name, {})
            kv = {k: v for k, v in c.items() if old.get(k) != v}
            kv.update({k: None for k in old if k not in c})
            if kv:
                view_deltas[name] = kv
        new_ids = dict(sched._seen_batch_ids)
        added = [b for b in new_ids if b not in shadow["ids"]]
        dropped = len(shadow["ids"]) + len(added) - len(new_ids)
        budget = _tile_budget()
        tile_plan = None
        split_changed: Dict = {}
        if budget > 0:
            for nid in host_changed:
                st = sched.executor.states.get(nid)
                if st is not None and _splittable(st):
                    split_changed[nid] = st
            tile_plan = _plan_keyed(
                list(view_deltas.values()) + list(split_changed.values()),
                budget)
            if tile_plan is None:
                split_changed = {}
        wal_pos = self._wal_anchor(sched)
        payload = {
            "tick": sched._tick,
            "base_tick": shadow["tick"],
            "parent": shadow["name"],
            "view_deltas": view_deltas if tile_plan is None else {},
            "host_states": (host_changed if tile_plan is None else
                            {nid: b for nid, b in host_changed.items()
                             if nid not in split_changed}),
            "array_states": {nid: t for nid, t in arr_changed.items()},
            "ids_added": added,
            "ids_dropped": max(0, dropped),
            "pending": {nid: list(batches)
                        for nid, batches in sched._pending.items()},
            "wal_pos": wal_pos,
        }
        name = f"delta-{self.saves:06d}.ckd"
        if tile_plan is None:
            self.tile_count = 0
            nbytes = _write_delta_file(os.path.join(self.root, name),
                                       payload)
        else:
            payload["tiles"] = len(tile_plan)
            nbytes = self._write_delta_tiles(
                os.path.join(self.root, name), payload, tile_plan,
                view_deltas, split_changed)
            self.tile_count = len(tile_plan)
        self._crash_point("ckpt_delta_before_flip")
        manifest = read_chain_manifest(self.root)
        manifest["deltas"] = list(manifest.get("deltas", [])) + [name]
        manifest["horizon"] = sched._tick
        manifest["wal_pos"] = (list(wal_pos) if wal_pos is not None
                               else None)
        manifest["saves"] = self.saves + 1
        if tile_plan is not None:
            manifest["tiles"] = {"count": len(tile_plan),
                                 "budget": budget,
                                 "peak_tile_bytes":
                                     self.peak_tile_bytes}
        self._flip_manifest(manifest)
        self._crash_point("ckpt_delta_after_flip")
        # lag-one truncation: keep the log back to the PREVIOUS
        # element's anchor, so a torn copy of the element we just wrote
        # falls back one link and replays its window from the WAL
        self._truncate_to(sched, shadow.get("wal_pos"))
        self._shadow = self._snapshot(sched)
        self._shadow["wal_pos"] = wal_pos
        self._shadow["name"] = name
        self.deltas += 1
        self.delta_bytes += nbytes
        return {"kind": "delta", "element": name, "tick": sched._tick,
                "wal_pos": wal_pos, "bytes": nbytes,
                "changed_sources": sorted(
                    list(host_changed) + list(arr_changed))}

    def _write_delta_tiles(self, path: str, header: dict, plan,
                           view_deltas: Dict,
                           split_changed: Dict) -> int:
        """Write a tiled delta element: frame 0 is the small header
        payload, then one CRC frame per key-range tile. One tile's
        slice is pickled at a time — writer peak is the largest tile
        frame, not the whole delta."""
        import time

        from reflow_tpu.obs import trace as _trace

        peak = 0
        view_slices = _slice_by_tile(view_deltas, plan)
        host_slices = _slice_by_tile(split_changed, plan)
        with open(path, "wb") as f:
            f.write(_DELTA_MAGIC)
            n = len(_DELTA_MAGIC)
            hbody = pickle.dumps(header)
            f.write(_DELTA_HEADER.pack(len(hbody), zlib.crc32(hbody)))
            f.write(hbody)
            n += _DELTA_HEADER.size + len(hbody)
            for t, (lo, hi) in enumerate(plan):
                t0 = time.perf_counter()
                tp = {
                    "range": [lo, hi],
                    "view_deltas": view_slices[t],
                    "host_states": {nid: {"cls": _cls_name(
                                              split_changed[nid]),
                                          "items": items}
                                    for nid, items in
                                    host_slices[t].items()},
                }
                body = pickle.dumps(tp)
                TILE_IO_STATS["writer_peak_frame_bytes"] = max(
                    TILE_IO_STATS["writer_peak_frame_bytes"],
                    len(body))
                peak = max(peak, len(body))
                f.write(_DELTA_HEADER.pack(len(body),
                                           zlib.crc32(body)))
                f.write(body)
                n += _DELTA_HEADER.size + len(body)
                f.flush()
                self._crash_point("ckpt_tile_append")
                if _trace.ENABLED:
                    _trace.evt("ckpt_tile", t0,
                               time.perf_counter() - t0,
                               track="checkpoint",
                               args={"tile": t, "of": len(plan),
                                     "kind": "delta",
                                     "bytes": len(body)})
            f.flush()
            os.fsync(f.fileno())
        self.peak_tile_bytes = max(self.peak_tile_bytes, peak)
        return n

    def publish_metrics(self, registry=None, name: str = "ckpt"
                        ) -> None:
        from reflow_tpu.obs.registry import REGISTRY

        reg = registry if registry is not None else REGISTRY
        reg.gauge(f"{name}.saves", lambda: self.saves)
        reg.gauge(f"{name}.fulls", lambda: self.fulls)
        reg.gauge(f"{name}.deltas", lambda: self.deltas)
        reg.gauge(f"{name}.delta_bytes", lambda: self.delta_bytes)
        reg.gauge(f"{name}.tile_count", lambda: self.tile_count)
        reg.gauge(f"{name}.peak_tile_bytes",
                  lambda: self.peak_tile_bytes)
        self._metric_names.append((reg, name))

    def close(self) -> None:
        for reg, name in self._metric_names:
            reg.unregister_prefix(name)
        self._metric_names.clear()

    def _gc(self, old_manifest: Optional[dict]) -> None:
        """Drop the superseded chain's elements (best-effort; stray
        files from a crashed save are harmless and reaped next full)."""
        import shutil

        if old_manifest is None:
            return
        for dname in old_manifest.get("deltas", []):
            try:
                os.remove(os.path.join(self.root, dname))
            except OSError:
                pass
        base = old_manifest.get("base")
        if base:
            shutil.rmtree(os.path.join(self.root, base),
                          ignore_errors=True)

    def restore(self, sched) -> Dict:
        """Reader convenience: :func:`load_chain` over this root."""
        return load_chain(sched, self.root)
