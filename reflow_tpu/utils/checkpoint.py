"""Durable checkpoint/resume (SURVEY.md §5).

The durable state of an incremental dataflow is small and well-defined:
(per-node operator state, tick counter, materialized sink views). The
checkpoint records ``tick`` so the host driver knows where its cursor
was. On its own, a checkpoint covers ingestion only *at* save points —
everything pushed since the last save is lost on a crash unless the
upstream replays it. ``reflow_tpu.wal`` closes that window: a WAL-backed
scheduler (``wal.DurableScheduler``) logs every accepted batch, the save
records the log replay position (``"wal_pos"``) and truncates the sealed
segments it covers, and ``wal.recovery.recover`` restores checkpoint +
tail for exactly-once ingestion across process death.

Two serialization paths behind one API:

- **array states** (TpuExecutor / ShardedTpuExecutor): the state pytree is
  saved via ``orbax.checkpoint`` — zarr-sharded, async-capable, and on
  restore each leaf is loaded *directly into the executor's current
  sharding* (the live state tree provides the abstract target), so a
  key-sharded table comes back key-sharded without a host gather.
- **host states** (CpuExecutor's dict/Counter oracle state): pickle.

Layout: ``<dir>/meta.pkl`` (tick, sink views, host states) and
``<dir>/states/`` (orbax tree of the array states, if any).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict

__all__ = ["save_checkpoint", "load_checkpoint", "meta_digest"]


def _split_states(states: Dict[int, object]):
    """Partition per-node states into (array pytrees, host objects)."""
    import jax

    arr, host = {}, {}
    for nid, st in states.items():
        leaves = jax.tree.leaves(st) if isinstance(st, dict) else []
        if leaves and all(isinstance(v, jax.Array) for v in leaves):
            arr[str(nid)] = st
        else:
            host[nid] = st
    return arr, host


def meta_digest(tick: int, seen_batch_ids) -> int:
    """64-bit digest of the host-side meta that multi-controller saves
    assume SPMD-identical (tick counter + dedup window, in insertion
    order — order divergence is divergence)."""
    import hashlib

    h = hashlib.sha256(repr((tick, list(seen_batch_ids))).encode())
    return int.from_bytes(h.digest()[:8], "big")


def save_checkpoint(sched, path: str) -> None:
    """Multi-controller: every process calls this collectively with the
    same (shared-filesystem) path — orbax writes each process's
    addressable shards of the global arrays; the host-side meta (tick
    counter, sink views, dedup set) is written by process 0 alone.
    That meta MUST be SPMD-identical across processes (use
    ``scheduler.SourceCursor`` so batch ids are identical by
    construction); rather than assume it, the save VERIFIES it with one
    digest allgather and fails loudly on divergence — a process whose
    dedup window drifted would otherwise silently restore the wrong
    exactly-once horizon (VERDICT r4 #4a)."""
    import jax

    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        mine = np.uint64(meta_digest(sched._tick, sched._seen_batch_ids))
        digests = np.asarray(multihost_utils.process_allgather(mine))
        if len(set(int(x) for x in digests.ravel())) != 1:
            raise RuntimeError(
                "checkpoint meta diverged across controllers (tick "
                "counter or batch-id dedup window differs between "
                "processes); mint batch ids from a shared "
                "scheduler.SourceCursor so every process dedups "
                "identically")
    os.makedirs(path, exist_ok=True)
    arr, host = _split_states(sched.executor.states)
    meta = {
        "tick": sched._tick,
        "sink_views": {name: dict(c) for name, c in sched.sink_views.items()},
        "seen_batch_ids": dict(sched._seen_batch_ids),
        # accepted-but-unticked batches: without these, a crash between
        # push and tick would lose deltas whose ids the dedup set already
        # claims (exactly-once would silently become at-most-once)
        "pending": {nid: list(batches)
                    for nid, batches in sched._pending.items()},
        "host_states": pickle.dumps(host),
        "has_array_states": bool(arr),
    }
    # a WAL-backed scheduler (wal/durable.py): everything the log holds
    # up to now is covered by this checkpoint. Rotate so the whole
    # covered history sits in sealed segments, record the fresh
    # segment's start as the replay position, and drop the sealed
    # segments once the save has fully landed (never before — a failed
    # save must leave the tail replayable).
    wal = getattr(sched, "wal", None)
    if wal is not None:
        wal.sync()
        wal.rotate()
        meta["wal_pos"] = tuple(wal.position())
        wal.append({"kind": "ckpt", "tick": sched._tick,
                    "path": os.path.abspath(path)})
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.pkl"), "wb") as f:
            pickle.dump(meta, f)
    if arr:
        import orbax.checkpoint as ocp

        ckpt = ocp.StandardCheckpointer()
        ckpt.save(os.path.join(os.path.abspath(path), "states"), arr,
                  force=True)
        ckpt.wait_until_finished()
    if wal is not None:
        from reflow_tpu.wal.log import LogPosition

        wal.truncate_until(LogPosition(*meta["wal_pos"]))


def load_checkpoint(sched, path: str) -> Dict:
    """Restore into a scheduler whose graph/executor match the saved one.
    Returns the checkpoint meta dict (``wal.recovery.recover`` reads the
    recorded WAL replay position, ``"wal_pos"``, from it)."""
    from collections import Counter

    with open(os.path.join(path, "meta.pkl"), "rb") as f:
        meta = pickle.load(f)
    sched._tick = meta["tick"]
    sched._seen_batch_ids = dict(meta["seen_batch_ids"])
    sched._pending.clear()
    for nid, batches in meta["pending"].items():
        sched._pending[nid].extend(batches)
    for name, d in meta["sink_views"].items():
        sched.sink_views[name] = Counter(d)
    states = dict(pickle.loads(meta["host_states"]))
    if meta["has_array_states"]:
        import orbax.checkpoint as ocp

        live_arr, _ = _split_states(sched.executor.states)
        if not live_arr:
            raise ValueError(
                "checkpoint holds array states but the bound executor has "
                "none — restore onto the same executor kind it was saved "
                "from")
        ckpt = ocp.StandardCheckpointer()
        restored = ckpt.restore(
            os.path.join(os.path.abspath(path), "states"), live_arr)
        for sid, st in restored.items():
            states[int(sid)] = st
    sched.executor.states = states
    # arena occupancy (rcount) and the sticky overflow flag travel inside
    # the checkpointed state pytree itself; the in-program high-water
    # check (lax.cond compaction in join_core) needs no host-side tracker
    # reconstruction after restore. Derived caches keyed to state content
    # (the linear fixpoint's sorted-arena CSR) must drop, though: two
    # lineages can share a (gen, rcount) pair over different arena rows,
    # so the in-program validity predicate alone cannot see the swap.
    sched.executor.on_states_replaced()
    return meta
