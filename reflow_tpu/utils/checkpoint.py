"""Durable checkpoint/resume (SURVEY.md §5).

The durable state of an incremental dataflow is small and well-defined:
(per-node operator state, tick counter, materialized sink views). The
checkpoint records ``tick`` so the host driver knows where its cursor
was. On its own, a checkpoint covers ingestion only *at* save points —
everything pushed since the last save is lost on a crash unless the
upstream replays it. ``reflow_tpu.wal`` closes that window: a WAL-backed
scheduler (``wal.DurableScheduler``) logs every accepted batch, the save
records the log replay position (``"wal_pos"``) and truncates the sealed
segments it covers, and ``wal.recovery.recover`` restores checkpoint +
tail for exactly-once ingestion across process death.

Two serialization paths behind one API:

- **array states** (TpuExecutor / ShardedTpuExecutor): the state pytree is
  saved via ``orbax.checkpoint`` — zarr-sharded, async-capable, and on
  restore each leaf is loaded *directly into the executor's current
  sharding* (the live state tree provides the abstract target), so a
  key-sharded table comes back key-sharded without a host gather.
- **host states** (CpuExecutor's dict/Counter oracle state): pickle.

Layout: ``<dir>/meta.pkl`` (tick, sink views, host states) and
``<dir>/states/`` (orbax tree of the array states, if any).

Bounded history (incremental checkpoints)
-----------------------------------------
A full checkpoint is O(state) bytes *every* save, which caps how often
an operator can afford to take one — and the WAL only truncates at
saves, so rare saves mean O(history) replay tails. :class:`CheckpointChain`
fixes the cost side: it manages a directory of one **full** checkpoint
plus a chain of **delta** elements (per-source state snapshots of only
what changed since the previous element, keyed by the macro-tick
horizon), linked by a ``chain.json`` manifest. ``load_checkpoint`` on a
chain directory restores base + deltas in order; a broken link
mid-chain fails loud, while a torn/partial *final* delta falls back one
chain element — exactly the WAL's torn-tail stance. To make that
fallback always recoverable, WAL truncation lags one element: a delta
save truncates only up to the *previous* element's anchor, so the log
still covers the newest element's window if its file is lost.

Delta file framing mirrors the WAL: ``RFCKD001`` magic, then one
``[u32 len][u32 crc32]`` pickled payload — torn bytes are detected the
same way a torn WAL record is.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import zlib
from typing import Dict, List, Optional

__all__ = ["save_checkpoint", "load_checkpoint", "meta_digest",
           "checkpoint_exists", "CheckpointChain", "CheckpointError",
           "load_chain", "read_chain_manifest", "chain_head_wal_pos",
           "CHAIN_MANIFEST", "CHAIN_SCHEMA"]

CHAIN_MANIFEST = "chain.json"
CHAIN_SCHEMA = "reflow.ckpt_chain/1"
_DELTA_MAGIC = b"RFCKD001"
_DELTA_HEADER = struct.Struct("<II")


class CheckpointError(RuntimeError):
    """A checkpoint/chain element is unreadable or the chain is
    inconsistent (broken parent link, horizon mismatch)."""

    def __init__(self, msg: str, *, torn: bool = False):
        super().__init__(msg)
        #: True when the element's *bytes* are torn/short/corrupt (the
        #: WAL-torn-tail analogue) as opposed to a structural link break
        self.torn = torn


def checkpoint_exists(path: Optional[str]) -> bool:
    """True when ``path`` holds a restorable checkpoint — either a
    legacy full checkpoint (``meta.pkl``) or a chain directory
    (``chain.json``)."""
    if path is None:
        return False
    return (os.path.exists(os.path.join(path, CHAIN_MANIFEST))
            or os.path.exists(os.path.join(path, "meta.pkl")))


def _split_states(states: Dict[int, object]):
    """Partition per-node states into (array pytrees, host objects)."""
    import jax

    arr, host = {}, {}
    for nid, st in states.items():
        leaves = jax.tree.leaves(st) if isinstance(st, dict) else []
        if leaves and all(isinstance(v, jax.Array) for v in leaves):
            arr[str(nid)] = st
        else:
            host[nid] = st
    return arr, host


def meta_digest(tick: int, seen_batch_ids) -> int:
    """64-bit digest of the host-side meta that multi-controller saves
    assume SPMD-identical (tick counter + dedup window, in insertion
    order — order divergence is divergence)."""
    import hashlib

    h = hashlib.sha256(repr((tick, list(seen_batch_ids))).encode())
    return int.from_bytes(h.digest()[:8], "big")


def save_checkpoint(sched, path: str, *, truncate: bool = True) -> None:
    """Multi-controller: every process calls this collectively with the
    same (shared-filesystem) path — orbax writes each process's
    addressable shards of the global arrays; the host-side meta (tick
    counter, sink views, dedup set) is written by process 0 alone.
    That meta MUST be SPMD-identical across processes (use
    ``scheduler.SourceCursor`` so batch ids are identical by
    construction); rather than assume it, the save VERIFIES it with one
    digest allgather and fails loudly on divergence — a process whose
    dedup window drifted would otherwise silently restore the wrong
    exactly-once horizon (VERDICT r4 #4a)."""
    import jax

    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        mine = np.uint64(meta_digest(sched._tick, sched._seen_batch_ids))
        digests = np.asarray(multihost_utils.process_allgather(mine))
        if len(set(int(x) for x in digests.ravel())) != 1:
            raise RuntimeError(
                "checkpoint meta diverged across controllers (tick "
                "counter or batch-id dedup window differs between "
                "processes); mint batch ids from a shared "
                "scheduler.SourceCursor so every process dedups "
                "identically")
    os.makedirs(path, exist_ok=True)
    arr, host = _split_states(sched.executor.states)
    meta = {
        "tick": sched._tick,
        "sink_views": {name: dict(c) for name, c in sched.sink_views.items()},
        "seen_batch_ids": dict(sched._seen_batch_ids),
        # accepted-but-unticked batches: without these, a crash between
        # push and tick would lose deltas whose ids the dedup set already
        # claims (exactly-once would silently become at-most-once)
        "pending": {nid: list(batches)
                    for nid, batches in sched._pending.items()},
        "host_states": pickle.dumps(host),
        "has_array_states": bool(arr),
    }
    # a WAL-backed scheduler (wal/durable.py): everything the log holds
    # up to now is covered by this checkpoint. Rotate so the whole
    # covered history sits in sealed segments, record the fresh
    # segment's start as the replay position, and drop the sealed
    # segments once the save has fully landed (never before — a failed
    # save must leave the tail replayable).
    wal = getattr(sched, "wal", None)
    if wal is not None:
        wal.sync()
        wal.rotate()
        meta["wal_pos"] = tuple(wal.position())
        wal.append({"kind": "ckpt", "tick": sched._tick,
                    "path": os.path.abspath(path)})
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.pkl"), "wb") as f:
            pickle.dump(meta, f)
    if arr:
        import orbax.checkpoint as ocp

        ckpt = ocp.StandardCheckpointer()
        ckpt.save(os.path.join(os.path.abspath(path), "states"), arr,
                  force=True)
        ckpt.wait_until_finished()
    if wal is not None and truncate:
        from reflow_tpu.wal.log import LogPosition

        wal.truncate_until(LogPosition(*meta["wal_pos"]))


def load_checkpoint(sched, path: str) -> Dict:
    """Restore into a scheduler whose graph/executor match the saved one.
    ``path`` may be a legacy full checkpoint directory (``meta.pkl``) or
    a :class:`CheckpointChain` directory (``chain.json``) — a chain is
    restored base-then-deltas. Returns the checkpoint meta dict
    (``wal.recovery.recover`` reads the recorded WAL replay position,
    ``"wal_pos"``, from it)."""
    if os.path.exists(os.path.join(path, CHAIN_MANIFEST)):
        return load_chain(sched, path)
    return _load_full(sched, path)


def _load_full(sched, path: str) -> Dict:
    """The legacy single-directory restore (meta.pkl + orbax states)."""
    from collections import Counter

    try:
        with open(os.path.join(path, "meta.pkl"), "rb") as f:
            meta = pickle.load(f)
    except (OSError, pickle.UnpicklingError, EOFError) as e:
        raise CheckpointError(f"{path}: unreadable checkpoint meta "
                              f"({e})", torn=True) from e
    sched._tick = meta["tick"]
    sched._seen_batch_ids = dict(meta["seen_batch_ids"])
    sched._pending.clear()
    for nid, batches in meta["pending"].items():
        sched._pending[nid].extend(batches)
    for name, d in meta["sink_views"].items():
        sched.sink_views[name] = Counter(d)
    states = dict(pickle.loads(meta["host_states"]))
    if meta["has_array_states"]:
        import orbax.checkpoint as ocp

        live_arr, _ = _split_states(sched.executor.states)
        if not live_arr:
            raise ValueError(
                "checkpoint holds array states but the bound executor has "
                "none — restore onto the same executor kind it was saved "
                "from")
        ckpt = ocp.StandardCheckpointer()
        restored = ckpt.restore(
            os.path.join(os.path.abspath(path), "states"), live_arr)
        for sid, st in restored.items():
            states[int(sid)] = st
    sched.executor.states = states
    # arena occupancy (rcount) and the sticky overflow flag travel inside
    # the checkpointed state pytree itself; the in-program high-water
    # check (lax.cond compaction in join_core) needs no host-side tracker
    # reconstruction after restore. Derived caches keyed to state content
    # (the linear fixpoint's sorted-arena CSR) must drop, though: two
    # lineages can share a (gen, rcount) pair over different arena rows,
    # so the in-program validity predicate alone cannot see the swap.
    sched.executor.on_states_replaced()
    return meta


# -- incremental checkpoint chain ------------------------------------------


def read_chain_manifest(root: str) -> Optional[dict]:
    """The chain manifest as a dict, or None when ``root`` is not a
    chain directory. Raises :class:`CheckpointError` on unparseable
    JSON (a half-written manifest is a broken chain, not an empty one —
    the flip is atomic, so this only happens under real corruption)."""
    path = os.path.join(root, CHAIN_MANIFEST)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: unreadable chain manifest "
                              f"({e})") from e


def chain_head_wal_pos(root: str):
    """The newest chain element's recorded WAL anchor as a
    ``(segment, offset)`` tuple, or None (no chain / no WAL)."""
    m = read_chain_manifest(root)
    if m is None or m.get("wal_pos") is None:
        return None
    return tuple(m["wal_pos"])


def _write_delta_file(path: str, payload: dict) -> int:
    body = pickle.dumps(payload)
    frame = (_DELTA_MAGIC + _DELTA_HEADER.pack(len(body),
                                               zlib.crc32(body)) + body)
    with open(path, "wb") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    return len(frame)


def _read_delta_file(path: str) -> dict:
    """Parse one framed delta element; raises :class:`CheckpointError`
    (``torn=True``) on missing/short/CRC-torn bytes — the condition the
    chain loader answers by falling back one element."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointError(f"{path}: missing delta element ({e})",
                              torn=True) from e
    if data[:len(_DELTA_MAGIC)] != _DELTA_MAGIC:
        raise CheckpointError(f"{path}: bad delta magic "
                              f"{data[:len(_DELTA_MAGIC)]!r}", torn=True)
    off = len(_DELTA_MAGIC)
    if off + _DELTA_HEADER.size > len(data):
        raise CheckpointError(f"{path}: truncated delta header",
                              torn=True)
    length, crc = _DELTA_HEADER.unpack_from(data, off)
    body = data[off + _DELTA_HEADER.size: off + _DELTA_HEADER.size
                + length]
    if len(body) < length:
        raise CheckpointError(
            f"{path}: truncated delta payload ({len(body)}/{length} "
            f"bytes)", torn=True)
    if zlib.crc32(body) != crc:
        raise CheckpointError(f"{path}: delta CRC mismatch", torn=True)
    try:
        return pickle.loads(body)
    except Exception as e:  # noqa: BLE001 - framed+CRC-clean yet unloadable
        raise CheckpointError(f"{path}: unpicklable delta payload "
                              f"({e})", torn=True) from e


def _numpyify(tree):
    import jax
    import numpy as np

    return jax.tree.map(lambda a: np.asarray(a), tree)


def _apply_delta(sched, payload: dict) -> None:
    from collections import Counter

    sched._tick = payload["tick"]
    for sink, kv in payload["view_deltas"].items():
        view = sched.sink_views.get(sink)
        if view is None:
            view = sched.sink_views[sink] = Counter()
        for k, v in kv.items():
            if v is None:
                view.pop(k, None)
            else:
                view[k] = v
    states = sched.executor.states
    for nid, blob in payload["host_states"].items():
        states[nid] = pickle.loads(blob)
    if payload.get("array_states"):
        import jax

        for nid, np_tree in payload["array_states"].items():
            live = states.get(nid)
            if live is not None and any(
                    isinstance(leaf, jax.Array)
                    for leaf in jax.tree.leaves(live)):
                # restore each leaf directly into the live leaf's
                # sharding (same stance as the orbax full-restore path)
                states[nid] = jax.tree.map(
                    lambda np_v, lv: jax.device_put(
                        np_v, lv.sharding) if isinstance(lv, jax.Array)
                    else np_v,
                    np_tree, live)
            else:
                states[nid] = np_tree
    for b in payload["ids_added"]:
        sched._seen_batch_ids[b] = None
    for _ in range(payload["ids_dropped"]):
        if not sched._seen_batch_ids:
            break
        sched._seen_batch_ids.pop(next(iter(sched._seen_batch_ids)))
    sched._pending.clear()
    for nid, batches in payload["pending"].items():
        sched._pending[nid].extend(batches)


def load_chain(sched, root: str) -> Dict:
    """Restore a :class:`CheckpointChain` directory: the base full
    checkpoint, then every delta element in manifest order. A broken
    link anywhere mid-chain (missing/corrupt element, parent or horizon
    mismatch) fails loud; a torn/partial *final* delta falls back to
    the previous chain element — the WAL still covers its window
    because truncation lags one element. Returns a meta dict whose
    ``"wal_pos"`` is the last successfully applied element's anchor."""
    manifest = read_chain_manifest(root)
    if manifest is None:
        raise CheckpointError(f"{root}: no chain manifest")
    base = manifest["base"]
    meta = _load_full(sched, os.path.join(root, base))
    wal_pos = meta.get("wal_pos")
    prev_name = base
    applied = 0
    fallback = None
    deltas: List[str] = list(manifest.get("deltas", []))
    for i, dname in enumerate(deltas):
        try:
            payload = _read_delta_file(os.path.join(root, dname))
            if payload.get("parent") != prev_name \
                    or payload.get("base_tick") != sched._tick:
                raise CheckpointError(
                    f"{root}/{dname}: broken chain link (parent "
                    f"{payload.get('parent')!r} @ tick "
                    f"{payload.get('base_tick')!r}, expected "
                    f"{prev_name!r} @ tick {sched._tick})")
        except CheckpointError as e:
            if e.torn and i == len(deltas) - 1:
                # torn tail of the chain: fall back one element, the
                # WAL tail (truncation lagged one save) replays the gap
                fallback = str(e)
                break
            raise
        _apply_delta(sched, payload)
        if payload.get("wal_pos") is not None:
            wal_pos = tuple(payload["wal_pos"])
        prev_name = dname
        applied += 1
    sched.executor.on_states_replaced()
    out = {
        "tick": sched._tick,
        "wal_pos": wal_pos,
        "seen_batch_ids": dict(sched._seen_batch_ids),
        "chain": {"base": base, "deltas_applied": applied,
                  "deltas_total": len(deltas), "fallback": fallback},
    }
    if wal_pos is None:
        out.pop("wal_pos")
    return out


class CheckpointChain:
    """Writer side of the bounded-history checkpoint chain.

    ``save(sched)`` takes a cheap **delta** element (only the sinks,
    per-source states, dedup-window entries and pending buffers that
    changed since the previous element), promoting to a **full**
    checkpoint every ``delta_every``-th save (or when forced with
    ``full=True``; the very first save is always full). Every save
    follows the WAL choreography of ``save_checkpoint`` — sync, rotate,
    record the fresh segment start as the element's anchor — and then
    truncates the log up to the *previous* element's anchor (lag-one:
    a torn final delta must leave its window replayable from the WAL).

    The atomic commit point of every save is the ``chain.json``
    manifest flip (write-tmp + fsync + ``os.replace``): a crash before
    the flip leaves the previous chain fully restorable, a crash after
    it leaves the new one. ``crash`` is a
    :class:`~reflow_tpu.utils.faults.CrashInjector` seam hook
    (``ckpt_full_before_flip`` / ``ckpt_delta_before_flip`` /
    ``ckpt_delta_after_flip``) for the differential crash tests."""

    def __init__(self, root: str, *, delta_every: Optional[int] = None,
                 crash=None):
        from reflow_tpu.utils.config import env_int

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.delta_every = (delta_every if delta_every is not None
                            else env_int("REFLOW_CKPT_DELTA_EVERY"))
        self._crash = crash
        self.saves = 0
        self.fulls = 0
        self.deltas = 0
        self.delta_bytes = 0
        #: what the previous element looked like, for diffing; None
        #: forces the next save to be full (fresh writer, fresh chain)
        self._shadow: Optional[dict] = None

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- shadow bookkeeping ------------------------------------------------

    @staticmethod
    def _classify_states(states: Dict):
        """(host {nid: pickled bytes}, array {nid: numpy pytree}) —
        both forms are digestable/diffable host-side."""
        import jax

        host, arr = {}, {}
        for nid, st in states.items():
            leaves = jax.tree.leaves(st) if isinstance(st, dict) else []
            if leaves and all(isinstance(v, jax.Array) for v in leaves):
                arr[nid] = _numpyify(st)
            else:
                host[nid] = pickle.dumps(st)
        return host, arr

    def _snapshot(self, sched) -> dict:
        host, arr = self._classify_states(sched.executor.states)
        return {
            "tick": sched._tick,
            "views": {name: dict(c)
                      for name, c in sched.sink_views.items()},
            "host": host,
            "arr_blobs": {nid: pickle.dumps(t) for nid, t in arr.items()},
            "arr_trees": arr,
            "ids": dict(sched._seen_batch_ids),
        }

    # -- saves -------------------------------------------------------------

    def _wal_anchor(self, sched):
        """sync+rotate the scheduler's WAL (if any) and return the
        fresh segment start — the element's replay anchor."""
        wal = getattr(sched, "wal", None)
        if wal is None:
            return None
        wal.sync()
        wal.rotate()
        pos = tuple(wal.position())
        wal.append({"kind": "ckpt", "tick": sched._tick,
                    "path": self.root})
        return pos

    def _flip_manifest(self, manifest: dict) -> None:
        path = os.path.join(self.root, CHAIN_MANIFEST)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _truncate_to(self, sched, wal_pos) -> None:
        wal = getattr(sched, "wal", None)
        if wal is None or wal_pos is None:
            return
        from reflow_tpu.wal.log import LogPosition

        wal.truncate_until(LogPosition(*wal_pos))

    def save(self, sched, *, full: Optional[bool] = None) -> dict:
        """Take one chain element; returns an info dict (kind, element
        name, tick horizon, anchor, bytes written)."""
        want_full = (full if full is not None
                     else (self._shadow is None or self.delta_every <= 1
                           or self.saves % self.delta_every == 0))
        if self._shadow is None:
            want_full = True
        info = (self._save_full(sched) if want_full
                else self._save_delta(sched))
        self.saves += 1
        return info

    def _save_full(self, sched) -> dict:
        old = read_chain_manifest(self.root) if os.path.exists(
            os.path.join(self.root, CHAIN_MANIFEST)) else None
        name = f"full-{self.saves:06d}"
        path = os.path.join(self.root, name)
        # truncate=False: the log must stay intact until the manifest
        # names this full as the new chain base — a crash between the
        # save and the flip restores the OLD chain, whose last element
        # still needs its replay tail
        save_checkpoint(sched, path, truncate=False)
        wal = getattr(sched, "wal", None)
        wal_pos = None
        if wal is not None:
            with open(os.path.join(path, "meta.pkl"), "rb") as f:
                wal_pos = pickle.load(f).get("wal_pos")
        self._crash_point("ckpt_full_before_flip")
        manifest = {
            "schema": CHAIN_SCHEMA,
            "base": name,
            "deltas": [],
            "horizon": sched._tick,
            "wal_pos": list(wal_pos) if wal_pos is not None else None,
            "saves": self.saves + 1,
        }
        self._flip_manifest(manifest)
        self._truncate_to(sched, wal_pos)
        self._gc(old)
        self._shadow = self._snapshot(sched)
        self._shadow["wal_pos"] = wal_pos
        self._shadow["name"] = name
        self.fulls += 1
        return {"kind": "full", "element": name, "tick": sched._tick,
                "wal_pos": wal_pos}

    def _save_delta(self, sched) -> dict:
        shadow = self._shadow
        host, arr = self._classify_states(sched.executor.states)
        host_changed = {nid: blob for nid, blob in host.items()
                        if shadow["host"].get(nid) != blob}
        arr_changed = {}
        for nid, tree in arr.items():
            blob = pickle.dumps(tree)
            if shadow["arr_blobs"].get(nid) != blob:
                arr_changed[nid] = tree
        view_deltas: Dict[str, Dict] = {}
        for name, c in sched.sink_views.items():
            old = shadow["views"].get(name, {})
            kv = {k: v for k, v in c.items() if old.get(k) != v}
            kv.update({k: None for k in old if k not in c})
            if kv:
                view_deltas[name] = kv
        new_ids = dict(sched._seen_batch_ids)
        added = [b for b in new_ids if b not in shadow["ids"]]
        dropped = len(shadow["ids"]) + len(added) - len(new_ids)
        wal_pos = self._wal_anchor(sched)
        payload = {
            "tick": sched._tick,
            "base_tick": shadow["tick"],
            "parent": shadow["name"],
            "view_deltas": view_deltas,
            "host_states": host_changed,
            "array_states": {nid: t for nid, t in arr_changed.items()},
            "ids_added": added,
            "ids_dropped": max(0, dropped),
            "pending": {nid: list(batches)
                        for nid, batches in sched._pending.items()},
            "wal_pos": wal_pos,
        }
        name = f"delta-{self.saves:06d}.ckd"
        nbytes = _write_delta_file(os.path.join(self.root, name),
                                   payload)
        self._crash_point("ckpt_delta_before_flip")
        manifest = read_chain_manifest(self.root)
        manifest["deltas"] = list(manifest.get("deltas", [])) + [name]
        manifest["horizon"] = sched._tick
        manifest["wal_pos"] = (list(wal_pos) if wal_pos is not None
                               else None)
        manifest["saves"] = self.saves + 1
        self._flip_manifest(manifest)
        self._crash_point("ckpt_delta_after_flip")
        # lag-one truncation: keep the log back to the PREVIOUS
        # element's anchor, so a torn copy of the element we just wrote
        # falls back one link and replays its window from the WAL
        self._truncate_to(sched, shadow.get("wal_pos"))
        self._shadow = self._snapshot(sched)
        self._shadow["wal_pos"] = wal_pos
        self._shadow["name"] = name
        self.deltas += 1
        self.delta_bytes += nbytes
        return {"kind": "delta", "element": name, "tick": sched._tick,
                "wal_pos": wal_pos, "bytes": nbytes,
                "changed_sources": sorted(
                    list(host_changed) + list(arr_changed))}

    def _gc(self, old_manifest: Optional[dict]) -> None:
        """Drop the superseded chain's elements (best-effort; stray
        files from a crashed save are harmless and reaped next full)."""
        import shutil

        if old_manifest is None:
            return
        for dname in old_manifest.get("deltas", []):
            try:
                os.remove(os.path.join(self.root, dname))
            except OSError:
                pass
        base = old_manifest.get("base")
        if base:
            shutil.rmtree(os.path.join(self.root, base),
                          ignore_errors=True)

    def restore(self, sched) -> Dict:
        """Reader convenience: :func:`load_chain` over this root."""
        return load_chain(sched, self.root)
