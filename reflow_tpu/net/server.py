"""ReplicaServer: put a ReplicaScheduler behind a transport listener.

The server end of "Replication over the wire" (docs/guide.md): it owns
a :class:`~reflow_tpu.net.transport.Listener` and answers the shipping
protocol as framed request-response messages, delegating every decision
to the wrapped :class:`~reflow_tpu.serve.replica.ReplicaScheduler` —
epoch fencing, order/CRC rejection, holdback and cursor persistence all
stay exactly where the in-process tests already exercise them. The
wire adds nothing but the wire.

Requests (pickled tuples, ``net/framing.py``)::

    ("subscribe",)                     -> ("ok", cursor | None, anchor)
    ("bootstrap", ckpt_dir)            -> ("ok", cursor)
    ("receive", *shipment_fields)      -> ("ack", cursor, horizon)
                                        | ("nack", cursor, reason)
    ("ping",)                          -> ("ok", {name, horizon, epoch,
                                                  lag_ticks})
    ("view", sink_name)                -> ("ok", horizon, {key: weight})
    anything else                      -> ("err", text)

Addressing: ``start()`` binds whatever the transport's listener
reports — under :class:`~reflow_tpu.net.transport.TcpTransport` that
is port 0 by default, so the OS assigns a free port and ``address``
is the authoritative ``(host, port)`` to advertise. Callers must read
``address`` *after* ``start()`` rather than pre-picking ports; this
is what lets the process harness spawn many replica processes in
parallel (each child prints its assigned address on its ready line)
without port collisions.

Concurrency: one accept-loop thread plus one handler thread per
connection. Multiple concurrent clients are not an edge case — during
a failover the NEW leader's shipper and the partitioned zombie's both
hold connections, and the replica's own lock (plus the epoch fence)
arbitrates. A handler treats :class:`WireTimeout` as "idle, keep
waiting" and any other :class:`TransportError` (including a
:class:`FrameError` from a corrupted frame — unsyncable by design) as
the end of that connection; the client reconnects and re-handshakes,
which ``subscribe()`` makes idempotent.
"""

from __future__ import annotations

import threading
from typing import Optional

from reflow_tpu.net.framing import TransportError, WireTimeout
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.wal.ship import ShipAck, Shipment

__all__ = ["ReplicaServer"]

#: accept/recv poll slice: how often blocked server threads re-check
#: the stop flag (short, so close() never hangs a test)
_POLL_S = 0.2


class ReplicaServer:
    """Serve one replica's shipping endpoint over ``transport``.

    ``start()`` binds a listener and returns; ``address`` is then
    dialable by a :class:`~reflow_tpu.net.client.RemoteFollower`.
    ``close()`` tears down the listener and every live connection.
    """

    def __init__(self, replica, transport: Transport) -> None:
        self.replica = replica
        self.transport = transport
        self._listener = None
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = named_lock("net.server")
        self._conns: list = []
        self._handlers: list = []
        self.connections_total = 0
        self.requests_total = 0
        self.frame_resets = 0

    @property
    def address(self):
        if self._listener is None:
            raise TransportError("server not started")
        return self._listener.address

    def start(self) -> "ReplicaServer":
        if self._accept_thread is not None:
            return self
        self._listener = self.transport.listen()
        self._stop.clear()
        name = getattr(self.replica, "name", "replica")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"net-accept/{name}",
            daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept(timeout_s=_POLL_S)
            except WireTimeout:
                continue
            except TransportError:
                return  # listener closed under us
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self.connections_total += 1
                self._conns.append(conn)
                t = threading.Thread(
                    target=self._serve_conn, args=(conn,),
                    name=f"net-serve/{self.connections_total}",
                    daemon=True)
                self._handlers.append(t)
            t.start()

    def _serve_conn(self, conn: Conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv_msg(timeout_s=_POLL_S)
                except WireTimeout:
                    continue  # idle connection; re-check stop and wait
                except TransportError:
                    # closed, reset, or an unsyncable corrupt frame —
                    # drop the connection; the client re-handshakes
                    self.frame_resets += 1
                    return
                try:
                    reply = self._dispatch(msg)
                except TransportError:
                    raise
                except Exception as e:  # noqa: BLE001 - a poisoned
                    # request must not kill the endpoint for the others
                    reply = ("err", f"{type(e).__name__}: {e}")
                try:
                    conn.send_msg(reply)
                except TransportError:
                    return
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _dispatch(self, msg):
        if not isinstance(msg, tuple) or not msg:
            return ("err", f"malformed request {type(msg).__name__}")
        self.requests_total += 1
        op, args = msg[0], msg[1:]
        r = self.replica
        if op == "subscribe":
            cur = r.subscribe()
            # piggyback a clock anchor on the handshake so the leader
            # can display this replica's span timestamps on one wall
            # axis; old clients ignore the third element (lazy import —
            # obs.wire rides this package's transports)
            from reflow_tpu.obs.wire import clock_anchor
            return ("ok", tuple(cur) if cur is not None else None,
                    clock_anchor(getattr(r, "name", "replica")))
        if op == "bootstrap":
            return ("ok", tuple(r.bootstrap(args[0])))
        if op == "receive":
            resp = r.receive(Shipment(*args))
            if isinstance(resp, ShipAck):
                return ("ack", tuple(resp.cursor), resp.horizon)
            return ("nack",
                    tuple(resp.cursor) if resp.cursor is not None
                    else None,
                    resp.reason)
        if op == "ping":
            return ("ok", {
                "name": getattr(r, "name", "replica"),
                "horizon": r.published_horizon(),
                "epoch": getattr(r, "epoch", 0),
                "lag_ticks": r.lag_ticks() if hasattr(r, "lag_ticks")
                else 0,
            })
        if op == "view":
            # published view at a consistent cut — parity checks across
            # process boundaries (bench oracle, harness barrier probes)
            horizon, view = r.view_at(args[0])
            return ("ok", horizon, dict(view))
        return ("err", f"unknown op {op!r}")

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns = list(self._conns)
            handlers = list(self._handlers)
        for c in conns:
            c.close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        for h in handlers:
            h.join(timeout=5.0)
