"""Replication over the wire (docs/guide.md): framed transports, the
fault injector, and the shipping protocol's two wire endpoints.

Layers, bottom up:

- ``framing`` — one message = one CRC-protected, magic-prefixed frame.
- ``transport`` — :class:`TcpTransport` (real sockets) and
  :class:`LoopbackTransport` (in-process twin, same bytes) behind one
  ``Conn``/``Listener``/``Transport`` surface.
- ``faults`` — :class:`FaultyTransport` composes over any transport
  and injects drop/delay/duplicate/reorder/corrupt/partition/reset
  from a seeded :class:`~reflow_tpu.utils.faults.WireFaults` schedule.
- ``backoff`` — :class:`ReconnectPolicy`, the per-link
  connect → healthy → degraded → unreachable state machine.
- ``client`` / ``server`` — :class:`RemoteFollower` (what a
  ``SegmentShipper`` attaches) and :class:`ReplicaServer` (what a
  ``ReplicaScheduler`` sits behind).
"""

from reflow_tpu.net.backoff import (ReconnectPolicy, STATE_CONNECTING,
                                    STATE_DEGRADED, STATE_HEALTHY,
                                    STATE_UNREACHABLE)
from reflow_tpu.net.client import RemoteFollower
from reflow_tpu.net.faults import FaultyConn, FaultyTransport
from reflow_tpu.net.framing import (FrameError, TransportError,
                                    WireTimeout, decode_frame,
                                    encode_frame)
from reflow_tpu.net.server import ReplicaServer
from reflow_tpu.net.transport import (Conn, Listener, LoopbackTransport,
                                      TcpTransport, Transport)

__all__ = [
    "Conn", "Listener", "Transport", "LoopbackTransport", "TcpTransport",
    "FaultyConn", "FaultyTransport",
    "ReconnectPolicy", "STATE_CONNECTING", "STATE_HEALTHY",
    "STATE_DEGRADED", "STATE_UNREACHABLE",
    "RemoteFollower", "ReplicaServer",
    "FrameError", "TransportError", "WireTimeout",
    "encode_frame", "decode_frame",
]
