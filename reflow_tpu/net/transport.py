"""Framed-message transports: real TCP and an in-process loopback twin.

Both speak the same protocol surface — :class:`Conn` (``send_msg`` /
``recv_msg`` / ``close``), :class:`Listener` (``accept``), and a
:class:`Transport` factory (``listen`` / ``connect``) — and both move
*the same framed bytes* (``net/framing.py``): the loopback twin
serializes every message through ``encode_frame`` into a byte buffer
and re-parses it on the far side, so a frame-level fault (a flipped
byte, a truncated tail) corrupts identically on either transport and
the protocol test matrix runs verbatim against both.

Timeouts are mandatory. Every blocking operation takes an explicit
timeout and raises :class:`~reflow_tpu.net.framing.TransportError` when
it expires — there is no infinite wait anywhere in this module (the
``socket-no-timeout`` lint rule machine-checks the TCP half). Defaults
come from the ``REFLOW_NET_*`` knobs (docs/guide.md "Environment
knobs").

Use :class:`LoopbackTransport` for hermetic tests and single-process
benches; :class:`TcpTransport` to put replicas in other processes or on
other hosts. ``serve/replica.py`` objects never see either — they sit
behind a :class:`~reflow_tpu.net.server.ReplicaServer` and in front of
a :class:`~reflow_tpu.net.client.RemoteFollower`, which are
transport-agnostic.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from reflow_tpu.net.framing import (HEADER, MAGIC, FrameError,
                                    TransportError, WireTimeout,
                                    decode_frame, encode_frame,
                                    frame_size)
from reflow_tpu.utils.config import env_float
from reflow_tpu.utils.runtime import named_lock

__all__ = ["Conn", "Listener", "Transport", "LoopbackTransport",
           "TcpTransport", "default_io_timeout_s"]

_HDR = len(MAGIC) + HEADER.size


def default_io_timeout_s() -> float:
    """The per-operation send/recv timeout (REFLOW_NET_IO_TIMEOUT_S)."""
    return env_float("REFLOW_NET_IO_TIMEOUT_S")


class Conn:
    """One framed-message connection. ``send_msg`` frames and writes;
    ``recv_msg`` blocks up to ``timeout_s`` for one whole frame. Both
    raise :class:`TransportError` on link death and ``recv_msg`` raises
    :class:`FrameError` (a subclass) on an unsyncable stream."""

    def send_msg(self, obj: Any, timeout_s: Optional[float] = None) -> int:
        raise NotImplementedError

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> int:
        """Write pre-framed (possibly deliberately mangled) bytes —
        the fault injector's corruption seam."""
        raise NotImplementedError

    def recv_msg(self, timeout_s: Optional[float] = None) -> Any:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def alive(self) -> bool:
        raise NotImplementedError


class Listener:
    def accept(self, timeout_s: Optional[float] = None) -> Conn:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def address(self):
        raise NotImplementedError


class Transport:
    """Factory pair: ``listen()`` binds a server endpoint, ``connect``
    dials one. Addresses are opaque tokens minted by ``listen``."""

    def listen(self) -> Listener:
        raise NotImplementedError

    def connect(self, address, timeout_s: Optional[float] = None) -> Conn:
        raise NotImplementedError


# -- loopback ---------------------------------------------------------------

class _LoopbackEnd(Conn):
    """One direction pair of an in-process connection: bytes land in
    the peer's buffer under the peer's condition. The framing layer is
    NOT bypassed — every message round-trips through encode/decode so
    corruption faults behave exactly as on a socket."""

    def __init__(self) -> None:
        self._cond = threading.Condition(
            named_lock("net.loopback.conn"))
        self._rx = bytearray()
        self._closed = False
        self.peer: Optional["_LoopbackEnd"] = None

    def send_msg(self, obj: Any, timeout_s: Optional[float] = None) -> int:
        return self.send_raw(encode_frame(obj), timeout_s)

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> int:
        peer = self.peer
        if peer is None or self._closed:
            raise TransportError("send on a closed loopback connection")
        with peer._cond:
            if peer._closed:
                raise TransportError("peer closed the loopback "
                                     "connection")
            peer._rx += data
            peer._cond.notify_all()
        return len(data)

    def recv_msg(self, timeout_s: Optional[float] = None) -> Any:
        timeout_s = default_io_timeout_s() if timeout_s is None \
            else timeout_s
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                got = self._try_parse_locked()
                if got is not None:
                    return got[0]
                if self._closed:
                    raise TransportError("loopback connection closed")
                left = deadline - time.monotonic()
                if left <= 0:
                    raise WireTimeout(
                        f"recv timed out after {timeout_s}s")
                self._cond.wait(left)

    def _try_parse_locked(self):
        if len(self._rx) < _HDR:
            return None
        length = frame_size(bytes(self._rx[:_HDR]))  # FrameError -> up
        if len(self._rx) < _HDR + length:
            return None
        hdr = bytes(self._rx[:_HDR])
        payload = bytes(self._rx[_HDR:_HDR + length])
        del self._rx[:_HDR + length]
        return (decode_frame(hdr, payload),)

    def close(self) -> None:
        for end in (self, self.peer):
            if end is None:
                continue
            with end._cond:
                end._closed = True
                end._cond.notify_all()

    @property
    def alive(self) -> bool:
        return not self._closed


class _LoopbackListener(Listener):
    def __init__(self, transport: "LoopbackTransport", address: str) -> None:
        self._transport = transport
        self._address = address
        self._cond = threading.Condition(
            named_lock("net.loopback.listener"))
        self._pending: list = []
        self._closed = False

    def accept(self, timeout_s: Optional[float] = None) -> Conn:
        timeout_s = default_io_timeout_s() if timeout_s is None \
            else timeout_s
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while not self._pending:
                if self._closed:
                    raise TransportError("listener closed")
                left = deadline - time.monotonic()
                if left <= 0:
                    raise WireTimeout(
                        f"accept timed out after {timeout_s}s")
                self._cond.wait(left)
            return self._pending.pop(0)

    def _offer(self, server_end: _LoopbackEnd) -> None:
        with self._cond:
            if self._closed:
                raise TransportError(
                    f"connection refused: {self._address} is closed")
            self._pending.append(server_end)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._transport._unbind(self._address)

    @property
    def address(self) -> str:
        return self._address


class LoopbackTransport(Transport):
    """The in-process twin: same framing, same protocol, no kernel.
    One instance is a private little network — listeners bind
    ``loopback:<n>`` addresses on it and ``connect`` dials them."""

    def __init__(self) -> None:
        self._lock = named_lock("net.loopback.transport")
        self._listeners: Dict[str, _LoopbackListener] = {}
        self._next = 0

    def listen(self) -> Listener:
        with self._lock:
            addr = f"loopback:{self._next}"
            self._next += 1
            lst = _LoopbackListener(self, addr)
            self._listeners[addr] = lst
        return lst

    def _unbind(self, address: str) -> None:
        with self._lock:
            self._listeners.pop(address, None)

    def connect(self, address, timeout_s: Optional[float] = None) -> Conn:
        with self._lock:
            lst = self._listeners.get(address)
        if lst is None:
            raise TransportError(f"connection refused: no listener at "
                                 f"{address!r}")
        client, server = _LoopbackEnd(), _LoopbackEnd()
        client.peer, server.peer = server, client
        lst._offer(server)
        return client


# -- TCP --------------------------------------------------------------------

class _TcpConn(Conn):
    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._closed = False
        # one writer/reader at a time per side; the protocol is
        # request-response so this never contends in steady state
        self._send_lock = named_lock("net.tcp.send")
        self._recv_lock = named_lock("net.tcp.recv")
        self._sock.settimeout(default_io_timeout_s())

    def send_msg(self, obj: Any, timeout_s: Optional[float] = None) -> int:
        return self.send_raw(encode_frame(obj), timeout_s)

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> int:
        with self._send_lock:
            if self._closed:
                raise TransportError("send on a closed TCP connection")
            try:
                self._sock.settimeout(
                    default_io_timeout_s() if timeout_s is None
                    else timeout_s)
                self._sock.sendall(data)
            except (OSError, ValueError) as e:
                raise TransportError(f"TCP send failed: {e}") from e
        return len(data)

    def _read_exact(self, n: int, deadline: float,
                    idle_ok: bool = False) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TransportError("recv timed out mid-frame")
            try:
                self._sock.settimeout(left)
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout as e:
                # a timeout before ANY byte of the frame arrived leaves
                # the stream synced (idle); one mid-frame does not
                if idle_ok and not buf:
                    raise WireTimeout(f"recv timed out: {e}") from e
                raise TransportError(
                    f"recv timed out mid-frame: {e}") from e
            except OSError as e:
                raise TransportError(f"TCP recv failed: {e}") from e
            if not chunk:
                raise TransportError("connection closed by peer")
            buf += chunk
        return bytes(buf)

    def recv_msg(self, timeout_s: Optional[float] = None) -> Any:
        timeout_s = default_io_timeout_s() if timeout_s is None \
            else timeout_s
        with self._recv_lock:
            if self._closed:
                raise TransportError("recv on a closed TCP connection")
            deadline = time.monotonic() + timeout_s
            hdr = self._read_exact(_HDR, deadline, idle_ok=True)
            length = frame_size(hdr)  # FrameError propagates: reset
            payload = self._read_exact(length, deadline)
        return decode_frame(hdr, payload)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def alive(self) -> bool:
        return not self._closed


class _TcpListener(Listener):
    def __init__(self, host: str, port: int) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._closed = False

    def accept(self, timeout_s: Optional[float] = None) -> Conn:
        if self._closed:
            raise TransportError("listener closed")
        try:
            self._sock.settimeout(
                default_io_timeout_s() if timeout_s is None
                else timeout_s)
            sock, _peer = self._sock.accept()
        except socket.timeout as e:
            raise WireTimeout(f"accept timed out: {e}") from e
        except OSError as e:
            raise TransportError(f"accept failed: {e}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return _TcpConn(sock)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def address(self) -> Tuple[str, int]:
        return self._sock.getsockname()


class TcpTransport(Transport):
    """Real sockets on ``host``. ``listen`` binds ``port`` — default 0,
    i.e. the OS assigns an ephemeral port and ``Listener.address``
    reports the ``(host, port)`` actually bound. Servers built on this
    (``ReplicaServer`` / ``RpcIngestServer`` / ``TelemetryServer``)
    therefore never need a pre-picked port: start one, read
    ``.address``, hand it to whoever dials — which is what lets the
    process harness spawn children in parallel without collisions.
    Pass an explicit ``port`` only to pin a deployment-known endpoint.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port

    def listen(self) -> Listener:
        return _TcpListener(self.host, self.port)

    def connect(self, address, timeout_s: Optional[float] = None) -> Conn:
        timeout_s = env_float("REFLOW_NET_CONNECT_TIMEOUT_S") \
            if timeout_s is None else timeout_s
        try:
            sock = socket.create_connection(tuple(address),
                                            timeout=timeout_s)
        except OSError as e:
            raise TransportError(f"connect to {address} failed: {e}") \
                from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return _TcpConn(sock)
