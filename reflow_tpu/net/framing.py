"""Wire framing for the replication transport (docs/guide.md
"Replication over the wire").

One message = one length-prefixed, CRC-protected frame::

    RFNET001 | <u32 payload_len> <u32 crc32(payload)> | payload

The 8-byte magic rides on EVERY frame (not once per stream like the
WAL's segment magic) so a desynchronized byte stream is detected at the
next frame boundary instead of being misparsed as a plausible length.
The payload is a pickled tuple ``(op, *args)`` — the same stance the
WAL takes on disk: pickling is the project's record codec, and both
ends re-verify the CRC before trusting a byte of it.

Shipping-protocol payloads (:class:`~reflow_tpu.wal.ship.Shipment` and
friends) are flattened to plain tuples by ``encode_msg`` and rebuilt by
the endpoint, so the wire never depends on NamedTuple class identity
across processes.

Everything here raises :class:`FrameError` for malformed bytes (a
corrupt or truncated frame — the connection is unsyncable past it) and
:class:`TransportError` for link-level failures (reset, timeout,
refused). Callers treat FrameError as grounds for a reset: with a
length-prefixed stream there is no way to find the next frame after a
bad header.
"""

from __future__ import annotations

import pickle
import struct
import zlib
from typing import Any, Tuple

__all__ = ["FrameError", "TransportError", "WireTimeout", "MAGIC",
           "HEADER", "MAX_FRAME", "encode_frame", "decode_frame",
           "frame_size", "split_frames"]

MAGIC = b"RFNET001"
HEADER = struct.Struct("<II")  # payload_len, crc32(payload)
#: sanity bound mirroring wal.log._MAX_RECORD: a corrupted length
#: prefix must not convince a receiver to buffer gigabytes
MAX_FRAME = 64 << 20


class TransportError(RuntimeError):
    """Link-level failure: connection refused / reset / timed out /
    closed under us. Retryable — the reconnect state machine's input."""


class WireTimeout(TransportError):
    """A blocking wire call ran out its deadline with the link still
    up. Servers treat this as 'idle, keep waiting'; clients treat it
    like any other TransportError (fail, back off, reconnect)."""


class FrameError(TransportError):
    """Malformed frame (bad magic, implausible length, CRC mismatch,
    unpicklable payload). NOT retryable on the same connection: a
    length-prefixed stream cannot re-synchronize past a bad header, so
    the only safe response is a reset."""


def encode_frame(obj: Any) -> bytes:
    """Pickle ``obj`` and wrap it in one framed message."""
    payload = pickle.dumps(obj)
    if len(payload) > MAX_FRAME:
        raise FrameError(f"message of {len(payload)} bytes exceeds the "
                         f"{MAX_FRAME}-byte frame bound")
    return MAGIC + HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def frame_size(header: bytes) -> int:
    """Payload length promised by a ``MAGIC + HEADER`` prefix (the
    receiver reads exactly this many more bytes). Raises
    :class:`FrameError` on bad magic or an implausible length."""
    if len(header) < len(MAGIC) + HEADER.size:
        raise FrameError(f"short frame header ({len(header)} bytes)")
    if header[:len(MAGIC)] != MAGIC:
        raise FrameError(f"bad frame magic {header[:len(MAGIC)]!r}")
    length, _crc = HEADER.unpack_from(header, len(MAGIC))
    if length > MAX_FRAME:
        raise FrameError(f"implausible frame length {length}")
    return length


def decode_frame(header: bytes, payload: bytes) -> Any:
    """Verify and unpickle one frame's payload against its header."""
    length = frame_size(header)
    _len, crc = HEADER.unpack_from(header, len(MAGIC))
    if len(payload) != length:
        raise FrameError(f"truncated frame payload "
                         f"({len(payload)}/{length} bytes)")
    if zlib.crc32(payload) != crc:
        raise FrameError("frame CRC mismatch")
    try:
        return pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 - framed yet unloadable
        raise FrameError(f"unpicklable frame payload ({e})") from e


def split_frames(data: bytes) -> Tuple[list, int]:
    """Walk ``data`` as a run of frames; returns ``(messages,
    consumed)`` where ``consumed < len(data)`` means the tail is an
    incomplete frame (more bytes needed). Raises :class:`FrameError`
    on a malformed complete frame. Loopback conns use this; TCP conns
    read frame-at-a-time off the socket."""
    msgs = []
    off = 0
    hdr = len(MAGIC) + HEADER.size
    while len(data) - off >= hdr:
        length = frame_size(data[off:off + hdr])
        if len(data) - off - hdr < length:
            break
        msgs.append(decode_frame(data[off:off + hdr],
                                 data[off + hdr:off + hdr + length]))
        off += hdr + length
    return msgs, off
