"""RemoteFollower: the shipper's wire-side view of one replica.

Duck-types the follower surface :class:`~reflow_tpu.wal.ship
.SegmentShipper` expects (``subscribe`` / ``bootstrap`` / ``receive``
/ ``name``) over a framed transport connection, and owns the whole
unreliable-link lifecycle so the shipper never sees a socket:

- **Link failures return ``None``** from :meth:`receive` — "no
  progress this pass", categorically different from a protocol
  :class:`ShipNack` (which is the *replica* speaking). The shipper
  skips the follower and retries on its own cadence; NACK counters
  never inflate from weather.
- **Reconnect is a state machine**, not a loop:
  :class:`~reflow_tpu.net.backoff.ReconnectPolicy` (connect → healthy
  → degraded → unreachable) gates every attempt with capped
  exponential backoff + seeded jitter. While a backoff window is open,
  calls return ``None`` immediately — a stalled link never blocks the
  pump thread.
- **Re-handshake after reset is idempotent**: the first exchange on a
  fresh connection is always ``subscribe()``, whose answer is the
  replica's authoritative persisted cursor. :meth:`receive` surfaces
  that as ``ShipNack(cursor, "reconnected: resync")`` so the shipper
  adopts it and re-reads from disk (the WAL is the retransmit buffer)
  instead of blindly resending a chunk the replica may have already
  durably applied (the ack-lost case).

Every roundtrip emits a ``net_send`` trace span and every recovery a
``net_reconnect`` span (``tools/trace_inspect.py`` folds both into its
network section).
"""

from __future__ import annotations

import time
from typing import Any, Optional, Tuple

from reflow_tpu.net.backoff import ReconnectPolicy
from reflow_tpu.net.framing import TransportError
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.obs import trace as _trace
from reflow_tpu.wal.ship import ShipAck, Shipment, ShipNack

__all__ = ["RemoteFollower"]


class RemoteFollower:
    """One replica endpoint as seen from the shipping leader."""

    def __init__(self, transport: Transport, address, *,
                 name: str = "remote", policy: Optional[ReconnectPolicy]
                 = None, io_timeout_s: Optional[float] = None) -> None:
        self.transport = transport
        self.address = address
        self.name = name
        self.policy = policy if policy is not None \
            else ReconnectPolicy(name)
        self.io_timeout_s = io_timeout_s
        self._conn: Optional[Conn] = None
        self.reconnects_total = 0      # successful re-dials after loss
        self.link_failures = 0
        #: replica's clock anchor from the last subscribe handshake
        #: (``obs.wire.clock_anchor`` + rtt_s / wall_offset_s), when the
        #: server sends one; display-only — never used for ordering
        self.anchor: Optional[dict] = None

    # -- connection state (read by ship.py / read.py / wal_inspect) ----

    @property
    def conn_state(self) -> str:
        return self.policy.state

    @property
    def last_backoff_s(self) -> float:
        return self.policy.last_backoff_s

    def transport_snapshot(self) -> dict:
        snap = self.policy.snapshot()
        snap["address"] = str(self.address)
        return snap

    # -- link machinery ------------------------------------------------

    def _fail(self, err: Exception) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self.link_failures += 1
        self.policy.failed()

    def _dial(self) -> Optional[Tuple[int, int]]:
        """Dial + handshake: returns the replica's authoritative cursor
        (or None-cursor for a fresh replica) on success; raises
        :class:`TransportError` on failure. On return ``self._conn``
        is live and subscribed."""
        conn = self.transport.connect(self.address)
        t0 = time.monotonic()
        try:
            conn.send_msg(("subscribe",), self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError:
            conn.close()
            raise
        rtt = time.monotonic() - t0
        if not (isinstance(resp, tuple) and len(resp) >= 2
                and resp[0] == "ok"):
            conn.close()
            raise TransportError(f"bad subscribe response {resp!r}")
        if len(resp) >= 3 and isinstance(resp[2], dict):
            # pre-anchor servers answer a 2-tuple; newer ones piggyback
            # a clock anchor so trace consumers can display this
            # replica's monotonic timestamps on the leader's wall axis
            # (error bounded by rtt/2 — never used for ordering)
            anchor = dict(resp[2])
            anchor["rtt_s"] = rtt
            anchor["wall_offset_s"] = anchor.get("wall", 0.0) - \
                (time.time() - rtt / 2.0)
            self.anchor = anchor
        self._conn = conn
        return resp[1] if resp[1] is None else tuple(resp[1])

    def _roundtrip(self, msg: tuple,
                   cause: Optional[str] = None) -> Any:
        """One request-response on the live connection. Returns the
        reply, or None on a link failure (connection closed, backoff
        scheduled). ``cause`` is echoed into the ``net_send`` span so
        the hop joins its shipment's cross-process causal chain."""
        conn = self._conn
        if conn is None:
            return None
        t0 = time.perf_counter()
        try:
            conn.send_msg(msg, self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError as e:
            self._fail(e)
            if _trace.ENABLED:
                args = {"op": msg[0], "ok": False,
                        "error": str(e)[:120],
                        "state": self.policy.state}
                if cause is not None:
                    args["cause"] = cause
                _trace.evt("net_send", t0, time.perf_counter() - t0,
                           track=f"net/{self.name}", args=args)
            return None
        self.policy.ok()
        if _trace.ENABLED:
            args = {"op": msg[0], "ok": True}
            if cause is not None:
                args["cause"] = cause
            _trace.evt("net_send", t0, time.perf_counter() - t0,
                       track=f"net/{self.name}", args=args)
        return resp

    def _reconnect(self) -> Optional[Tuple[Optional[Tuple[int, int]]]]:
        """One gated reconnect attempt. Returns a 1-tuple holding the
        subscribe cursor on success (so a None cursor is distinguishable
        from 'attempt failed' = None)."""
        if not self.policy.due():
            return None
        t0 = time.perf_counter()
        try:
            cursor = self._dial()
        except TransportError as e:
            self._fail(e)
            if _trace.ENABLED:
                _trace.evt("net_reconnect", t0,
                           time.perf_counter() - t0,
                           track=f"net/{self.name}",
                           args={"ok": False, "error": str(e)[:120],
                                 "state": self.policy.state,
                                 "backoff_s": self.policy.last_backoff_s})
            return None
        recovered = self.policy.ok()
        if recovered:
            self.reconnects_total += 1
        if _trace.ENABLED:
            _trace.evt("net_reconnect", t0, time.perf_counter() - t0,
                       track=f"net/{self.name}",
                       args={"ok": True, "recovered": recovered})
        return (cursor,)

    # -- the follower surface ship.py drives ---------------------------

    def subscribe(self) -> Optional[Tuple[int, int]]:
        """The replica's persisted cursor. Called by ``attach()`` at
        wiring time — a dead link here raises so the operator sees the
        misconfiguration instead of a silently idle follower."""
        if self._conn is None:
            got = self._reconnect()
            if got is None:
                raise TransportError(
                    f"{self.name}: cannot reach {self.address} "
                    f"(state={self.policy.state})")
            return got[0]
        resp = self._roundtrip(("subscribe",))
        if resp is None:
            raise TransportError(f"{self.name}: subscribe failed "
                                 f"(state={self.policy.state})")
        if not (isinstance(resp, tuple) and resp[0] == "ok"):
            raise TransportError(f"bad subscribe response {resp!r}")
        return resp[1] if resp[1] is None else tuple(resp[1])

    def bootstrap(self, ckpt_dir: str) -> Tuple[int, int]:
        resp = self._roundtrip(("bootstrap", ckpt_dir))
        if resp is None:
            raise TransportError(f"{self.name}: bootstrap failed "
                                 f"(state={self.policy.state})")
        if not (isinstance(resp, tuple) and resp[0] == "ok"):
            raise TransportError(f"bootstrap rejected: {resp!r}")
        return tuple(resp[1])

    def receive(self, sh: Shipment):
        """Ship one chunk. Returns :class:`ShipAck` / :class:`ShipNack`
        from the replica, or ``None`` for "no progress" (link down,
        backoff window open, or failed mid-exchange)."""
        if self._conn is None:
            got = self._reconnect()
            if got is None:
                return None
            # fresh link: hand the shipper the replica's authoritative
            # cursor instead of guessing whether our last chunk landed
            return ShipNack(got[0], "reconnected: resync")
        fields = tuple(sh)
        if fields and fields[-1] is None:
            # unstamped shipment: drop the trailing None cause so the
            # wire frame stays byte-identical to the pre-trace protocol
            fields = fields[:-1]
        resp = self._roundtrip(("receive",) + fields, cause=sh.cause)
        if resp is None:
            return None
        if isinstance(resp, tuple) and resp and resp[0] == "ack":
            return ShipAck(tuple(resp[1]), resp[2])
        if isinstance(resp, tuple) and resp and resp[0] == "nack":
            cur = tuple(resp[1]) if resp[1] is not None else None
            return ShipNack(cur, resp[2])
        # ("err", ...) or garbage: treat as link trouble, force rescync
        self._fail(TransportError(f"bad receive response {resp!r}"))
        return None

    def ping(self) -> Optional[dict]:
        """Replica liveness + horizon probe; None when unreachable."""
        if self._conn is None:
            got = self._reconnect()
            if got is None:
                return None
        resp = self._roundtrip(("ping",))
        if isinstance(resp, tuple) and len(resp) == 2 \
                and resp[0] == "ok":
            return resp[1]
        return None

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
