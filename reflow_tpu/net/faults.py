"""FaultyTransport: deterministic wire chaos over any transport.

Wraps a :class:`~reflow_tpu.net.transport.Transport` and injects the
faults a real network has — drop, delay, duplicate, reorder,
truncate/corrupt, one-way partition, connection reset — from a seeded
:class:`~reflow_tpu.utils.faults.WireFaults` schedule (the policy
object; this module is only the mechanism). Injection happens
client-side at message granularity, so the exact same chaos plays out
over :class:`LoopbackTransport` and :class:`TcpTransport`.

How each fault maps onto a strict request-response stream:

- **drop (c2s)** — the request never transmits; the caller sees a
  :class:`TransportError` as a timeout would deliver one, just without
  burning the real timeout.
- **drop (s2c)** — the request transmits (the server APPLIES it), the
  response is read off the wire and discarded to keep the stream
  frame-synced, then the caller gets a :class:`TransportError`. This is
  the ack-lost case that forces a duplicate retransmission.
- **duplicate** — the framed request is written twice; the extra
  response is drained on a later receive so pairing never skews.
- **reorder** — the previous request is retransmitted *before* the
  current one (out-of-order duplicate delivery, the only reordering a
  windowless request-response protocol can observe); the extra response
  is drained like a duplicate's.
- **corrupt (frame)** — one seeded bit of the framed bytes flips in
  flight; the receiver's frame CRC (or magic check) fails and the
  connection resets.
- **corrupt (payload)** — one seeded bit flips inside the message's
  embedded WAL bytes *before* framing, so the frame verifies but the
  replica's record-CRC check NACKs the shipment whole — the deep
  end-to-end integrity path.
- **partition** — scripted, directional: ``c2s`` makes requests (and
  new dials) vanish; ``s2c`` lets requests through but eats responses.
- **reset** — the connection is closed under the caller mid-exchange.

Response-pairing safety: a drained or mis-paired response can only be a
``ShipAck``/``ShipNack``, and both carry the receiver's *authoritative*
cursor at response time — adopting one is always safe, which is why
the shipping protocol tolerates this whole menu without sequence
numbers.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from reflow_tpu.net.framing import TransportError, encode_frame
from reflow_tpu.net.transport import (Conn, Listener, Transport,
                                      default_io_timeout_s)
from reflow_tpu.utils.faults import WireFaults

__all__ = ["FaultyTransport", "FaultyConn"]

#: cap on a single injected delay so a hostile schedule cannot wedge a
#: pump thread past its link timeout
_MAX_DELAY_S = 0.25


def _flip_payload_bytes(faults: WireFaults, msg: Any) -> Optional[Any]:
    """Flip one bit inside the largest bytes field of a message tuple
    (the shipped WAL chunk). Returns the mangled message, or None when
    the message carries no meaningful byte payload."""
    if not isinstance(msg, tuple):
        return None
    best, best_i = None, -1
    for i, v in enumerate(msg):
        if isinstance(v, (bytes, bytearray)) and len(v) >= 16:
            if best is None or len(v) > len(best):
                best, best_i = v, i
    if best is None:
        return None
    out = list(msg)
    out[best_i] = faults.flip(bytes(best))
    return tuple(out)


class FaultyConn(Conn):
    """One chaotic connection: consults the :class:`WireFaults`
    schedule on every message. Client-side only — servers always get a
    clean conn and the chaos happens on the way in/out of it."""

    def __init__(self, inner: Conn, faults: WireFaults) -> None:
        self._inner = inner
        self._faults = faults
        self._stale = 0          # extra responses to drain (dup/reorder)
        self._eat_response = False   # s2c drop: discard the next one
        self._last_frame: Optional[bytes] = None

    def send_msg(self, obj: Any, timeout_s: Optional[float] = None) -> int:
        f = self._faults
        if f.take_scripted_reset():
            self._inner.close()
            raise TransportError("injected: connection reset")
        if f.is_partitioned("c2s"):
            f.count_partitioned()
            raise TransportError("injected: partitioned (c2s)")
        d = f.delay_roll()
        if d > 0.0:
            time.sleep(min(d, _MAX_DELAY_S))
        roll = f.decide()
        if roll == "drop_c2s":
            return 0  # vanished in flight; the recv will time out fast
        if roll == "reset":
            self._inner.close()
            raise TransportError("injected: connection reset")
        if roll == "corrupt_frame":
            frame = f.flip(encode_frame(obj))
            self._last_frame = None
            return self._inner.send_raw(frame, timeout_s)
        if roll == "corrupt_payload":
            mangled = _flip_payload_bytes(f, obj)
            if mangled is None:  # nothing to corrupt deeply: hit frame
                frame = f.flip(encode_frame(obj))
                self._last_frame = None
                return self._inner.send_raw(frame, timeout_s)
            frame = encode_frame(mangled)
            self._last_frame = frame
            return self._inner.send_raw(frame, timeout_s)
        frame = encode_frame(obj)
        n = 0
        if roll == "reorder" and self._last_frame is not None:
            n += self._inner.send_raw(self._last_frame, timeout_s)
            self._stale += 1
        n += self._inner.send_raw(frame, timeout_s)
        if roll == "dup":
            n += self._inner.send_raw(frame, timeout_s)
            self._stale += 1
        if roll == "drop_s2c":
            self._eat_response = True
        self._last_frame = frame
        return n

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> int:
        return self._inner.send_raw(data, timeout_s)

    def recv_msg(self, timeout_s: Optional[float] = None) -> Any:
        timeout_s = default_io_timeout_s() if timeout_s is None \
            else timeout_s
        while self._stale > 0:
            self._stale -= 1
            self._inner.recv_msg(timeout_s)  # drain; pairing stays 1:1
        if self._faults.is_partitioned("s2c"):
            self._faults.count_partitioned()
            # the server DID apply; eat its answer to stay frame-synced
            try:
                self._inner.recv_msg(timeout_s)
            except TransportError:
                pass
            raise TransportError("injected: partitioned (s2c)")
        if self._eat_response:
            self._eat_response = False
            try:
                self._inner.recv_msg(timeout_s)
            except TransportError:
                pass
            raise TransportError("injected: response dropped (s2c)")
        return self._inner.recv_msg(timeout_s)

    def close(self) -> None:
        self._inner.close()

    @property
    def alive(self) -> bool:
        return self._inner.alive


class FaultyTransport(Transport):
    """Compose chaos over any transport: ``connect`` wraps the dialed
    conn in a :class:`FaultyConn`; ``listen`` passes through untouched
    (injection is single-ended by design — double-ending would square
    every probability)."""

    def __init__(self, inner: Transport, faults: WireFaults) -> None:
        self.inner = inner
        self.faults = faults

    def listen(self) -> Listener:
        return self.inner.listen()

    def connect(self, address, timeout_s: Optional[float] = None) -> Conn:
        if self.faults.is_partitioned("c2s"):
            self.faults.count_partitioned()
            raise TransportError("injected: partitioned (c2s, dial)")
        if self.faults.take_scripted_reset():
            raise TransportError("injected: connection refused (reset)")
        return FaultyConn(self.inner.connect(address, timeout_s),
                          self.faults)
