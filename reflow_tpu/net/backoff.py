"""Per-link reconnect state machine: capped exponential backoff with
deterministic jitter (docs/guide.md "Replication over the wire").

One :class:`ReconnectPolicy` instance tracks one follower link through
the connection lifecycle::

    connecting -> healthy -> degraded -> unreachable
         ^___________________________________|   (on the next success)

State transitions are driven only by :meth:`ok` / :meth:`failed`, and
time only flows through the injected ``clock`` callable — so tests run
the whole machine on a fake clock with zero real sleeps
(tests/test_net.py). Thresholds and delays come from the
``REFLOW_NET_*`` knobs; jitter is drawn from a per-link RNG seeded by
``(seed, link name)`` so two runs with the same seed reconnect on the
same schedule.

The shipper never sleeps on this object: it polls :meth:`due` from its
existing pump cadence and skips the link while a backoff window is
open. That keeps one stalled follower from blocking the others — the
same reasoning as the per-follower cursors in ``wal/ship.py``.
"""

from __future__ import annotations

import random
import time
import zlib
from typing import Callable, Optional

from reflow_tpu.utils.config import env_float, env_int

__all__ = ["ReconnectPolicy", "STATE_CONNECTING", "STATE_HEALTHY",
           "STATE_DEGRADED", "STATE_UNREACHABLE"]

STATE_CONNECTING = "connecting"
STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_UNREACHABLE = "unreachable"


class ReconnectPolicy:
    """Failure-count state machine + backoff scheduler for one link.

    Not thread-safe by itself: the owning shipper/read-tier already
    serializes per-follower work, and tests drive it single-threaded.
    """

    def __init__(self, name: str, *,
                 base_s: Optional[float] = None,
                 cap_s: Optional[float] = None,
                 jitter: Optional[float] = None,
                 degraded_after: Optional[int] = None,
                 unreachable_after: Optional[int] = None,
                 seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.base_s = env_float("REFLOW_NET_BACKOFF_BASE_S") \
            if base_s is None else base_s
        self.cap_s = env_float("REFLOW_NET_BACKOFF_CAP_S") \
            if cap_s is None else cap_s
        self.jitter = env_float("REFLOW_NET_BACKOFF_JITTER") \
            if jitter is None else jitter
        self.degraded_after = env_int("REFLOW_NET_DEGRADED_AFTER") \
            if degraded_after is None else degraded_after
        self.unreachable_after = env_int("REFLOW_NET_UNREACHABLE_AFTER") \
            if unreachable_after is None else unreachable_after
        if seed is None:
            seed = env_int("REFLOW_NET_FAULT_SEED")
        # crc32, not hash(): str hashing is salted per process and the
        # schedule must replay identically under the same seed
        self._rng = random.Random((seed << 32)
                                  ^ zlib.crc32(name.encode("utf-8")))
        self._clock = clock
        self.failures = 0          # consecutive, reset on success
        self.reconnects = 0        # successes that ended a failure run
        self.last_backoff_s = 0.0  # most recent scheduled delay
        self._retry_at = clock()   # next attempt allowed at this time
        self._state = STATE_CONNECTING

    @property
    def state(self) -> str:
        return self._state

    def ok(self) -> bool:
        """Record a successful exchange; returns True when this success
        ended a failure run (i.e. the link just *re*connected)."""
        recovered = self.failures > 0 or self._state == STATE_CONNECTING
        was_down = self.failures > 0
        if was_down:
            self.reconnects += 1
        self.failures = 0
        self.last_backoff_s = 0.0
        self._retry_at = self._clock()
        self._state = STATE_HEALTHY
        return recovered and was_down

    def failed(self) -> float:
        """Record a link failure; schedules the next attempt and
        returns the chosen backoff delay in seconds."""
        self.failures += 1
        if self.failures >= self.unreachable_after:
            self._state = STATE_UNREACHABLE
        elif self.failures >= self.degraded_after:
            self._state = STATE_DEGRADED
        raw = min(self.cap_s, self.base_s * (2 ** (self.failures - 1)))
        factor = 1.0 + self.jitter * self._rng.uniform(-1.0, 1.0)
        self.last_backoff_s = raw * factor
        self._retry_at = self._clock() + self.last_backoff_s
        return self.last_backoff_s

    def due(self) -> bool:
        """May the next attempt go out yet? (The caller polls this from
        its pump loop instead of sleeping.)"""
        return self._clock() >= self._retry_at

    def seconds_until_due(self) -> float:
        return max(0.0, self._retry_at - self._clock())

    def snapshot(self) -> dict:
        """State for ship-state.json's transport section and the
        ``replica.<name>.conn_state`` gauge."""
        return {
            "state": self._state,
            "failures": self.failures,
            "reconnects": self.reconnects,
            "last_backoff_s": round(self.last_backoff_s, 6),
        }
