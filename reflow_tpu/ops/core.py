"""Core operator definitions + exact host-side incremental semantics.

Every op implements:

- ``arity``: number of input ports.
- ``initial_state()``: host-side state (the TPU executor builds its own
  device state; see ``executors/tpu.py``).
- ``apply(state, in_batches) -> out_batch``: consume one tick's deltas on
  each port, mutate/replace state, emit output deltas. Must satisfy the
  incremental-vs-full oracle property (SURVEY.md §4b): folding the emitted
  deltas equals recomputing the op on the fully accumulated input.

Ops are data: the graph stores them; executors interpret or lower them.
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter, defaultdict
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from reflow_tpu.delta import (DeltaBatch, Spec, _hashable,
                              counter_to_batch)

__all__ = ["Op", "Map", "Filter", "GroupBy", "Reduce", "Join", "Union", "REDUCERS"]


class Op:
    """Base operator. Subclasses are declarative; executors do the work."""

    arity: int = 1
    kind: str = "op"

    def initial_state(self) -> Any:
        return None

    def out_spec(self, in_specs: Sequence[Spec]) -> Spec:
        return in_specs[0]

    def apply(self, state: Any, in_batches: Sequence[DeltaBatch]) -> DeltaBatch:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class Map(Op):
    """Pure per-row value transform; key and weight preserved.

    ``fn(value) -> value'``. If ``vectorized``, ``fn`` is applied to the
    whole values column at once (NumPy on CPU, jax.Array on TPU); otherwise
    it is applied per row on CPU and wrapped in ``jax.vmap`` on TPU.

    ``params`` (optional) is a pytree of ARRAYS the transform closes over
    logically but receives as an explicit first argument: ``fn(params,
    value)``. On device executors the pytree is held as op state and flows
    into the compiled tick program as an *argument*, never a traced
    constant — so the program size is independent of the model size and
    params can be swapped without recompiling (VERDICT r2 #2: a ViT-B
    embedded as constants produced a ~350MB HLO). Static configuration
    (python ints driving reshapes) does NOT belong in ``params``; close
    ``fn`` over it.
    """

    kind = "map"

    def __init__(self, fn: Callable, *, vectorized: bool = False,
                 linear: bool = False, out_spec: Optional[Spec] = None,
                 params: Any = None, param_specs: Any = None):
        self.fn = fn
        self.vectorized = vectorized
        #: optional pytree of jax.sharding.PartitionSpec matching
        #: ``params``: under a ShardedTpuExecutor with a model axis, the
        #: params shard per these specs instead of replicating, and
        #: ``fn`` receives its LOCAL shard inside shard_map — the fn is
        #: then responsible for the model-axis collectives (e.g.
        #: models.vit.vit_forward_tp's two psums per block). This is the
        #: tensor-parallel seam for models too large for one chip's HBM.
        self.param_specs = param_specs
        #: declares fn linear (fn(a·x + b·y) == a·fn(x) + b·fn(y), so
        #: fn(0) == 0). Enables the fused delta-vector fixpoint lowering
        #: for loop regions whose operator chain is linear end to end
        #: (see executors/linear_fixpoint.py).
        self.linear = linear
        self.params = params
        self._out_spec = out_spec

    def out_spec(self, in_specs):
        return self._out_spec if self._out_spec is not None else in_specs[0]

    def apply(self, state, in_batches):
        (b,) = in_batches
        if len(b) == 0:
            return DeltaBatch.empty(self._out_spec)
        fn = self.fn if self.params is None else (
            lambda *cols: self.fn(self.params, *cols))
        if self.vectorized:
            vals = np.asarray(fn(b.values))
        else:
            vals = np.array([fn(v) for v in b.values], dtype=object)
        return DeltaBatch(b.keys, vals, b.weights)


class Filter(Op):
    """Keep rows where ``pred(value)`` holds; key/weight preserved.

    Same vectorization contract as :class:`Map`.
    """

    kind = "filter"

    def __init__(self, pred: Callable, *, vectorized: bool = False):
        self.pred = pred
        self.vectorized = vectorized

    def apply(self, state, in_batches):
        (b,) = in_batches
        if len(b) == 0:
            return b
        if self.vectorized:
            mask = np.asarray(self.pred(b.values), dtype=bool)
        else:
            mask = np.array([bool(self.pred(v)) for v in b.values])
        return DeltaBatch(b.keys[mask], b.values[mask], b.weights[mask])


class GroupBy(Op):
    """Re-key rows: ``key' = key_fn(key, value)``; value/weight preserved
    unless ``value_fn`` is given.

    Feeds :class:`Reduce` (SURVEY.md §2 item 6). On TPU a re-key is what
    triggers cross-shard routing (``all_to_all`` on the key axis).
    """

    kind = "groupby"

    def __init__(self, key_fn: Callable, value_fn: Optional[Callable] = None,
                 *, vectorized: bool = False, out_spec: Optional[Spec] = None,
                 stable_key: bool = False):
        self.key_fn = key_fn
        self.value_fn = value_fn
        self.vectorized = vectorized
        self._out_spec = out_spec
        #: DECLARATION (unchecked contract): inside a declared-linear loop
        #: region, ``key_fn``'s output does not depend on the loop/left
        #: value — only on the input key and the right-side (arena) value
        #: components of the merged row (e.g. PageRank's dst, read from
        #: the edge). The fused fixpoint then precomputes each arena
        #: row's destination at CSR-build time and runs its dense tier as
        #: a destination-SORTED segment sum instead of a random
        #: scatter-add (~30% cheaper at 1M rows, measured v5e).
        self.stable_key = stable_key

    def out_spec(self, in_specs):
        if self._out_spec is not None:
            return self._out_spec
        # re-keying can collapse distinct keys: uniqueness is NOT preserved
        return dataclasses.replace(in_specs[0], unique=False)

    def apply(self, state, in_batches):
        (b,) = in_batches
        if len(b) == 0:
            return b
        if self.vectorized:
            keys = np.asarray(self.key_fn(b.keys, b.values))
            vals = (np.asarray(self.value_fn(b.keys, b.values))
                    if self.value_fn else b.values)
        else:
            keys = np.array([self.key_fn(k, v) for k, v in zip(b.keys, b.values)],
                            dtype=object)
            vals = (np.array([self.value_fn(k, v) for k, v in zip(b.keys, b.values)],
                             dtype=object)
                    if self.value_fn else b.values)
        return DeltaBatch(keys, vals, b.weights)


# -- Reduce ---------------------------------------------------------------

def _wv(v, w):
    """Weighted value; vector values (stored as tuples) go through numpy."""
    if isinstance(v, tuple):
        return np.asarray(v, np.float64) * w
    return v * w


def _agg_sum(ms: Counter):
    return sum(_wv(v, w) for v, w in ms.items())


def _agg_count(ms: Counter) -> int:
    return sum(ms.values())


def _agg_mean(ms: Counter):
    n = sum(ms.values())
    return _agg_sum(ms) / n


def _agg_min(ms: Counter):
    return min(v for v, w in ms.items() if w > 0)


def _agg_max(ms: Counter):
    return max(v for v, w in ms.items() if w > 0)


_EMPTY_MS: Counter = Counter()

#: name -> (aggregate_fn, linear?) — linear reducers lower to pure
#: scatter-add on device; non-linear ones need multiset state (host) or
#: recompute-on-retract (device, bounded key groups).
REDUCERS = {
    "sum": (_agg_sum, True),
    "count": (_agg_count, True),
    "mean": (_agg_mean, True),
    "min": (_agg_min, False),
    "max": (_agg_max, False),
}


class _NoAgg:
    """Sentinel: the group has no defined aggregate (empty / degenerate)."""

    def __repr__(self):
        return "<no-agg>"


_NO_AGG = _NoAgg()


class Reduce(Op):
    """Incremental keyed aggregation with persistent per-key state.

    Emits the *change in the aggregate*: retract the previously **emitted**
    aggregate, insert the new one (each weight ±1); a group appearing emits
    only the insert, a group vanishing only the retract. ``tol`` suppresses
    emission when a float aggregate moved by ≤ tol — this is what lets
    iterative graphs (PageRank) quiesce. Retractions are always against the
    last emitted value (not the raw state aggregate), so tol-suppressed
    drift never corrupts downstream views.

    Oracle state: ``{key: (Counter(value -> weight), last_emitted_agg)}`` —
    exact for all reducers including non-invertible min/max. Multisets with
    negative or mixed-sign multiplicities (legal transients in the
    differential algebra) are preserved, not discarded.
    """

    kind = "reduce"

    def __init__(self, how: str = "sum", *, tol: float = 0.0,
                 out_spec: Optional[Spec] = None, candidates: int = 8):
        if how not in REDUCERS:
            raise ValueError(f"unknown reducer {how!r}; have {sorted(REDUCERS)}")
        if candidates < 1:
            raise ValueError(f"candidates must be >= 1, got {candidates}")
        self.how = how
        self.tol = tol
        #: device min/max only: per-key candidate-buffer depth. The device
        #: path keeps the ``candidates`` best distinct values per key with
        #: their multiset weights, so retractions stay EXACT until a key's
        #: churn exceeds the buffer — then a sticky error raises at the
        #: next sync (loud, never a wrong aggregate). The host oracle is
        #: always exact. Irrelevant for linear reducers.
        self.candidates = candidates
        self._out_spec = out_spec

    def out_spec(self, in_specs):
        spec = self._out_spec if self._out_spec is not None else in_specs[0]
        return spec.as_unique()  # one aggregate row per key

    def initial_state(self):
        return {}

    def _aggregate(self, ms: Counter):
        """Aggregate of a (possibly mixed-sign) multiset, or _NO_AGG.

        Linear reducers define group existence via their *linear
        observables* (net count Σw, weighted sum Σw·v): a group whose
        observables are all zero is indistinguishable from an empty group
        downstream, so both host and device treat it as vanished. This
        keeps the cpu-vs-tpu differential contract exact (the device path
        only keeps the linear observables, never the full multiset).
        min/max keep true multiset existence (host-only reducers).
        """
        if not ms:
            return _NO_AGG
        if self.how in ("min", "max"):
            if not any(w > 0 for w in ms.values()):
                return _NO_AGG
        elif self.how in ("mean", "count"):
            if sum(ms.values()) == 0:
                return _NO_AGG
        fn, _ = REDUCERS[self.how]
        agg = fn(ms)
        if self.how == "sum":
            if (sum(ms.values()) == 0 and
                    bool(np.all(np.asarray(agg) == 0))):
                return _NO_AGG
        if isinstance(agg, np.ndarray):
            # vector aggregate: keep it hashable for the emission multiset
            agg = tuple(agg.tolist())
        return agg

    def apply(self, state, in_batches):
        (b,) = in_batches
        tick: dict = defaultdict(Counter)
        for k, v, w in b.rows():
            tick[k][v] += w
        out: Counter = Counter()
        for k, dms in tick.items():
            old_ms, emitted = state.get(k, (_EMPTY_MS, _NO_AGG))
            new_ms = Counter(old_ms)
            for v, w in dms.items():
                new_ms[v] += w
            new_ms = Counter({v: w for v, w in new_ms.items() if w != 0})
            new_agg = self._aggregate(new_ms)
            if emitted is _NO_AGG and new_agg is not _NO_AGG:
                out[(k, new_agg)] += 1
                emitted = new_agg
            elif emitted is not _NO_AGG and new_agg is _NO_AGG:
                out[(k, emitted)] -= 1
                emitted = _NO_AGG
            elif emitted is not _NO_AGG and not _close(emitted, new_agg, self.tol):
                out[(k, emitted)] -= 1
                out[(k, new_agg)] += 1
                emitted = new_agg
            if new_ms or emitted is not _NO_AGG:
                state[k] = (new_ms, emitted)
            else:
                state.pop(k, None)
        return counter_to_batch(out, like=b)


def _close(a, b, tol: float) -> bool:
    if isinstance(a, tuple) or isinstance(b, tuple):
        if tol <= 0.0:
            return a == b
        try:
            av = np.asarray(a, np.float64)
            bv = np.asarray(b, np.float64)
            ok = (np.abs(av - bv) <= tol) | (np.isnan(av) & np.isnan(bv))
            return bool(np.all(ok))
        except (TypeError, ValueError):
            return a == b
    if tol <= 0.0:
        return a == b
    try:
        return bool(abs(a - b) <= tol) or (isinstance(a, float) and isinstance(b, float)
                                           and math.isnan(a) and math.isnan(b))
    except TypeError:
        return a == b


def _merge_arg(v):
    """Host-boundary form of a join value handed to ``merge``: FLAT tuples
    of numeric scalars become 1-D f64 arrays (the array-like contract);
    anything else — scalars, strings, arrays, and ANY nested tuple —
    passes through unchanged. The flatness test is explicit (ADVICE r3):
    ``np.asarray`` would silently coerce a rectangular numeric nest (e.g.
    a default join's ``(va, vb)`` pair of equal-length vectors) into a
    2-D array, handing a downstream custom merge a different shape than
    the nested-tuple contract documents."""
    if isinstance(v, tuple) and all(
            isinstance(x, (int, float, bool, np.number, np.bool_))
            for x in v):
        return np.asarray(v, np.float64)
    return v


class Join(Op):
    """Incremental binary equi-join with per-side multiset state.

    δ(A⋈B) = δA⋈B + (A+δA)⋈δB. Output rows are
    ``(key, merge(key, va, vb))`` with weight ``wa*wb``; ``merge`` defaults
    to the tuple ``(va, vb)``.

    Merge contract: values arrive ARRAY-LIKE on both executors — per row
    on the CPU oracle (scalars stay scalars; vector values arrive as 1-D
    float64 arrays), batched with a leading row axis on the device path.
    Elementwise expressions (``va + vb``) therefore behave identically on
    both; a merge that needs to tell the forms apart branches on ``ndim``
    (see ``workloads/pagerank._contrib_merge``). Host multiset state
    stays hashable internally (tuples) — the conversion happens at this
    call boundary, both ways.
    """

    kind = "join"
    arity = 2

    def __init__(self, merge: Optional[Callable] = None, *,
                 out_spec: Optional[Spec] = None, arena_capacity: int = 1 << 16,
                 linear_left: bool = False,
                 left_arena_capacity: Optional[int] = None,
                 product_slack: int = 4):
        self.merge = merge
        self._out_spec = out_spec
        #: device-path right-side arena capacity (rows); the TPU executor
        #: stores the right collection as a fixed-size append log.
        self.arena_capacity = arena_capacity
        #: MULTISET-left device path only (left Spec not unique): the left
        #: side is a second append arena of this capacity (defaults to
        #: arena_capacity), and each tick's delta×arena products run at a
        #: static budget of ``product_slack x delta_capacity`` pair slots
        #: per side — a true pair count beyond the budget sets the sticky
        #: error (loud, never truncation). Unique-left joins ignore both.
        self.left_arena_capacity = left_arena_capacity
        self.product_slack = product_slack
        #: declares ``merge(k, va, vb)`` linear in ``va`` (so
        #: ``merge(k, 0, vb)`` zeroes every va-dependent component), and —
        #: if a GroupBy consumes this join — that its ``key_fn``/any
        #: va-independent uses read only components that survive
        #: ``merge(k, 0, vb)`` unchanged. Enables the fused delta-vector
        #: fixpoint lowering (executors/linear_fixpoint.py).
        self.linear_left = linear_left

    def out_spec(self, in_specs):
        if self._out_spec is not None:
            return self._out_spec
        return in_specs[0]

    def initial_state(self):
        return (defaultdict(Counter), defaultdict(Counter))

    def _emit(self, out: Counter, k, va, wa, vb, wb):
        if self.merge is None:
            out[(k, (va, vb))] += wa * wb
            return
        # NUMERIC vector values live as hashable TUPLES in the host
        # multiset state; the device path hands merge jax ARRAYS. Convert
        # at the boundary both ways so one array-style merge (e.g.
        # ``lambda k, va, vb: va + vb`` meaning elementwise) serves both
        # executors — without this, tuple + tuple would concatenate.
        # Non-numeric / nested tuples (host-only graphs: strings, a
        # default join's (va, vb) pairs) pass through untouched.
        v = self.merge(k, _merge_arg(va), _merge_arg(vb))
        if isinstance(v, np.ndarray):
            v = _hashable(v)
        out[(k, v)] += wa * wb

    def apply(self, state, in_batches):
        left, right = state
        da, db = in_batches
        out: Counter = Counter()
        # δA ⋈ B (old B)
        for k, va, wa in da.rows():
            for vb, wb in right[k].items():
                if wb:
                    self._emit(out, k, va, wa, vb, wb)
        # fold δA into A
        for k, va, wa in da.rows():
            left[k][va] += wa
            if left[k][va] == 0:
                del left[k][va]
            if not left[k]:
                del left[k]
        # (A + δA) ⋈ δB
        for k, vb, wb in db.rows():
            for va, wa in left[k].items():
                if wa:
                    self._emit(out, k, va, wa, vb, wb)
        # fold δB into B
        for k, vb, wb in db.rows():
            right[k][vb] += wb
            if right[k][vb] == 0:
                del right[k][vb]
            if not right[k]:
                del right[k]
        return counter_to_batch(out, like=da if len(da) else db)


class Union(Op):
    """Multiset union (addition) of n same-spec delta streams."""

    kind = "union"

    def __init__(self, arity: int = 2):
        self.arity = arity

    def out_spec(self, in_specs):
        # merged streams can collide on keys: uniqueness is NOT preserved
        return dataclasses.replace(in_specs[0], unique=False)

    def apply(self, state, in_batches):
        return DeltaBatch.concat(in_batches)
