"""Operator library: Map, Filter, GroupBy, Reduce, Join, Union.

SURVEY.md §2 items 2–6. Each op defines pure functional incremental
semantics ``(state, in_deltas) -> (state', out_deltas)`` over the multiset
delta algebra (see ``delta.py``). The definitions here are the host-side
oracle semantics (exact, dict/Counter-based); the TPU executor lowers the
same ops to padded device arrays + segment/collective primitives
(``executors/tpu.py``) and is differentially tested against these.
"""

from reflow_tpu.ops.core import (
    Op,
    Map,
    Filter,
    GroupBy,
    Reduce,
    Join,
    Union,
    REDUCERS,
)
from reflow_tpu.ops.knn import KnnIndex

__all__ = ["Op", "Map", "Filter", "GroupBy", "Reduce", "Join", "Union",
           "KnnIndex", "REDUCERS"]
