"""KnnIndex: incremental k-nearest-neighbour maintenance (config 4).

The k-NN re-index workload (BASELINE.md: "k-NN re-index on 1Mx768
embedding deltas — vmapped cosine, Pallas top-k") as a first-class
operator, demonstrating the op-extension seam: a stateful binary op with
its exact host semantics here and a device lowering in
``executors/lowerings.py`` (cosine scores on the MXU, Pallas top-k).

Semantics
---------
Inputs: port 0 = query deltas {qid: vec}, port 1 = corpus deltas
{did: vec}; weights +-1 insert/retract (an update is retract + insert —
re-inserting a live id without retracting it first is undefined).
Maintains, per live query, the top-k corpus ids by cosine similarity.
Emits Reduce-style retract-old/insert-new rows keyed by query id; the
value is a ``[k, 2]`` float32 array of (doc_id, score) rows, padded with
(-1, NEG) when fewer than k docs are live — so the collection stays
unique-keyed and telescopes.

Ties resolve to the lowest doc id (both executors). Exact float ties may
still order differently across executors when scores are computed in
different precisions; use real-valued embeddings in differential tests.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec, counter_to_batch
from reflow_tpu.ops.core import Op

__all__ = ["KnnIndex", "NEG"]

NEG = float(np.finfo(np.float32).min)


def _normalize(v: np.ndarray) -> np.ndarray:
    n = float(np.linalg.norm(v))
    return v / n if n > 0 else v


class KnnIndex(Op):
    kind = "knn"
    arity = 2

    def __init__(self, k: int, dim: int, *, out_spec: Optional[Spec] = None,
                 scan_chunk: int = 8192, precision: str = "highest"):
        self.k = k
        self.dim = dim
        self._out_spec = out_spec
        #: device path: corpus chunk size for the streaming top-k scan
        self.scan_chunk = scan_chunk
        #: MXU input precision for the scoring matmuls. "highest" keeps
        #: f32 (bf16x3 passes) so scores match the host oracle to ~1e-6;
        #: "default" allows bf16 inputs (~1e-3 relative — fine for ANN
        #: recall, 3x faster on the MXU)
        self.precision = precision

    def out_spec(self, in_specs):
        if self._out_spec is not None:
            return self._out_spec
        return Spec((self.k, 2), np.float32,
                    key_space=in_specs[0].key_space, unique=True)

    def initial_state(self):
        return {"queries": {}, "docs": {}, "emitted": {}}

    # -- exact host semantics (the oracle) ---------------------------------

    @staticmethod
    def _corpus(docs: dict):
        """(ids sorted ascending, stacked matrix) — built once per tick."""
        if not docs:
            return None
        ids = np.array(sorted(docs), dtype=np.int64)
        mat = np.stack([docs[int(i)] for i in ids])
        return ids, mat

    def _topk_row(self, qvec: np.ndarray, corpus) -> np.ndarray:
        row = np.full((self.k, 2), NEG, np.float32)
        row[:, 0] = -1.0
        if corpus is not None:
            ids, mat = corpus
            scores = mat @ qvec
            # stable sort on id-ascending corpus: ties -> lowest doc id
            take = np.argsort(-scores, kind="stable")[:self.k]
            m = len(take)
            row[:m, 0] = ids[take].astype(np.float32)
            row[:m, 1] = scores[take].astype(np.float32)
        return row

    def apply(self, state, in_batches):
        dq, dd = in_batches
        queries, docs, emitted = (state["queries"], state["docs"],
                                  state["emitted"])
        for kq, v, w in zip(dq.keys, dq.values, dq.weights):
            if w > 0:
                queries[int(kq)] = _normalize(np.asarray(v, np.float32))
            elif w < 0:
                queries.pop(int(kq), None)
        doc_change = len(dd) > 0
        for kd, v, w in zip(dd.keys, dd.values, dd.weights):
            if w > 0:
                docs[int(kd)] = _normalize(np.asarray(v, np.float32))
            elif w < 0:
                docs.pop(int(kd), None)

        affected = set(queries) if doc_change else \
            {int(kq) for kq in dq.keys}
        affected |= {q for q in emitted if q not in queries}
        from collections import Counter

        out: Counter = Counter()
        corpus = self._corpus(docs)
        for q in sorted(affected):
            old = emitted.get(q)
            new = (self._topk_row(queries[q], corpus)
                   if q in queries else None)
            if old is not None and (new is None or
                                    not np.array_equal(old, new)):
                out[(q, tuple(map(tuple, old.tolist())))] -= 1
                emitted.pop(q, None)
            if new is not None and (old is None or
                                    not np.array_equal(old, new)):
                out[(q, tuple(map(tuple, new.tolist())))] += 1
                emitted[q] = new
        like = DeltaBatch(
            np.empty(0, np.int64),
            np.empty((0, self.k, 2), np.float32),
            np.empty(0, np.int64))
        batch = counter_to_batch(out, like=like)
        if len(batch) and batch.values.dtype == object:
            batch = DeltaBatch(
                batch.keys,
                np.array([np.array(v, np.float32) for v in batch.values]),
                batch.weights)
        return batch
