"""Pallas TPU kernels for the hot ops (SURVEY.md §2 item 14).

Each kernel ships with a pure-XLA fallback used on non-TPU backends, so
the same graph runs under the CPU-mesh test harness.
"""

from reflow_tpu.kernels.topk import chunked_corpus_topk, topk

__all__ = ["topk", "chunked_corpus_topk"]
