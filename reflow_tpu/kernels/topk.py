"""Top-k: Pallas TPU kernel + XLA fallback (SURVEY.md §7.10).

The k-NN workload's hot op: row-wise top-k over a scores matrix. On TPU a
Pallas kernel keeps the whole row block in VMEM and does k unrolled
(max, first-argmax, mask) sweeps on the VPU — for the small k of k-NN
re-indexing this beats a full sort, and the scores never round-trip to
HBM between sweeps. Off-TPU (the CPU-mesh test harness) it falls back to
``jax.lax.top_k``, which implements the same tie-break (first index wins).

``chunked_corpus_topk`` is the streaming form for corpora whose scores
matrix would not fit memory: matmul one corpus chunk at a time on the MXU
and fold it into a running (values, ids) top-k carry.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["topk", "chunked_corpus_topk", "NEG"]


def _remote_tunnel_runtime() -> bool:
    """Measured on the tunnel runtime: every execution of a program
    containing a Pallas custom-call pays a multi-second fixed penalty
    (~21s/exec at the k-NN bench shape vs ~0.05s device time), so the
    XLA fallback wins by orders of magnitude despite the kernel being
    faster on-chip. Override with REFLOW_TOPK_PALLAS=1/0. (Detection
    shared with the forced-sync advisory — utils/runtime.py.)"""
    from reflow_tpu.utils.runtime import remote_tunnel_runtime
    return remote_tunnel_runtime()


def _pallas_default() -> Optional[bool]:
    from reflow_tpu.utils.config import env_str
    env = env_str("REFLOW_TOPK_PALLAS", None)
    if env is not None:
        return env == "1"
    if _remote_tunnel_runtime():
        return False
    return None  # platform default: pallas on real TPU

#: sentinel for "no candidate" — finite so arithmetic/compares stay clean
NEG = float(jnp.finfo(jnp.float32).min)

_BQ = 8  # rows per grid step (f32 sublane tile)


def _topk_kernel(x_ref, vals_ref, idx_ref, *, k: int):
    x = x_ref[...].astype(jnp.float32)                     # [BQ, N]
    bq, n = x.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (bq, n), 1)
    for i in range(k):                                     # k static, unrolled
        m = jnp.max(x, axis=1, keepdims=True)              # [BQ, 1]
        first = jnp.min(jnp.where(x >= m, col, n), axis=1, keepdims=True)
        vals_ref[:, i] = m[:, 0]
        idx_ref[:, i] = first[:, 0].astype(jnp.int32)
        x = jnp.where(col == first, NEG, x)


def _topk_pallas(scores: jax.Array, k: int,
                 interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, n = scores.shape
    if n % 128:
        pad = 128 - n % 128
        scores = jnp.pad(scores, ((0, 0), (0, pad)), constant_values=NEG)
        n += pad
    grid = (pl.cdiv(q, _BQ),)
    vals, idx = pl.pallas_call(
        functools.partial(_topk_kernel, k=k),
        grid=grid,
        in_specs=[pl.BlockSpec((_BQ, n), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((_BQ, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BQ, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q, k), jnp.float32),
            jax.ShapeDtypeStruct((q, k), jnp.int32),
        ],
        interpret=interpret,
    )(scores)
    return vals, idx


def topk(scores: jax.Array, k: int,
         use_pallas: Optional[bool] = None) -> Tuple[jax.Array, jax.Array]:
    """Row-wise top-k of ``scores [Q, N]`` -> ``(values, ids) [Q, k]``.

    Ties resolve to the lowest column index on both paths. Requesting the
    Pallas path off-TPU runs the kernel in interpreter mode (CI coverage
    of the kernel logic on the CPU mesh).
    """
    on_tpu = jax.default_backend() == "tpu"
    if use_pallas is None:
        use_pallas = _pallas_default()
        if use_pallas is None:
            use_pallas = on_tpu
    if use_pallas:
        return _topk_pallas(scores, k, interpret=not on_tpu)
    vals, idx = jax.lax.top_k(scores, k)
    return vals, idx.astype(jnp.int32)


#: int8 embedding encoding: wire/table value is round(unit_vec * 127);
#: cosine only needs direction, so the per-vector scale folds away
INT8_EMBED_SCALE = 127.0


def score_form(v: jax.Array) -> jax.Array:
    """Compute-form of stored embeddings: int8 tables dequantize to bf16
    at score time (wire/HBM stay 1 byte/dim); float tables pass
    through."""
    if v.dtype == jnp.int8:
        return jnp.asarray(v, jnp.bfloat16) * jnp.bfloat16(
            1.0 / INT8_EMBED_SCALE)
    return v


def chunked_corpus_topk(qvec: jax.Array, dvec: jax.Array, dlive: jax.Array,
                        k: int, chunk: int = 8192,
                        use_pallas: Optional[bool] = None,
                        precision=None) -> Tuple[jax.Array, jax.Array]:
    """Top-k of ``qvec @ dvec.T`` without materializing the full [Q, D]
    scores matrix: stream the corpus in chunks through the MXU and fold
    each chunk into a running top-k carry.

    ``dlive`` masks dead corpus slots to NEG. D must be a multiple of the
    chunk (or <= chunk, in which case one pass covers it).
    """
    q, _dim = qvec.shape
    d = dvec.shape[0]
    chunk = min(chunk, d)
    if d % chunk:
        raise ValueError(f"corpus size {d} must be a multiple of the "
                         f"scan chunk {chunk}")

    def step(c, carry):
        vals, ids = carry
        lo = c * chunk
        blk = jax.lax.dynamic_slice_in_dim(dvec, lo, chunk, 0)
        live = jax.lax.dynamic_slice_in_dim(dlive, lo, chunk, 0)
        s = jnp.dot(score_form(qvec), score_form(blk).T,
                    preferred_element_type=jnp.float32,
                    precision=precision)
        s = jnp.where(live[None, :], s, NEG)
        cand_vals = jnp.concatenate([vals, s], axis=1)
        cand_ids = jnp.concatenate(
            [ids, jnp.broadcast_to(
                lo + jnp.arange(chunk, dtype=jnp.int32), (q, chunk))],
            axis=1)
        vals, sel = topk(cand_vals, k, use_pallas)
        ids = jnp.take_along_axis(cand_ids, sel, axis=1)
        return vals, ids

    init = (jnp.full((q, k), NEG, jnp.float32),
            jnp.full((q, k), -1, jnp.int32))
    return jax.lax.fori_loop(0, d // chunk, step, init)
