"""Incremental single-source shortest paths: iterative Join + min-Reduce.

A sixth example workload beyond the five BASELINE configs — the min-plus
analog of PageRank's sum-loop, and the graph shape that exercises the
retraction-capable device min/max (executors/lowerings.py
``minmax_core``) inside the on-device fixpoint: every distance
improvement emits retract(old)/insert(new) through the min-Reduce, and
edge churn retracts relaxation candidates outright.

Graph::

    edges   source {src: [dst, weight]}
    seeds   source {node: dist}          (0.0 at the SSSP source)
    dist    loop   {node: best dist}     (unique)
    relax   Join(dist, edges, merge=[dst, d + w], )
    cands   GroupBy(dst, value d + w)
    best    Reduce('min')( Union(cands, seeds) )
    close_loop(dist, best)

Per tick the loop relaxes until no node's best distance changes — the
host-driven loop on the CPU oracle, one compiled ``lax.while_loop``
program on the TPU executor. Edge deletions retract the corresponding
relaxation candidates; the device path stays exact while each node's
candidate-distance churn fits the min-Reduce's ``candidates`` buffer and
fails loudly beyond it.

**Quiescence contract.** Distances must stay positive (min-plus
semiring). Insertion ticks always quiesce (relaxation only improves
distances, and a shortest path has at most ``n_nodes - 1`` hops). A
DELETION tick quiesces too — *unless* it disconnects a cycle from the
source: the orphaned cycle's nodes then sustain each other with
ever-growing candidate distances (the classic incremental-SSSP
invalidation problem; cf. Ramalingam–Reps-style algorithms that track
shortest-path trees to break such cycles). Because every legitimate tick
converges within ``n_nodes`` relaxation passes, running the scheduler
with ``max_loop_iters = n_nodes + 2`` (see :func:`max_loop_iters`) turns
that divergence into a cheap, sound detection: ``TickResult.quiesced``
comes back False, the loop state is NOT trustworthy, and the driver
falls back to a from-scratch rebuild (fresh scheduler over the surviving
edges) — incremental-with-fallback, demonstrated in
``tests/test_sssp.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node


@dataclasses.dataclass
class SsspGraph:
    graph: FlowGraph
    edges: Node
    seeds: Node
    dist: Node    # loop var
    best: Node    # the min-Reduce; read_table -> {node: distance}


def _relax_merge(k, d, vb):
    """(dist, [dst, w]) -> [dst, dist + w] (array contract, ndim branch)."""
    if getattr(vb, "ndim", 1) <= 1:
        return np.asarray([vb[0], d + vb[1]])
    import jax.numpy as jnp

    return jnp.stack([vb[:, 0], d + vb[:, 1]], axis=-1)


def build_graph(n_nodes: int, *, arena_capacity: Optional[int] = None,
                candidates: int = 16) -> SsspGraph:
    dist_spec = Spec((), np.float32, key_space=n_nodes, unique=True)
    scalar = Spec((), np.float32, key_space=n_nodes)
    edge2 = Spec((2,), np.float32, key_space=n_nodes)
    arena = arena_capacity if arena_capacity is not None else 1 << 15

    g = FlowGraph("sssp")
    edges = g.source("edges", edge2)
    seeds = g.source("seeds", scalar)
    dist = g.loop("dist", dist_spec)
    relax = g.join(dist, edges, merge=_relax_merge, spec=edge2,
                   arena_capacity=arena, name="relax")
    cands = g.group_by(relax, key_fn=lambda k, v: v[:, 0].astype("int32"),
                       value_fn=lambda k, v: v[:, 1], vectorized=True,
                       spec=scalar, name="cands")
    best = g.reduce(g.union(cands, seeds), "min", name="best",
                    spec=dist_spec, candidates=candidates)
    g.close_loop(dist, best)
    return SsspGraph(g, edges, seeds, dist, best)


def max_loop_iters(n_nodes: int) -> int:
    """The quiescence bound: a legitimate tick converges in <= n_nodes
    relaxation passes, so exceeding this proves an orphaned sustaining
    cycle (rebuild from scratch — see the module docstring)."""
    return n_nodes + 2


def edge_batch(src, dst, w, weight: int = 1) -> DeltaBatch:
    """Edge rows keyed by src with [dst, w] values; ``weight=-1``
    retracts (values must replay the inserted rows exactly)."""
    src = np.asarray(src, np.int64)
    vals = np.stack([np.asarray(dst, np.float32),
                     np.asarray(w, np.float32)], axis=1)
    return DeltaBatch(src, vals, np.full(len(src), weight, np.int64))


def seed_batch(node: int) -> DeltaBatch:
    return DeltaBatch(np.array([node], np.int64),
                      np.zeros(1, np.float32), np.ones(1, np.int64))


def affected_set(n_nodes: int, src, dst, w, dist_prev: dict,
                 del_src, del_dst, del_w) -> set:
    """Conservative affected set for a batch of edge deletions
    (Ramalingam–Reps phase 1, host-side, O(E)).

    ``dist_prev`` is the TRUSTWORTHY pre-deletion distance table;
    ``src/dst/w`` are the SURVIVING edges. A node is affected when its
    (pre-deletion) shortest path may have used a deleted edge: seed with
    each deleted edge's head whose distance was tight through it
    (``dist[v] == dist[u] + w``), then close over the shortest-path DAG
    of the surviving edges (descendants of a stale node are themselves
    suspect). Conservative — a superset only costs re-derivation work,
    never correctness.
    """
    inf = np.inf
    d = np.full(n_nodes, inf)
    for k, v in dist_prev.items():
        d[int(k)] = v
    def _tight(du, dv, ww):
        # device distances are f32: tightness must tolerate one rounding
        # (a false positive only widens the conservative superset)
        return (np.isfinite(du) & np.isfinite(dv)
                & np.isclose(dv, du + ww, rtol=1e-6, atol=1e-5))

    seeds = set()
    for u, v, ww in zip(np.asarray(del_src, np.int64),
                        np.asarray(del_dst, np.int64),
                        np.asarray(del_w, np.float64)):
        if _tight(d[u], d[v], ww):
            seeds.add(int(v))
    if not seeds:
        return set()
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    w = np.asarray(w, np.float64)
    tight = _tight(d[src], d[dst], w)
    affected = set(seeds)
    frontier = list(seeds)
    # adjacency over tight (shortest-path DAG) surviving edges only
    from collections import defaultdict
    adj = defaultdict(list)
    for u, v in zip(src[tight], dst[tight]):
        adj[int(u)].append(int(v))
    while frontier:
        u = frontier.pop()
        for v in adj[u]:
            if v not in affected:
                affected.add(v)
                frontier.append(v)
    return affected


def repair(sched, sg: SsspGraph, src, dst, w, affected: set):
    """Ramalingam–Reps-style in-place repair after edge deletions
    (module docstring: the orphaned-cycle case), WITHOUT a fresh
    scheduler: ``sched.rederive`` the surviving in-edges of the affected
    set. The retraction makes every affected candidate vanish through
    the exact algebra (a shrinking wave — it quiesces even from a
    paused, divergent iteration), and the re-insertion re-derives the
    affected region from the valid boundary distances. Device work is
    proportional to the affected region's in-edges + the relaxation
    cascade — incremental, not a rebuild.

    ``src/dst/w`` are the SURVIVING edges; returns the two TickResults.
    """
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    mask = np.isin(dst, np.fromiter(affected, np.int64,
                                    len(affected)))
    if not mask.any():
        raise ValueError("repair: affected set has no surviving in-edges "
                         "(nothing to re-derive — the keys are simply "
                         "unreachable; a normal tick settles that)")
    batch = edge_batch(src[mask], dst[mask], np.asarray(w)[mask])
    return sched.rederive(sg.edges, batch)


def reference_distances(n_nodes, src_arr, dst_arr, w_arr, source: int):
    """Bellman-Ford oracle -> {node: distance} for reachable nodes."""
    dist = np.full(n_nodes, np.inf)
    dist[source] = 0.0
    for _ in range(n_nodes):
        nd = dist[src_arr] + w_arr
        new = dist.copy()
        np.minimum.at(new, dst_arr, nd)
        if np.array_equal(new, dist):
            break
        dist = new
    return {int(i): float(dist[i]) for i in range(n_nodes)
            if np.isfinite(dist[i])}
