"""Benchmark config 2: streaming TF-IDF over document-edit deltas.

BASELINE.md: "Streaming TF-IDF over Wikipedia-edit deltas (Map / GroupBy /
Reduce)". The graph maintains the classic decomposition with exactly that
op vocabulary (no Join), so it lowers to both executors and shards:

    src(key=pair, value=[term, doc], weight=+-occurrences)
    tf      = Reduce(sum)(Map(1))            {pair: tf}
    pres    = Reduce(mean)(Map(v[0]))        {pair: term}   (see below)
    df      = Reduce(sum)(GroupBy(term, 1)(pres-emissions)) {term: df}
    doctok  = Reduce(sum)(GroupBy(doc, 1)(src))             {doc: tokens}
    ndocs   = Reduce(sum)(GroupBy(0, 1)(doctok-emissions))  {0: N}

The presence trick: ``Reduce('mean')`` over a constant per-pair value
emits exactly one insert when a (doc, term) pair first appears and one
retract when its count reaches zero — tf changes in between leave the
mean unchanged and are suppressed. Grouping those +-1 presence rows by
term and summing gives the document frequency incrementally. The same
telescoping applied to ``doctok``'s emissions (every live doc nets exactly
one row) counts distinct documents.

``tfidf(doc, term) = tf * log(N / df)`` is combined at the sink boundary
(host side) from the three maintained tables — the graph keeps the
decomposition incremental; the final scalar combine is O(changed rows).

Exactness bound (device path): the mean-reduce keeps a float32 running
sum of ``component * tf`` per pair, so each stored component must satisfy
``component * max_tf < 2**24``. Storing the raw term id would cap the
vocabulary at 2**14 (VERDICT r2: a real Wikipedia vocabulary is ~10^6);
instead the presence value is the term id split radix-``_TERM_RADIX``
into two small components ``[term // R, term % R]`` (each < 4096), and
the by-term GroupBy reassembles ``term = v0*R + v1``. That lifts the
vocabulary bound to 2**24 terms at max per-document term count 4096.
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node

_TOKEN = re.compile(r"[A-Za-z0-9']+")


def tokenize(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN.findall(text)]


@dataclasses.dataclass
class TfidfGraph:
    graph: FlowGraph
    tokens: Node   # source
    tf: Node       # read_table -> {pair: tf}
    df: Node       # read_table -> {term: df}
    ndocs: Node    # read_table -> {0: N}


#: radix for splitting term ids into two f32-exact presence components
_TERM_RADIX = 4096


def _split_term(v):
    """[C, 2] (term, doc) -> [C, 2] (term // R, term % R); dual contract
    (NumPy on the CPU oracle, jnp under the device lowering)."""
    if isinstance(v, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    t = v[:, 0]
    hi = t // _TERM_RADIX
    return xp.stack([hi, t - hi * _TERM_RADIX], axis=-1)


def build_graph(n_pairs: int, n_terms: int, n_docs: int,
                *, n0: int = 8) -> TfidfGraph:
    if n_terms > 1 << 24:
        raise ValueError(
            f"n_terms {n_terms} > 2**24 would overflow the float32 "
            f"radix-split presence components (see module docstring)")
    f32 = np.float32
    g = FlowGraph("tfidf")
    src = g.source("tokens", Spec((2,), f32, key_space=n_pairs))
    ones = g.map(src, lambda v: 1.0, spec=Spec((), f32, key_space=n_pairs),
                 name="ones")
    tf = g.reduce(ones, "sum", name="tf")
    term_of = g.map(src, _split_term, vectorized=True,
                    spec=Spec((2,), f32, key_space=n_pairs), name="term_of")
    pres = g.reduce(term_of, "mean", name="pair_presence")
    bterm = g.group_by(
        pres, key_fn=lambda k, v: v[0] * _TERM_RADIX + v[1],
        value_fn=lambda k, v: 1.0,
        spec=Spec((), f32, key_space=n_terms), name="by_term")
    df = g.reduce(bterm, "sum", name="df")
    bdoc = g.group_by(src, key_fn=lambda k, v: v[1],
                      value_fn=lambda k, v: 1.0,
                      spec=Spec((), f32, key_space=n_docs), name="by_doc")
    doctok = g.reduce(bdoc, "sum", name="doc_tokens")
    bone = g.group_by(doctok, key_fn=lambda k, v: 0,
                      value_fn=lambda k, v: 1.0,
                      spec=Spec((), f32, key_space=n0), name="all_docs")
    ndocs = g.reduce(bone, "sum", name="ndocs")
    return TfidfGraph(g, src, tf, df, ndocs)


# -- host boundary: edit ingestion + vocab interning -----------------------

class Corpus:
    """Host mirror: documents, term/pair vocabularies, delta generation."""

    def __init__(self, n_pairs: int, n_terms: int):
        self.n_pairs, self.n_terms = n_pairs, n_terms
        self.terms: Dict[str, int] = {}
        self.pairs: Dict[Tuple[int, int], int] = {}
        self.docs: Dict[int, Counter] = {}

    def _term(self, t: str) -> int:
        i = self.terms.setdefault(t, len(self.terms))
        if i >= self.n_terms:
            raise ValueError(f"term vocabulary overflow (> {self.n_terms})")
        return i

    def _pair(self, doc: int, term: int) -> int:
        i = self.pairs.setdefault((doc, term), len(self.pairs))
        if i >= self.n_pairs:
            raise ValueError(f"pair vocabulary overflow (> {self.n_pairs})")
        return i

    def edit(self, doc: int, new_text: Optional[str]) -> DeltaBatch:
        """Replace (or with None, delete) a document; returns token deltas."""
        old = self.docs.get(doc, Counter())
        new = Counter(self._term(t) for t in tokenize(new_text)) \
            if new_text is not None else Counter()
        keys, vals, weights = [], [], []
        for term in set(old) | set(new):
            w = new[term] - old[term]
            if w:
                keys.append(self._pair(doc, term))
                vals.append((float(term), float(doc)))
                weights.append(w)
        if new:
            self.docs[doc] = new
        else:
            self.docs.pop(doc, None)
        return DeltaBatch(np.array(keys, np.int64),
                          np.array(vals, np.float32).reshape(-1, 2),
                          np.array(weights, np.int64))

    # -- oracles -----------------------------------------------------------

    def reference_tfidf(self) -> Dict[Tuple[int, int], float]:
        """Brute-force recompute over the current corpus."""
        n = len(self.docs)
        df: Counter = Counter()
        for c in self.docs.values():
            df.update(set(c))
        out = {}
        for doc, c in self.docs.items():
            for term, tf in c.items():
                out[(doc, term)] = tf * math.log(n / df[term])
        return out


def tfidf_view(sched, tg: TfidfGraph, corpus: Corpus
               ) -> Dict[Tuple[int, int], float]:
    """Sink-boundary combine of the three maintained tables."""
    tf = sched.read_table(tg.tf)
    df = sched.read_table(tg.df)
    nd = sched.read_table(tg.ndocs)
    n = float(next(iter(nd.values()))) if nd else 0.0
    rev = {i: dt for dt, i in corpus.pairs.items()}
    out = {}
    for pair, tfv in tf.items():
        doc, term = rev[int(pair)]
        out[(doc, term)] = float(tfv) * math.log(n / float(df[term]))
    return out
