"""The five reference benchmark workloads (SURVEY.md §2 item 12 /
BASELINE.md), plus one beyond-spec demo:

1. ``wordcount``   — incremental word-count (Map→Reduce, CPU default path)
2. ``tfidf``       — streaming TF-IDF (Map / GroupBy / Reduce)
3. ``pagerank``    — incremental PageRank (iterative Join + Reduce; north star)
4. ``knn``         — k-NN re-index (vmapped cosine + Pallas top-k)
5. ``image_embed`` — ViT-B feature extract → incremental groupby-agg
6. ``sssp``        — incremental single-source shortest paths (min-plus
                     Join + min-Reduce fixpoint; beyond the spec)
"""
