"""Benchmark config 4: k-NN re-index on embedding deltas.

BASELINE.md: "k-NN re-index on 1Mx768 embedding deltas (vmapped cosine,
Pallas top-k)". The graph is two sources (queries, corpus) feeding a
:class:`~reflow_tpu.ops.KnnIndex` op; the maintained collection is each
query's top-k corpus ids by cosine similarity, re-indexed incrementally as
embedding deltas arrive. The host driver streams batches of corpus
insertions (the re-index flow) and occasional retractions (which trigger
the chunked full corpus rescan on device).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node


@dataclasses.dataclass
class KnnGraph:
    graph: FlowGraph
    queries: Node
    docs: Node
    index: Node   # read_table -> {query_id: [k, 2] (doc_id, score) rows}


def build_graph(n_queries: int, n_docs: int, dim: int, k: int,
                *, scan_chunk: int = 8192, dtype=np.float32,
                doc_dtype=None, precision: str = "highest") -> KnnGraph:
    """``dtype`` is the embedding storage/transfer dtype. ``bfloat16``
    halves corpus HBM residency and the per-tick host->device upload
    (the bandwidth-bound cost of streaming inserts) at ~1e-3 relative
    score error — scoring still accumulates in float32 on the MXU; pair
    it with ``precision="default"`` so the MXU takes bf16 inputs
    natively instead of upcasting.

    ``doc_dtype=jnp.int8`` (ROADMAP r4 #6 / VERDICT r4 #3a) halves the
    corpus wire+HBM cost AGAIN vs bf16: the host sends
    ``quantize_int8(vecs)`` — ``round(unit_vec * 127)``, 1 byte/dim —
    and scoring dequantizes to bf16 on chip (``kernels.topk.score_form``;
    per-vector scale folds away because cosine only needs direction).
    ~0.4% component error; recall bound tested in tests/test_knn.py.
    Queries keep ``dtype`` (their upload is negligible)."""
    g = FlowGraph("knn")
    q = g.source("queries", Spec((dim,), dtype, key_space=n_queries))
    d = g.source("docs", Spec((dim,), doc_dtype if doc_dtype is not None
                              else dtype, key_space=n_docs))
    idx = g.knn(q, d, k, dim, name="index", scan_chunk=scan_chunk,
                precision=precision)
    return KnnGraph(g, q, d, idx)


def quantize_int8(vals: np.ndarray) -> np.ndarray:
    """Host-side int8 embedding encoding: normalize each row, scale by
    127, round. The device stores these RAW (re-normalizing would
    truncate at int8) and dequantizes at score time."""
    vals = np.asarray(vals, np.float32)
    n = np.linalg.norm(vals, axis=1, keepdims=True)
    u = vals / np.maximum(n, 1e-30)
    return np.clip(np.round(u * 127.0), -127, 127).astype(np.int8)


# -- host-side data + churn driver ----------------------------------------

@dataclasses.dataclass
class EmbeddingStore:
    """Host mirror of the corpus for generating deltas + the oracle."""

    dim: int
    rng: np.random.Generator
    vecs: dict  # id -> raw (unnormalized) vector

    @staticmethod
    def create(dim: int, seed: int = 0) -> "EmbeddingStore":
        return EmbeddingStore(dim, np.random.default_rng(seed), {})

    def _random(self, n: int) -> np.ndarray:
        return self.rng.normal(size=(n, self.dim)).astype(np.float32)

    def insert_batch(self, ids: np.ndarray, *,
                     quantize: bool = False) -> DeltaBatch:
        """``quantize=True`` sends int8-encoded rows (1 byte/dim wire
        cost — pair with ``build_graph(doc_dtype=jnp.int8)``); the host
        mirror keeps the raw f32 vectors for the oracle either way."""
        vals = self._random(len(ids))
        for i, v in zip(ids, vals):
            self.vecs[int(i)] = v
        wire = quantize_int8(vals) if quantize else vals
        return DeltaBatch(np.asarray(ids, np.int64), wire,
                          np.ones(len(ids), np.int64))

    def retract_batch(self, ids: np.ndarray) -> DeltaBatch:
        vals = np.stack([self.vecs.pop(int(i)) for i in ids])
        return DeltaBatch(np.asarray(ids, np.int64), vals,
                          -np.ones(len(ids), np.int64))

    def reference_topk(self, queries: np.ndarray, k: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Brute-force float64 oracle -> (ids [Q,k], scores [Q,k])."""
        ids = np.array(sorted(self.vecs), np.int64)
        if not len(ids):
            return (np.full((len(queries), k), -1, np.int64),
                    np.full((len(queries), k), -np.inf))
        mat = np.stack([self.vecs[int(i)] for i in ids]).astype(np.float64)
        mat /= np.maximum(np.linalg.norm(mat, axis=1, keepdims=True), 1e-30)
        qn = queries.astype(np.float64)
        qn /= np.maximum(np.linalg.norm(qn, axis=1, keepdims=True), 1e-30)
        s = qn @ mat.T
        take = np.argsort(-s, axis=1, kind="stable")[:, :k]
        out_ids = np.full((len(queries), k), -1, np.int64)
        out_s = np.full((len(queries), k), -np.inf)
        m = min(k, len(ids))
        out_ids[:, :m] = ids[take[:, :m]]
        out_s[:, :m] = np.take_along_axis(s, take, 1)[:, :m]
        return out_ids, out_s
