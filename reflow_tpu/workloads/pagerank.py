"""Benchmark config 3: incremental PageRank — iterative Join + Reduce.

The north-star workload (BASELINE.json): 1M-edge web graph, 1% edge churn
per tick, target ≥20× wall-clock vs the CPU executor on a TPU.

Dataflow formulation (scaled ranks: Σrank ≈ N, avg 1.0 — keeps float32
well-conditioned at 1M nodes)::

    ranks    = loop var, unique-keyed {node: rank}
    teleport = source {node: 1-d}                  (pushed once)
    edges    = source {src: [dst, 1/outdeg(src)]}
    contribs = Join(ranks, edges, merge -> [dst, rank·invdeg])   (keyed src)
    by_dst   = GroupBy(key=dst, value=contrib)                   (keyed dst)
    damped   = Map(v -> d·v)
    new_rank = Reduce('sum', tol)(Union(teleport, damped))        (unique)
    close_loop(ranks, new_rank)

The teleport term flows *through* the Reduce rather than seeding the loop
variable directly: every rank row then originates from a Reduce emission,
so the Reduce's retract-old/insert-new discipline keeps the ranks
collection exactly unique across iterations (a directly-pushed seed would
never be retracted and the contributions would accumulate as a geometric
series — the classic fixpoint seeding bug).

Each tick re-runs the cyclic region until the Reduce's tol suppresses all
changes (host-driven passes; the deltas stay on device under the TPU
executor). Edge churn preserves out-degrees (edge rewiring), so a churned
edge is exactly two delta rows: retract [old_dst, invdeg], insert
[new_dst, invdeg] — no degree cascade.

Host work is confined to the boundary: the churn driver keeps the adjacency
list host-side and emits delta rows; ranks are read back via
``scheduler.read_table`` once per tick.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node

DAMPING = 0.85


@dataclasses.dataclass
class PageRankGraph:
    graph: FlowGraph
    ranks: Node     # loop var
    teleport: Node  # source (push teleport_batch once)
    edges: Node     # source (push edge deltas here)
    join: Node      # read_table -> current ranks collection (left table)
    new_rank: Node  # the Reduce; read_table -> converged ranks


def build_graph(n_nodes: int, *, damping: float = DAMPING, tol: float = 1e-4,
                arena_capacity: Optional[int] = None,
                defer_passes: Optional[int] = None) -> PageRankGraph:
    """``defer_passes`` opts the rank loop into cross-tick residual
    deferral (docs/guide.md "Deferred fixpoint"): each tick runs at most
    that many fixpoint passes, carrying un-propagated rank deltas to the
    next tick. Ranks then lag full convergence by the in-flight mass —
    bounded by d/(1-d) · ||resid||₁ — and ``DirtyScheduler.drain``
    flushes to the quiescent fixpoint."""
    rank_spec = Spec((), np.float32, key_space=n_nodes, unique=True)
    scalar = Spec((), np.float32, key_space=n_nodes)
    edge_spec = Spec((2,), np.float32, key_space=n_nodes)
    g = FlowGraph("pagerank")
    ranks = g.loop("ranks", rank_spec)
    teleport = g.source("teleport", scalar)
    edges = g.source("edges", edge_spec)
    j = g.join(
        ranks, edges, merge=_contrib_merge, spec=edge_spec, name="contribs",
        arena_capacity=arena_capacity or max(1 << 10, 4 * n_nodes),
        # merge is linear in rank and the GroupBy key (dst) comes from the
        # edge side only: the TPU executor fuses the loop into the
        # delta-vector frontier push (executors/linear_fixpoint.py)
        linear_left=True,
    )
    by_dst = g.group_by(
        j, key_fn=lambda k, v: v[0], value_fn=lambda k, v: v[1],
        spec=scalar, name="by_dst",
        # the grouping key is the edge's dst — a pure arena-value read,
        # independent of the rank flowing on the loop: the fused fixpoint
        # may run its dense tier destination-sorted
        stable_key=True)
    damped = g.map(by_dst, lambda v: damping * v, vectorized=True,
                   linear=True, name="damp")
    everything = g.union(teleport, damped, name="teleport_plus_contribs")
    new_rank = g.reduce(everything, "sum", tol=tol, name="rank",
                        spec=rank_spec)
    g.close_loop(ranks, new_rank, defer_passes=defer_passes)
    return PageRankGraph(g, ranks, teleport, edges, j, new_rank)


def _contrib_merge(k, rank, vb):
    """(rank, [dst, invdeg]) -> [dst, rank·invdeg].

    Merge contract (ops/core.py Join): values arrive array-like — per-row
    on the CPU oracle (``vb: f64[2]``, ``rank`` scalar), batched on the
    device path (``vb: f32[R, 2]``, ``rank: f32[R]``); branch on ndim.
    """
    if getattr(vb, "ndim", 1) <= 1:
        return np.asarray([vb[0], rank * vb[1]])
    import jax.numpy as jnp

    return jnp.stack([vb[:, 0], rank * vb[:, 1]], axis=-1)


# -- host-side data + churn driver (the source boundary) -------------------

@dataclasses.dataclass
class WebGraph:
    """Host adjacency: out-edge array per node, regenerable churn."""

    n_nodes: int
    dst: np.ndarray      # [E] int64 destination per edge
    src: np.ndarray      # [E] int64 source per edge
    rng: np.random.Generator

    @staticmethod
    def random(n_nodes: int, n_edges: int, seed: int = 0) -> "WebGraph":
        rng = np.random.default_rng(seed)
        src = rng.integers(0, n_nodes, n_edges)
        # power-law-ish popularity for destinations (web-graph flavored)
        dst = (n_nodes * rng.power(0.3, n_edges)).astype(np.int64) % n_nodes
        return WebGraph(n_nodes, dst.astype(np.int64), src.astype(np.int64), rng)

    def out_degree(self) -> np.ndarray:
        deg = np.zeros(self.n_nodes, np.int64)
        np.add.at(deg, self.src, 1)
        return deg

    def edge_rows(self, idx: np.ndarray, weight: int) -> DeltaBatch:
        inv = 1.0 / self.out_degree()[self.src[idx]]
        vals = np.stack([self.dst[idx].astype(np.float32),
                         inv.astype(np.float32)], axis=-1)
        return DeltaBatch(self.src[idx].copy(),
                          vals,
                          np.full(len(idx), weight, dtype=np.int64))

    def initial_batch(self) -> DeltaBatch:
        return self.edge_rows(np.arange(len(self.src)), 1)

    def churn(self, fraction: float) -> DeltaBatch:
        """Rewire a fraction of edges (out-degree preserving). Returns the
        retract+insert delta rows."""
        m = max(1, int(len(self.src) * fraction))
        idx = self.rng.choice(len(self.src), size=m, replace=False)
        retract = self.edge_rows(idx, -1)
        self.dst[idx] = self.rng.integers(0, self.n_nodes, m)
        insert = self.edge_rows(idx, 1)
        return DeltaBatch.concat([retract, insert])


def teleport_batch(n_nodes: int, damping: float = DAMPING) -> DeltaBatch:
    """The (1-d) teleport row per node; push once to the teleport source."""
    return DeltaBatch(
        np.arange(n_nodes, dtype=np.int64),
        np.full(n_nodes, 1.0 - damping, dtype=np.float32),
        np.ones(n_nodes, dtype=np.int64),
    )


def ranks_to_array(table: Dict[int, float], n_nodes: int,
                   damping: float = DAMPING) -> np.ndarray:
    """Dense rank vector from a ``read_table`` dict.

    Missing keys default to the teleport floor ``1 - damping`` — the exact
    rank of a node with no in-edges, and the one value a key can hold
    without ever having been (re-)emitted. The single shared definition
    keeps every checker (tests, dryrun) agreeing on what absence means.
    """
    out = np.full(n_nodes, 1.0 - damping)
    for k, v in table.items():
        out[int(k)] = float(v)
    return out


def reference_ranks(web: WebGraph, damping: float = DAMPING,
                    iters: int = 200, tol: float = 1e-8) -> np.ndarray:
    """Dense NumPy power iteration — the independent correctness oracle."""
    n = web.n_nodes
    deg = web.out_degree()
    inv = np.where(deg > 0, 1.0 / np.maximum(deg, 1), 0.0)
    r = np.ones(n, np.float64)
    for _ in range(iters):
        contrib = np.zeros(n, np.float64)
        np.add.at(contrib, web.dst, r[web.src] * inv[web.src])
        r_new = (1.0 - damping) + damping * contrib
        if np.abs(r_new - r).max() < tol:
            r = r_new
            break
        r = r_new
    return r
