"""Benchmark config 5: image-embed ETL — ViT feature extract feeding an
incremental groupby-agg, sharded over the mesh.

BASELINE.md: "Image-embed ETL: ViT-B feature extract -> incremental
groupby-agg, sharded on a TPU v4-8". The graph is::

    images  source {image_id: uint8 [group_byte, *raw_pixels]}
    embed   Map(vit_forward)            -> f32 [group_id, *features]
    by_grp  GroupBy(key=group, value=features)
    cent    Reduce('mean')              {group: centroid}

Under the ShardedTpuExecutor this is data-parallel model inference: the
per-tick image deltas are row-sharded over the mesh, each shard runs the
(pure) ViT forward on its slice inside the shard_map'd tick, and the
centroid Reduce combines cross-shard with one psum_scatter — the
groupby-agg never leaves the device.

An image moving between groups (or being deleted) is an ordinary
retract/insert delta pair; the mean's retract-old/insert-new emission
keeps every centroid exact, not approximate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node
from reflow_tpu.models import vit_forward


@dataclasses.dataclass
class ImageEmbedGraph:
    graph: FlowGraph
    images: Node     # source
    centroids: Node  # read_table -> {group: mean feature vector}


def pixels_to_input(px):
    """uint8 pixels -> the model's [-1, 1] float input.

    One definition shared by the device Map and the host oracle so the
    differential tests compare the same forward pass. Works on numpy and
    jax arrays alike.
    """
    return px.astype("float32") * np.float32(2.0 / 255.0) - np.float32(1.0)


def build_graph(n_images: int, n_groups: int, params: Dict,
                model_axis: Optional[str] = None) -> ImageEmbedGraph:
    """``model_axis`` (VERDICT r4 #8): tensor-parallel the ViT over that
    mesh axis — params shard per ``vit_param_specs`` (run under
    ``ShardedTpuExecutor(mesh, model_axis=...)`` on a (delta, model)
    mesh) and the Map runs ``vit_forward_tp`` (two psums per block).
    A model too large for one chip's HBM then holds 1/m of its weights
    per device while deltas stay row-sharded on the delta axis."""
    import jax.numpy as jnp

    cfg = params["_cfg"]
    flat = cfg["img"] * cfg["img"] * cfg["chans"]
    dim = cfg["dim"]
    f32 = np.float32
    if n_groups > 256:
        raise ValueError("group id rides in the row's leading uint8 byte; "
                         "n_groups must be <= 256 (ids 0-255)")
    g = FlowGraph("image_embed")
    # rows ship as RAW uint8 [group_byte | pixels] — what a real ETL
    # ingests, and 4x less host->device traffic than f32 pixels (the
    # measured bottleneck of config 5 over a ~50 MB/s tunnel)
    src = g.source("images", Spec((1 + flat,), np.uint8, key_space=n_images))

    # weights ride as op params (compiled-program ARGUMENTS: VERDICT r2 #2
    # — closing over them traced ~86M ViT-B floats into a ~350MB HLO and
    # meant full recompilation on any weight change); only the static
    # shape-driving config is closed over
    weights = {k: v for k, v in params.items() if k != "_cfg"}
    param_specs = None
    if model_axis is not None:
        from reflow_tpu.models.vit import vit_forward_tp, vit_param_specs

        param_specs = vit_param_specs(cfg, model_axis)

        def embed(p, v):
            feats = vit_forward_tp({**p, "_cfg": cfg},
                                   pixels_to_input(v[:, 1:]),
                                   axis=model_axis)
            return jnp.concatenate([v[:, :1].astype(jnp.float32), feats],
                                   axis=-1)
    else:
        def embed(p, v):  # (weights, [C, 1+flat] u8) -> [C, 1+dim] f32
            feats = vit_forward({**p, "_cfg": cfg},
                                pixels_to_input(v[:, 1:]))
            return jnp.concatenate([v[:, :1].astype(jnp.float32), feats],
                                   axis=-1)

    emb = g.map(src, embed, vectorized=True, params=weights,
                param_specs=param_specs,
                spec=Spec((1 + dim,), f32, key_space=n_images), name="embed")
    by_grp = g.group_by(emb, key_fn=lambda k, v: v[0],
                        value_fn=lambda k, v: v[1:],
                        spec=Spec((dim,), f32, key_space=n_groups),
                        name="by_group")
    cent = g.reduce(by_grp, "mean", name="centroids")
    return ImageEmbedGraph(g, src, cent)


# -- host boundary: image stream driver ------------------------------------

class ImageStream:
    """Host mirror: images with group assignments, delta generation."""

    def __init__(self, params: Dict, seed: int = 0):
        self.cfg = params["_cfg"]
        self.params = params
        self.rng = np.random.default_rng(seed)
        self.images: Dict[int, np.ndarray] = {}   # id -> flat pixels
        self.groups: Dict[int, int] = {}          # id -> group

    def _flat(self) -> int:
        return self.cfg["img"] * self.cfg["img"] * self.cfg["chans"]

    def _row(self, i: int) -> np.ndarray:
        return np.concatenate(
            [[np.uint8(self.groups[i])], self.images[i]]).astype(np.uint8)

    def insert(self, ids, groups) -> DeltaBatch:
        rows = []
        for i, grp in zip(ids, groups):
            self.images[int(i)] = self.rng.integers(
                0, 256, size=self._flat(), dtype=np.uint8)
            self.groups[int(i)] = int(grp)
            rows.append(self._row(int(i)))
        return DeltaBatch(np.asarray(ids, np.int64), np.stack(rows),
                          np.ones(len(rows), np.int64))

    def move(self, i: int, new_group: int) -> DeltaBatch:
        """Reassign an image's group: retract old row, insert new."""
        old = self._row(i)
        self.groups[i] = int(new_group)
        new = self._row(i)
        return DeltaBatch(np.array([i, i], np.int64), np.stack([old, new]),
                          np.array([-1, 1], np.int64))

    def delete(self, i: int) -> DeltaBatch:
        row = self._row(i)
        del self.images[i], self.groups[i]
        return DeltaBatch(np.array([i], np.int64), row[None],
                          -np.ones(1, np.int64))

    def reference_centroids(self) -> Dict[int, np.ndarray]:
        """Oracle: same forward pass, float64 group means."""
        if not self.images:
            return {}
        ids = sorted(self.images)
        feats = np.asarray(vit_forward(
            self.params,
            pixels_to_input(np.stack([self.images[i] for i in ids]))))
        out: Dict[int, list] = {}
        for i, f in zip(ids, feats):
            out.setdefault(self.groups[i], []).append(f.astype(np.float64))
        return {g: np.mean(v, axis=0) for g, v in out.items()}
