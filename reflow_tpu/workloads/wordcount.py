"""Benchmark config 1: incremental word-count (single Map→Reduce).

Tokenization happens at the host boundary (source ingest) per the north
star's "host callbacks only at graph sources and sinks"; the graph itself is
Map (normalize) → Reduce (count). Raw word strings are the keys on the CPU
path; for the TPU path the ingest helper hashes words into an integer key
space via a host-side vocabulary.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec
from reflow_tpu.graph import FlowGraph, Node

_TOKEN = re.compile(r"[A-Za-z0-9']+")


def tokenize(line: str) -> List[str]:
    return [t.lower() for t in _TOKEN.findall(line)]


def build_graph(key_space: int = 0) -> Tuple[FlowGraph, Node, Node]:
    """Map→Reduce word-count graph. Returns (graph, source, sink).

    The classic shape: Map projects each token row to the countable unit
    ``1.0`` (so upstream payloads don't matter), Reduce('sum') folds
    ``value*weight`` per word.
    """
    spec = Spec((), np.float32, key_space=key_space)
    g = FlowGraph("wordcount")
    words = g.source("words", spec)
    # dtype-generic (v*0+1): stays numpy-pure on the CPU oracle and traces
    # cleanly under jit on device — no jax import on the host-only path
    ones = g.map(words, lambda v: v * 0 + 1, vectorized=True, name="to_ones")
    counts = g.reduce(ones, "sum", name="counts", spec=spec)
    out = g.sink(counts, "out")
    return g, words, out


def ingest_lines(lines: Iterable[str], weight: int = 1,
                 vocab: Optional[Dict[str, int]] = None) -> DeltaBatch:
    """Host-side ingest: tokenize lines into (word, 1) delta rows.

    With ``vocab``, words are interned to dense int keys (extending the
    vocab in place) for integer-keyed / TPU graphs.
    """
    keys: List = []
    for line in lines:
        for tok in tokenize(line):
            if vocab is not None:
                tok = vocab.setdefault(tok, len(vocab))
            keys.append(tok)
    n = len(keys)
    if vocab is not None:
        karr = np.array(keys, dtype=np.int64)
    else:
        karr = np.array(keys, dtype=object)
    return DeltaBatch(karr, np.ones(n, dtype=np.float32),
                      np.full(n, weight, dtype=np.int64))
