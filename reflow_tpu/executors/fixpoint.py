"""On-device fixpoint: a whole tick as ONE compiled XLA program.

SURVEY.md §2 item 13 / §7.9 / hard part (e): the host-driven loop in
``DirtyScheduler.tick`` pays one device dispatch plus one scalar readback
*per fixpoint pass* — tens of round-trips per tick for iterative graphs
like PageRank, and the dominant cost when the device sits behind a network
tunnel. This module lowers the entire tick to one jit-compiled program:

    phase A   one pass over the dirty plan (source ingest; sinks outside
              loop regions emit here),
    phase B   ``lax.while_loop`` over the cyclic region with the loop
              deltas as carry and an on-device quiescence predicate
              (any live delta row left?),
    phase C   one "exit pass" over nodes strictly downstream of the
              region, fed the *telescoped* boundary deltas (see below).

Host↔device crossings per tick: ingress upload, one (iters, rows) scalar
readback, sink materialization. Nothing else.

Boundary telescoping: a consumer outside the region would, under the host
loop, receive one delta batch per pass. Those per-pass emissions of a
Reduce telescope (retract prev / insert next), so their multiset sum equals
the diff of the Reduce's emitted table before phase B vs after. We
therefore require every region-exit edge to originate at a Reduce (true of
keyed iterative graphs — the back-edge value is an aggregate), snapshot
its ``emitted`` table after phase A, and emit the table diff to the exit
pass once, after quiescence. Graphs violating the restriction fall back to
the host-driven loop (``supports_fixpoint`` returns False).

Loop-carry shapes: XLA needs the while-carry shape-stable, but a pass's
output capacity is a static function of its input capacities, so we solve
caps = f(caps) by abstract evaluation (``jax.eval_shape`` — no FLOPs, no
transfers) and pad phase A's loop deltas up to the fixed point. Divergence
(pathological graphs whose emission capacity grows without bound) falls
back to the host loop.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.lowerings import _differs
from reflow_tpu.graph import FlowGraph, Node

__all__ = ["FixpointProgram", "FixpointStructure", "analyze"]

_CAP_SOLVER_ITERS = 32


@dataclasses.dataclass(frozen=True)
class FixpointStructure:
    """Static decomposition of a graph for on-device fixpoint execution."""

    loops: Tuple[Node, ...]          # loop nodes (all have back_input)
    region_ids: frozenset            # the cyclic region (includes loops)
    loop_plan: Tuple[Node, ...]      # region nodes, topo order
    boundary: Tuple[Node, ...]       # region producers with outside consumers
    exit_plan: Tuple[Node, ...]      # non-region nodes downstream of boundary


def analyze(graph: FlowGraph) -> Optional[FixpointStructure]:
    """Static feasibility analysis; None = use the host-driven loop."""
    loops = tuple(l for l in graph.loops if l.back_input is not None)
    if not loops:
        return None
    region = graph.loop_region()
    region_ids = frozenset(n.id for n in region)
    for node in region:
        if (node.kind == "op" and node.op.kind == "join"
                and node.inputs[1].id in region_ids):
            # a loop-carried right (arena) input appends rows every
            # while_loop iteration, invisibly to the host-side overflow
            # tracker — only the host-driven loop tracks those (ADVICE r1)
            return None
    boundary = []
    for node in region:
        if any(c.id not in region_ids for c, _ in graph.consumers(node)):
            boundary.append(node)
    for node in boundary:
        if node.kind != "op" or node.op.kind != "reduce":
            # only Reduce emissions telescope into a table diff
            return None
    # nodes strictly downstream of the boundary, outside the region
    downstream = set(n.id for n in boundary)
    exit_plan = []
    for node in graph.nodes:  # construction order == topo order
        if node.id in region_ids or node.id in downstream:
            continue
        if any(i.id in downstream for i in node.inputs):
            downstream.add(node.id)
            exit_plan.append(node)
    return FixpointStructure(
        loops=loops,
        region_ids=region_ids,
        loop_plan=tuple(n for n in region),
        boundary=tuple(boundary),
        exit_plan=tuple(exit_plan),
    )


def _pad_delta(d: DeviceDelta, cap: int) -> DeviceDelta:
    """Grow a delta to ``cap`` rows with weight-0 padding (trace-static)."""
    extra = cap - d.capacity
    if extra == 0:
        return d
    if extra < 0:
        raise ValueError(f"cannot shrink delta {d.capacity} -> {cap}")
    return DeviceDelta(
        keys=jnp.concatenate([d.keys, jnp.zeros((extra,), d.keys.dtype)]),
        values=jnp.concatenate(
            [d.values, jnp.zeros((extra,) + d.values.shape[1:],
                                 d.values.dtype)]),
        weights=jnp.concatenate(
            [d.weights, jnp.zeros((extra,), d.weights.dtype)]),
    )


def _abstract_delta(spec, cap: int) -> DeviceDelta:
    import numpy as np

    return DeviceDelta(
        keys=jax.ShapeDtypeStruct((cap,), jnp.int32),
        values=jax.ShapeDtypeStruct((cap,) + tuple(spec.value_shape),
                                    np.dtype(spec.value_dtype)),
        weights=jax.ShapeDtypeStruct((cap,), jnp.int32),
    )


def _solve_carry_caps(body_fn, states, structure: FixpointStructure,
                      caps: Dict[int, int]) -> Optional[Dict[int, int]]:
    """Fixed point of the loop body's capacity map (abstract eval only)."""
    specs = {l.id: l.spec for l in structure.loops}
    for _ in range(_CAP_SOLVER_ITERS):
        carry = {lid: _abstract_delta(specs[lid], c) for lid, c in caps.items()}
        _, egress = jax.eval_shape(body_fn, states, carry)
        if any(lid not in egress for lid in caps):
            return None  # a loop's back-edge produced nothing: structural bug
        new = {lid: egress[lid].keys.shape[0] for lid in caps}
        if new == caps:
            return caps
        caps = {lid: max(caps[lid], new[lid]) for lid in caps}
    return None


def _emitted_diff(snap: Tuple[jax.Array, jax.Array], state: dict,
                  node: Node) -> DeviceDelta:
    """Telescoped boundary delta: diff of a Reduce's emitted table.

    Unchanged keys keep bit-identical stored values (the lowering writes
    through where-masks), so exact inequality is the right changed-test.
    """
    em_a, has_a = snap
    em_f, has_f = state["emitted"], state["emitted_has"]
    differ = _differs(em_a, em_f, 0.0)
    ret = has_a & (~has_f | differ)
    ins = has_f & (~has_a | differ)
    K = em_a.shape[0]
    keys = jnp.arange(K, dtype=jnp.int32)
    return DeviceDelta(
        keys=jnp.concatenate([keys, keys]),
        values=jnp.concatenate([em_a, em_f]),
        weights=jnp.concatenate(
            [-ret.astype(jnp.int32), ins.astype(jnp.int32)]),
    )


def make_scan_program(tick_fn):
    """K consecutive ticks fused into ONE device execution.

    ``lax.scan`` over the tick program with the K per-tick ingress
    pytrees stacked on a leading axis. Every execution over a
    tunnel-attached device carries a large fixed overhead (measured
    ~0.1-0.3s regardless of program size), so batching K ticks into one
    program amortizes it K-fold — the "macro-tick" streaming fast path.
    Sink-free graphs only (the caller guards): per-tick sink egress
    would otherwise need stacking and per-tick host materialization.

    The ingress stack is DONATED alongside the state pytree (the
    mega-tick queue's buffers would otherwise stay live across the whole
    window execution — one extra copy per source) and a fresh zeroed
    stack rides back out in (potentially) the same memory, so the
    persistent ingress queue can re-bind it (``run_window``) and keep
    slot-writing in place.
    """
    import jax

    def scan_fn(op_states, ing_stack):
        def body(states, ing):
            states2, sink_eg, _carry, iters, rows, conv = tick_fn(states,
                                                                  ing)
            if sink_eg:  # trace-time structural check
                raise RuntimeError("macro-tick requires a sink-free graph")
            return states2, (iters, rows, conv)

        states, ys = jax.lax.scan(body, op_states, ing_stack)
        return states, ys, jax.tree.map(jnp.zeros_like, ing_stack)

    return jax.jit(scan_fn, donate_argnums=(0, 1))


class _MacroTickMixin:
    """Shared macro-tick entry for the two fixpoint program kinds: both
    set ``self.tick_fn`` (the unjitted tick) in ``__init__``."""

    def call_many(self, op_states, ing_stack, n_ticks: int):
        """-> (states', (iters[K], rows[K], converged[K]), fresh_stack).
        ``ing_stack`` is donated; ``fresh_stack`` is the zeroed
        replacement the ingress queue re-binds."""
        cache = getattr(self, "_many_cache", None)
        if cache is None:
            cache = self._many_cache = {}
        prog = cache.get(n_ticks)
        if prog is None:
            prog = cache[n_ticks] = make_scan_program(self.tick_fn)
        return prog(op_states, ing_stack)


class FixpointProgram(_MacroTickMixin):
    """One compiled tick: phase A pass + while_loop + exit pass.

    Built per (dirty-plan, ingress-capacity) signature and cached by the
    executor exactly like single-pass programs.
    """

    def __init__(self, executor, plan: Sequence[Node],
                 ingress_caps: Dict[int, int], max_iters: int,
                 structure: Optional[FixpointStructure] = None):
        graph = executor.graph
        if structure is None:
            structure = analyze(graph)
        if structure is None:
            raise ValueError("graph has no on-device-fixpoint structure")
        self.structure = structure
        self.max_iters = max_iters
        self.sink_ids = [s.id for s in graph.sinks]

        full_pass = executor.build_pass_fn(list(plan))
        body_pass = executor.build_pass_fn(list(structure.loop_plan))
        exit_pass = (executor.build_pass_fn(list(structure.exit_plan))
                     if structure.exit_plan else None)

        # solve the while-carry capacity fixed point (abstract)
        specs = {l.id: l.spec for l in structure.loops}
        ingress_abstract = {
            nid: _abstract_delta(graph.nodes[nid].spec, cap)
            for nid, cap in ingress_caps.items()}
        states_abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), executor.states)
        _, eg_a = jax.eval_shape(full_pass, states_abstract, ingress_abstract)
        caps0 = {
            l.id: (eg_a[l.id].keys.shape[0] if l.id in eg_a else 64)
            for l in structure.loops}
        caps = _solve_carry_caps(body_pass, states_abstract, structure, caps0)
        if caps is None:
            raise ValueError("loop-carry capacities do not stabilize")
        self.carry_caps = caps

        loops = structure.loops
        boundary = structure.boundary
        mi = max_iters

        def tick_fn(op_states, ingress):
            states, eg_a = full_pass(op_states, ingress)
            carry = {}
            for l in loops:
                d = eg_a.get(l.id)
                if d is None:
                    d = DeviceDelta.empty(specs[l.id], caps[l.id])
                carry[l.id] = _pad_delta(d, caps[l.id])
            snaps = {n.id: (states[n.id]["emitted"],
                            states[n.id]["emitted_has"]) for n in boundary}

            def live_rows(cr):
                n = jnp.zeros((), jnp.int32)
                for d in cr.values():
                    n = n + jnp.sum((d.weights != 0).astype(jnp.int32))
                return n

            def cond(c):
                st, cr, it, rows = c
                return jnp.logical_and(it < mi, live_rows(cr) > 0)

            def body(c):
                st, cr, it, rows = c
                rows = rows + live_rows(cr)
                st2, eg = body_pass(st, cr)
                cr2 = {lid: eg[lid] for lid in cr}
                return st2, cr2, it + 1, rows

            states, carry, iters, rows = jax.lax.while_loop(
                cond, body, (states, carry, jnp.zeros((), jnp.int32),
                             jnp.zeros((), jnp.int32)))
            # converged iff the carry actually went dead (distinguishes
            # "quiesced on the last allowed iteration" from "exhausted")
            converged = live_rows(carry) == 0

            eg_b = {}
            if exit_pass is not None:
                diffs = {n.id: _emitted_diff(snaps[n.id], states[n.id], n)
                         for n in boundary}
                states, eg_b = exit_pass(states, diffs)

            sink_egress = {}
            for sid in self.sink_ids:
                batches = []
                if sid in eg_a:
                    batches.append(eg_a[sid])
                if sid in eg_b:
                    batches.append(eg_b[sid])
                if batches:
                    sink_egress[sid] = tuple(batches)
            # the final carry rides out so a max_iters halt can PAUSE
            # instead of dropping in-flight loop deltas (the scheduler
            # stashes live carries as pending; all-dead when converged)
            return states, sink_egress, dict(carry), iters, rows, converged

        # donate the state pytree: ticks update arenas/tables in place
        # instead of copying them (the executor drops old refs on return)
        self.tick_fn = tick_fn
        self._fn = jax.jit(tick_fn, donate_argnums=0)

    def __call__(self, op_states, dev_ingress):
        """-> (states', {sink_id: (DeviceDelta, ...)}, {loop_id: carry},
        iters, loop_rows, converged)."""
        return self._fn(op_states, dev_ingress)
