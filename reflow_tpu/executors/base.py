"""Executor ABC: run one pass of a tick's dirty plan.

The scheduler computes *what* to run (dirty plan, structural — no device
values are consulted, keeping host↔device traffic at the graph boundary per
the north star); the executor decides *how*. The contract:

``run_pass(plan, ingress) -> egress``

- ``plan``: topo-ordered dirty nodes (sources/loops first).
- ``ingress``: {node_id: DeltaBatch} for the dirty source/loop nodes.
- ``egress``: {node_id: DeltaBatch} for every sink in the plan **and** every
  loop node whose back-edge produced deltas this pass (the scheduler re-ticks
  those). Internal edges never cross the executor boundary.
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, List, Sequence, Union

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import FlowGraph, Node

__all__ = ["Executor", "register_executor", "get_executor"]


class Executor(abc.ABC):
    name: str = "?"

    def __init__(self):
        self.graph: FlowGraph | None = None
        self.states: Dict[int, object] = {}
        #: device→host readbacks done by :meth:`materialize` (forced
        #: syncs on a streaming path; always 0 for host executors)
        self.materialize_count = 0

    def bind(self, graph: FlowGraph) -> None:
        """Attach to a validated graph and allocate per-node state."""
        self.graph = graph
        self.states = {
            n.id: n.op.initial_state()
            for n in graph.nodes
            if n.kind == "op" and n.op is not None
        }

    @abc.abstractmethod
    def run_pass(self, plan: Sequence[Node],
                 ingress: Dict[int, DeltaBatch]) -> Dict[int, DeltaBatch]:
        ...

    def run_tick_fixpoint(self, plan: Sequence[Node],
                          ingress: Dict[int, DeltaBatch], max_iters: int,
                          *, sync: bool = True):
        """Optionally run an ENTIRE tick (all fixpoint passes) in one call.

        Returns ``({sink_id: [batches]}, passes, loop_rows, quiesced,
        extra_dirty_node_ids, leftover)`` or None when unsupported — the
        scheduler then drives passes itself. ``leftover`` maps loop node
        ids to in-flight loop-delta batches of a tick that halted at
        ``max_iters``: the scheduler stashes them as pending so the
        paused iteration RESUMES next tick (empty when quiescent).
        Executors that can fuse the loop on device (TpuExecutor via
        ``lax.while_loop``) override this.

        ``sync=False`` permits the scalar observability fields (passes,
        loop_rows, quiesced) to come back as device values without
        blocking — streaming callers pipeline ticks and block once per
        batch (see ``TickResult.block``).
        """
        return None

    def materialize(self, batch) -> DeltaBatch:
        """Convert a (possibly device-resident) sink egress batch to host."""
        return batch

    def refresh_minmax(self, node: Node, batch: DeltaBatch) -> None:
        """Maintenance hook for bounded min/max state (no-op by default):
        rebuild the candidate buffers of every key in ``batch`` from a
        replay of its full live multiset, resetting the monotone
        overflow latches. The CPU oracle keeps exact multisets and needs
        no refresh; device executors override."""

    def on_states_replaced(self) -> None:
        """Hook: the caller swapped ``self.states`` wholesale (checkpoint
        restore). Executors holding derived caches keyed to state content
        (e.g. the linear fixpoint's sorted-arena CSR) must invalidate
        them here — the (gen, rcount) validity predicate cannot detect a
        lineage swap whose counters happen to line up."""

    def check_errors(self) -> None:
        """Raise if any op state carries a sticky error flag (called by the
        scheduler once per tick, so invalid state fails loudly instead of
        leaking corrupt deltas into sink views)."""

    def read_table(self, node: Node) -> Dict:
        """Materialized {key: value} of a stateful node's collection.

        Reduce: the last emitted aggregate per key. Join: the left table.
        """
        st = self.states.get(node.id)
        if st is None:
            raise KeyError(f"{node} holds no materialized state")
        if node.op.kind == "reduce":
            from reflow_tpu.ops.core import _NO_AGG
            return {k: em for k, (ms, em) in st.items() if em is not _NO_AGG}
        if node.op.kind == "join":
            left, _right = st
            out = {}
            for k, ms in left.items():
                for v, w in ms.items():
                    if w > 0:
                        out[k] = v
            return out
        if node.op.kind == "knn":
            return dict(st["emitted"])
        raise KeyError(f"{node} ({node.op.kind}) has no table to read")

    # -- checkpoint seam (SURVEY.md §5) -----------------------------------

    def state_snapshot(self) -> Dict[int, object]:
        """Host-representable snapshot of all per-node operator state.

        Deep-copied: ops mutate their state in place, so a shallow copy
        would alias live state and be invalidated by the next tick.
        """
        import copy

        return copy.deepcopy(self.states)

    def state_restore(self, snapshot: Dict[int, object]) -> None:
        self.states = dict(snapshot)


_REGISTRY: Dict[str, Union[type, Callable[[], type]]] = {}


def register_executor(name: str, cls_or_thunk) -> None:
    _REGISTRY[name] = cls_or_thunk


def get_executor(name: str, **kwargs) -> Executor:
    """Instantiate a registered executor by name ('cpu' is the default path)."""
    if name not in _REGISTRY:
        raise KeyError(f"no executor {name!r}; registered: {sorted(_REGISTRY)}")
    entry = _REGISTRY[name]
    cls = entry if isinstance(entry, type) else entry()
    return cls(**kwargs)
