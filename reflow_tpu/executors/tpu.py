"""TpuExecutor: one tick pass = one jit-compiled XLA step.

North star (BASELINE.json): the DirtyScheduler's per-tick batch of
invalidated nodes is lowered to a single ``jax.jit`` step — vmapped
Map/Filter, dense segment reductions for GroupBy/Reduce, table×arena
products for Join — with delta buffers device-resident and host callbacks
only at graph sources (``to_device``) and sinks (``to_host``). Back-edge
(loop) deltas stay on device between passes; the only mid-tick readback is
one scalar liveness count per pass for the scheduler's quiescence check
(removed entirely by the on-device ``lax.while_loop`` fixpoint path — see
``fixpoint.py``).

Compiled pass programs are cached per (plan, ingress-capacity-bucket)
signature, so steady-state ticks hit the cache and pay zero tracing cost.
Mega-tick window programs additionally share a process-wide cache keyed
on the plan *signature* (graph structure + fn code, not node identity),
so structurally-identical tenants — e.g. K spread-placed twins on a
serving tier — trace their window program once (``megatick_cache_hits``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors.base import Executor
from reflow_tpu.executors.device_delta import (DeviceDelta, bucket_capacity,
                                               check_weight_mass, to_device,
                                               to_host)
from reflow_tpu.executors.lowerings import (DEVICE_REDUCERS, join_state,
                                            lower_node, reduce_state)
from reflow_tpu.graph import FlowGraph, GraphError, Node
from reflow_tpu.obs import trace as _trace
from reflow_tpu.utils.config import env_int
from reflow_tpu.utils.runtime import named_lock

__all__ = ["TpuExecutor", "StagedWindow"]


# -- process-wide window-program sharing (plan-signature cache) ------------
#
# Two graphs built by the same code are distinct Node objects with
# distinct (per-graph) ids, so the per-executor program cache cannot see
# that their dirty plans are the same computation. The signature below
# captures everything the traced window program can observe — node
# structure, op configuration, fn CODE identity (plus captured scalar
# cells), specs, plan positions, capacities — so identical tenants share
# one traced program object (jax then caches compiled executables per
# argument sharding/device underneath, so the share also spans devices).
# Anything the tokenizer can't prove shareable (arrays or rich objects in
# a closure, fn-less callables) falls back to the per-executor cache.

_SHARED_WINDOW_PROGRAMS: Dict[tuple, object] = {}
_SHARED_WINDOW_LOCK = named_lock("executors.window_cache")


class _Unshareable(Exception):
    pass


class StagedWindow:
    """A staged-but-not-yet-dispatched K-tick window: the ingress queue
    generation its slot writes landed in, the [K, cap] stack to hand the
    window program, and everything :meth:`TpuExecutor.dispatch_window` /
    :meth:`TpuExecutor.retire_window` need to finish the lifecycle.
    ``fresh`` is filled by dispatch (the program's returned zeroed
    pass-through stack) and consumed by retire."""

    __slots__ = ("plan", "caps", "K", "max_iters", "queue", "gen", "stack",
                 "qsig", "fresh")

    def __init__(self, plan, caps, K, max_iters, queue, gen, stack, qsig):
        self.plan = plan
        self.caps = caps
        self.K = K
        self.max_iters = max_iters
        self.queue = queue
        self.gen = gen
        self.stack = stack
        self.qsig = qsig
        self.fresh = None


def _value_token(v):
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, tuple):
        return tuple(_value_token(x) for x in v)
    import numpy as np

    if isinstance(v, np.generic):
        return (str(v.dtype), v.item())
    if isinstance(v, np.dtype) or (isinstance(v, type)
                                   and issubclass(v, np.generic)):
        return str(np.dtype(v))
    if callable(v):
        return _fn_token(v)
    raise _Unshareable


def _fn_token(fn):
    """Identity of a user fn AS TRACED: its code object plus the values
    it closes over / defaults to. Two lambdas from the same source line
    share the code object; differing captured scalars split the token."""
    code = getattr(fn, "__code__", None)
    if code is None:
        raise _Unshareable
    toks = [_value_token(c.cell_contents) for c in (fn.__closure__ or ())]
    toks += [_value_token(d) for d in (fn.__defaults__ or ())]
    return ("fn", code, tuple(toks))


def _spec_token(spec):
    import numpy as np

    return (tuple(spec.value_shape), str(np.dtype(spec.value_dtype)),
            int(spec.key_space), bool(spec.unique))


def _op_token(op):
    toks = [type(op).__name__]
    for k in sorted(vars(op)):
        v = vars(op)[k]
        if k in ("params", "param_specs"):
            # params are program ARGUMENTS (op state), not traced
            # constants: only their presence shapes the program
            toks.append((k, v is not None))
        elif hasattr(v, "value_shape"):
            toks.append((k, _spec_token(v)))
        else:
            toks.append((k, _value_token(v)))
    return tuple(toks)


def _node_token(node: Node):
    # node.name is observability-only (error strings), deliberately out
    return (node.id, node.kind,
            _op_token(node.op) if node.op is not None else None,
            tuple(i.id for i in node.inputs), _spec_token(node.spec),
            node.back_input.id if node.back_input is not None else None,
            node.sharding, node.stage, node.defer_passes)


class TpuExecutor(Executor):
    name = "tpu"

    def __init__(self, *, fixpoint: bool = True, linear_fixpoint: bool = True):
        super().__init__()
        self._cache: Dict[tuple, object] = {}
        #: lower whole ticks of iterative graphs to one lax.while_loop
        #: program (False forces the host-driven per-pass loop)
        self.fixpoint = fixpoint
        #: allow the fused delta-vector loop for declared-linear regions
        #: (False forces the row-based while_loop program)
        self.linear_fixpoint = linear_fixpoint
        self._fx_structure = None
        self._fx_unsupported = not fixpoint
        #: mesh size for sharded subclasses: arena overflow is bounded
        #: against the per-shard slice (worst-case key skew)
        self._arena_divisor = 1
        #: the fused delta-vector loop runs on both the single-device and
        #: the sharded executor (the sharded variant runs the loop inside
        #: one shard_map region — see linear_fixpoint.py)
        self._linear_fixpoint = linear_fixpoint
        self._linear_structure = None
        #: ONE persistent sorted-arena CSR cache per join node, shared by
        #: every LinearFixpointProgram signature over that join (a
        #: per-program copy would duplicate tens of MB of HBM per ingress
        #: bucket and re-sort appends the other signature already covered)
        self._csr_cache: Dict[int, dict] = {}
        #: mega-tick window path (run_window): per-source host batches
        #: above this row bound don't fit a reasonable queue slot — the
        #: scheduler falls back to the per-tick path instead
        self.megatick_max_rows = env_int("REFLOW_MEGATICK_MAX_ROWS")
        #: windows dispatched through the device-resident ingress queue
        self.window_dispatches = 0
        #: tenant placement: the jax.Device this executor's state, ingress
        #: uploads, queue buffers — and therefore every compiled program's
        #: execution — are committed to. None = jax's default device. Set
        #: via :meth:`place` (the serve tier's GraphConfig placement path).
        self.device = None
        #: window programs adopted from the process-wide plan-signature
        #: cache instead of traced locally (surfaced as a scheduler gauge)
        self.megatick_cache_hits = 0

    #: subclasses whose traced programs close over executor-specific
    #: context (e.g. the sharded executor's mesh/axis in ``_lower``) must
    #: opt out of the process-wide window-program share
    _share_window_programs = True

    # -- tenant placement --------------------------------------------------

    def place(self, device) -> None:
        """Commit this executor to one device: states move, and every
        subsequent upload, queue buffer, and compiled-program execution
        follows them (jit dispatch targets the committed argument device).
        ``device`` is a ``jax.Device`` or an index into ``jax.devices()``.
        Compiled programs and cached queues reference buffers on the old
        device, so the program cache is dropped; call between windows."""
        if isinstance(device, int):
            device = jax.devices()[device]
        self.device = device
        self._cache.clear()
        self._csr_cache.clear()
        if self.states:
            self.states = jax.device_put(self.states, device)

    @property
    def device_label(self) -> Optional[str]:
        """Short obs tag for spans/gauges: ``"cpu:3"``-style for a pinned
        executor, None when running on the default device."""
        d = self.device
        if d is None:
            return None
        return f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', '?')}"

    def _ingress_placement(self):
        """Placement handed to ingress buffers (queue slots, stacked
        feeds): the pinned device here; the sharded subclass returns its
        ``(mesh, axis)`` so the capacity axis lands shard-local."""
        return self.device

    # -- bind: validate lowerability, build device state -------------------

    def bind(self, graph: FlowGraph) -> None:
        # compiled passes close over graph nodes: rebinding the *same* graph
        # (fresh state, e.g. a full-recompute baseline) keeps the jit cache;
        # a different graph invalidates it
        if graph is not self.graph:
            self._cache.clear()
            self._fx_structure = None
            self._fx_unsupported = not self.fixpoint
            self._linear_structure = None
            self._linear_fixpoint = self.linear_fixpoint
        # state is reset below: any sorted-arena cache is now stale (the
        # (gen, rcount) predicate would also catch this via count > rcount,
        # but an explicit drop is cheaper than relying on it)
        self._csr_cache.clear()
        self.graph = graph
        self.states = {}
        for loop in graph.loops:
            if loop.defer_passes:
                # cross-tick residual deferral: the loop carries its
                # un-propagated emission deltas as dense linear
                # observables [K, P+1] (flattened dval columns + dw).
                # SEMANTIC state — checkpointed with the state tree,
                # unlike the derived CSR cache (docs/guide.md).
                import jax.numpy as jnp
                import numpy as np
                K = loop.spec.key_space
                if K <= 0:
                    raise GraphError(
                        f"{loop}: defer_passes needs key_space > 0")
                P = int(np.prod(loop.spec.value_shape)) if \
                    loop.spec.value_shape else 1
                self.states[loop.id] = {
                    "resid": jnp.zeros((K, P + 1), jnp.float32)}
        for node in graph.nodes:
            if node.kind != "op":
                continue
            op = node.op
            if op.kind == "map" and op.params is not None:
                for leaf in jax.tree.leaves(op.params):
                    if not hasattr(leaf, "shape"):
                        raise GraphError(
                            f"{node}: Map params leaves must be arrays, got "
                            f"{type(leaf).__name__}; close fn over static "
                            f"(shape-driving) config instead of passing it "
                            f"in params")
                import jax.numpy as jnp
                # deep-copy: tick programs DONATE state, and aliasing the
                # caller's arrays would delete them out from under the
                # user on the first tick
                self.states[node.id] = {
                    "params": jax.tree.map(lambda x: jnp.array(x, copy=True),
                                           op.params)}
                continue
            if op.kind in ("map", "filter", "groupby", "union"):
                continue
            in_specs = [i.spec for i in node.inputs]
            for s in in_specs:
                if s.key_space <= 0:
                    raise GraphError(
                        f"{node}: TPU lowering needs key_space > 0 on every "
                        f"keyed-op input Spec")
            if op.kind == "reduce":
                if op.how not in DEVICE_REDUCERS:
                    raise GraphError(
                        f"{node}: reducer {op.how!r} has no device lowering "
                        f"yet (have {DEVICE_REDUCERS}); run it on the cpu "
                        f"executor")
                self.states[node.id] = reduce_state(op, in_specs[0], node.spec)
            elif op.kind == "knn":
                for port, s in enumerate(in_specs):
                    if tuple(s.value_shape) != (op.dim,):
                        raise GraphError(
                            f"{node}: knn input {port} value_shape "
                            f"{s.value_shape} != (dim={op.dim},)")
                D = in_specs[1].key_space
                if D > op.scan_chunk and D % op.scan_chunk:
                    raise GraphError(
                        f"{node}: corpus key_space {D} must be a multiple "
                        f"of scan_chunk {op.scan_chunk}")
                from reflow_tpu.executors.lowerings import knn_state
                self.states[node.id] = knn_state(op, *in_specs)
            elif op.kind == "join":
                if op.merge is None:
                    # the default merge lowers to the flattened
                    # concatenation of (va, vb) — the device encoding of
                    # the host oracle's tuple; the out Spec must size it
                    import numpy as _np
                    flat = int(_np.prod(in_specs[0].value_shape or (1,))
                               ) + int(_np.prod(in_specs[1].value_shape
                                                or (1,)))
                    got = int(_np.prod(node.spec.value_shape or (1,)))
                    if got != flat:
                        raise GraphError(
                            f"{node}: default-merge device Join needs a "
                            f"spec with {flat} flat value elements "
                            f"(va ++ vb), got {node.spec.value_shape}")
                self.states[node.id] = join_state(op, in_specs[0], in_specs[1])
            else:
                raise GraphError(f"{node}: no TPU lowering for {op.kind}")
        if self.device is not None:
            # placed BEFORE bind: move the freshly-built state tree onto
            # the pinned device (the jnp.zeros above land on the default)
            self.states = jax.device_put(self.states, self.device)

    # -- one pass ----------------------------------------------------------

    def _to_device_ingress(self, ingress) -> Dict[int, DeviceDelta]:
        """Host boundary in: upload host batches; pass device ones through."""
        dev_ingress: Dict[int, DeviceDelta] = {}
        for nid, b in ingress.items():
            if isinstance(b, DeviceDelta):
                # jit dispatch follows committed args: a pinned executor
                # pulls a stray default-device batch over (no-op when it
                # already lives on self.device)
                if self.device is not None:
                    b = jax.tree.map(
                        lambda x: jax.device_put(x, self.device), b)
                dev_ingress[nid] = b
            else:
                dev_ingress[nid] = to_device(b, self.graph.nodes[nid].spec,
                                             device=self.device)
        return dev_ingress

    def run_pass(self, plan: Sequence[Node],
                 ingress: Dict[int, DeltaBatch]) -> Dict[int, object]:
        dev_ingress = self._to_device_ingress(ingress)

        sig = (
            tuple(n.id for n in plan),
            tuple(sorted((nid, d.capacity) for nid, d in dev_ingress.items())),
        )
        fn = self._cache.get(sig)
        if fn is None:
            fn = self._build(list(plan))
            self._cache[sig] = fn

        # fail loudly BEFORE truncation
        self._track_arena(plan, {nid: d.capacity
                                 for nid, d in dev_ingress.items()})
        op_states = {nid: st for nid, st in self.states.items()}
        new_states, egress_dev = fn(op_states, dev_ingress)
        self.states = new_states

        # everything stays device-resident: sink batches are materialized
        # lazily by the scheduler once per tick, loop back-edges feed the
        # next pass directly on device
        return dict(egress_dev)

    # -- whole-tick on-device fixpoint (SURVEY.md §7.9, hard part e) -------

    def run_tick_fixpoint(self, plan: Sequence[Node],
                          ingress: Dict[int, DeltaBatch], max_iters: int,
                          *, sync: bool = True):
        """Run an entire tick (initial pass + fixpoint + exit pass) as one
        compiled program. Returns ``(sink_batches, passes, loop_rows,
        quiesced)`` or None when the graph doesn't fit the on-device
        structure (the scheduler then uses its host-driven loop).

        With ``sync=False`` the scalar tick metadata stays device-resident
        (no readback, so pipelined ticks enqueue back-to-back); the dirty
        set is then reported conservatively (as if the loop iterated)."""
        from reflow_tpu.executors.fixpoint import analyze

        if self._fx_unsupported:
            return None
        if self._fx_structure is None:
            self._fx_structure = analyze(self.graph)
            if self._fx_structure is None:
                self._fx_unsupported = True
                return None

        dev_ingress = self._to_device_ingress(ingress)
        caps = {nid: d.capacity for nid, d in dev_ingress.items()}

        sig = ("fx", tuple(n.id for n in plan),
               tuple(sorted(caps.items())), max_iters)
        prog = self._cache.get(sig)
        if prog is None:
            prog = self._build_fixpoint(plan, caps, max_iters)
            if prog is None:
                return None
            self._cache[sig] = prog

        st = self._fx_structure
        self._track_arena(plan, caps)
        if st.exit_plan:
            self._track_arena(
                list(st.exit_plan),
                {n.id: 2 * n.inputs[0].spec.key_space for n in st.boundary})

        t_d0 = time.perf_counter() if _trace.ENABLED else 0.0
        new_states, sink_egress, carry, iters, rows, converged = prog(
            dict(self.states), dev_ingress)
        if _trace.ENABLED:
            _trace.evt("device_dispatch", t_d0,
                       time.perf_counter() - t_d0,
                       args={"kind": "fixpoint",
                             "device": self.device_label})
        self.states = new_states
        exit_passes = 1 if st.exit_plan else 0
        leftover = {}
        if sync:
            iters = int(iters)
            passes = 1 + iters + exit_passes
            rows = int(rows)
            converged = bool(converged)
            looped = iters > 0
            if not converged and carry:
                # max_iters halt: hand the live carry back so the
                # scheduler stashes it as pending — the halted iteration
                # RESUMES on the next tick instead of silently dropping
                # in-flight loop deltas (which would desync the join's
                # left table from the reduce's emissions)
                leftover = dict(carry)
        else:
            # LazyScalar, not eager jnp arithmetic: a per-tick scalar op
            # would dispatch an extra device execution (large fixed cost
            # over a tunnel); int() combines at the sync point instead
            from reflow_tpu.scheduler import LazyScalar

            passes = LazyScalar(1 + exit_passes, iters)
            looped = True  # conservative dirty-set report
            if carry:
                # streaming mode cannot branch on the device-resident
                # converged flag, so the ROW program's carry stashes
                # UNCONDITIONALLY: a quiescent tick's carry is all
                # weight-0 rows (a semantic no-op that keeps the next
                # tick's ingress signature stable), and a max_iters halt
                # resumes losslessly instead of silently desyncing the
                # join's left table. The fused linear program returns
                # carry=None (its in-flight state is the defer resid),
                # so the streaming headline path is untouched.
                leftover = dict(carry)
        # nodes the fused passes executed beyond the phase-A plan (for the
        # scheduler's dirty-set observability): region + exit nodes, which
        # only ran if the loop actually iterated
        extra_dirty = (set(st.region_ids) | {n.id for n in st.exit_plan}
                       if looped else set())
        return ({sid: list(batches) for sid, batches in sink_egress.items()},
                passes, rows, converged, extra_dirty, leftover)

    def run_tick_fixpoint_many(self, plan, feeds, max_iters):
        """K consecutive ticks as ONE device execution (the macro-tick).

        ``feeds`` is a list of K ``{node_id: DeltaBatch}`` ingress dicts
        with identical node sets and identical padded capacities. Only
        sink-free graphs qualify (sink egress would need per-tick host
        materialization): iterative graphs scan the fused fixpoint
        program, loop-free graphs scan the plain pass program. NOTE:
        the scan discards per-tick fixpoint carries between iterations,
        so a ROW-program tick that halts at max_iters inside a
        macro-tick does NOT pause/resume (its conv flag comes back
        False at block() — size max_loop_iters to quiesce, or stream
        per-tick; the fused linear program's defer resid is in-state
        and carries fine). Returns
        ``(passes_base, iters, rows, converged, extra_dirty)`` with any
        per-tick scalars device-resident (zero readbacks — the streaming
        fast path), or None when the graph/feeds don't fit (caller falls
        back to the per-tick loop).

        Why: every device execution over a tunnel carries a large fixed
        overhead (~0.1-0.3s measured, independent of program size);
        ``lax.scan``-ing K ticks into one execution amortizes it K-fold.
        """
        if not self.supports_window():
            return None
        K = len(feeds)
        node_ids = sorted(feeds[0])
        if any(sorted(f) != node_ids for f in feeds):
            return None

        t_h0 = time.perf_counter() if _trace.ENABLED else 0.0
        stack, caps = self._stack_feeds(feeds)
        if _trace.ENABLED:
            _trace.evt("stack_feeds", t_h0, time.perf_counter() - t_h0,
                       args={"ticks": K})
        return self._dispatch_many(plan, stack, caps, K, max_iters)

    def supports_window(self) -> bool:
        """Does this executor's bound graph fit the fused macro-tick /
        mega-tick window path? The scheduler's ``window_support``
        property and the serve frontend read this to decide whether the
        window path can engage at all. ``fixpoint=False`` is the
        whole-tick-fusion opt-out (and what the staged executor, whose
        states are pinned per stage device, relies on to keep tick_many
        on the per-tick fallback); sinks need per-tick host egress."""
        from reflow_tpu.executors.fixpoint import analyze

        if self.graph is None or not self.fixpoint or self.graph.sinks:
            return False
        if not self.graph.loops:
            return True
        if self._fx_unsupported:
            return False
        if self._fx_structure is None:
            self._fx_structure = analyze(self.graph)
            if self._fx_structure is None:
                self._fx_unsupported = True
                return False
        return True

    def run_window(self, plan, feeds, max_iters):
        """One K-tick commit window as ONE dispatch fed from the
        device-resident ingress queue (the compiled mega-tick).

        Same contract as :meth:`run_tick_fixpoint_many` — ``feeds`` is a
        list of K ``{node_id: DeltaBatch}`` dicts over an identical
        (scheduler-padded) source set — but instead of restacking host
        [K, C] arrays every window, each batch is index-written into a
        persistent per-(plan, caps, K) queue slot and the window program
        scans the queue buffers in place. Returns the
        ``(passes_base, iters, rows, converged, extra_dirty)`` tuple
        with per-tick counters device-resident, or None when the window
        doesn't fit (device-resident batches, rows above
        ``megatick_max_rows``, unsupported graph) — the scheduler then
        falls back to the stacked/per-tick paths.

        This is the depth-1 composition of the staged lifecycle the
        pipelined pump drives directly: :meth:`stage_window` →
        :meth:`dispatch_window` → :meth:`retire_window`.
        """
        sw = self.stage_window(plan, feeds, max_iters)
        if sw is None:
            return None
        out = self.dispatch_window(sw)
        if out is None:
            return None
        self.retire_window(sw)
        return out

    def stage_window(self, plan, feeds, max_iters):
        """Front half of the window lifecycle: validate the window fits
        the fused path, slot-write every host batch into the ingress
        queue's staging generation, and SEAL that generation (its buffers
        now belong to the upcoming dispatch — the queue's next write
        rotates onto a fresh set, so a pipelined caller can stage window
        N+1 while N is in flight). Returns a :class:`StagedWindow` to
        pass to :meth:`dispatch_window`, or None when the window doesn't
        fit (same conditions as :meth:`run_window`; nothing is staged or
        sealed in that case).

        A successful stage GUARANTEES the dispatch can engage: for loop
        graphs the fused fixpoint program (``call_many``) is built and
        cache-checked here, so the caller may commit irreversible work
        (WAL appends) between stage and dispatch without risking a
        silent fallback in between."""
        if not self.supports_window():
            return None
        K = len(feeds)
        node_ids = sorted(feeds[0])
        if any(sorted(f) != node_ids for f in feeds):
            return None
        caps: Dict[int, int] = {}
        for nid in node_ids:
            rows = 0
            for f in feeds:
                b = f[nid]
                if hasattr(b, "nonzero"):
                    # already device-resident: no host rows to slot-write
                    # (and len() would force a readback) — stack path
                    return None
                rows = max(rows, len(b))
            if rows > self.megatick_max_rows:
                return None
            caps[nid] = bucket_capacity(rows)

        if self.graph.loops:
            # pre-build the fused fixpoint program NOW: dispatch must not
            # be able to return None after the caller has WAL-logged the
            # staged window (a post-stage fallback would double-append)
            sig = ("fx", tuple(n.id for n in plan),
                   tuple(sorted(caps.items())), max_iters)
            prog = self._cache.get(sig)
            if prog is None:
                prog = self._build_fixpoint(plan, caps, max_iters)
                if prog is None:
                    return None
                self._cache[sig] = prog
            if not hasattr(prog, "call_many"):
                return None

        qsig = ("ingress_q", tuple(n.id for n in plan),
                tuple(sorted(caps.items())), K)
        queue = self._cache.get(qsig)
        if queue is None:
            from reflow_tpu.executors.ingress_queue import DeviceIngressQueue

            # negotiate capacity with the arena BEFORE reserving device
            # memory: impossible ingress sizes raise here, not mid-window
            self._track_arena(plan, caps)
            queue = DeviceIngressQueue(
                {nid: self.graph.nodes[nid].spec for nid in node_ids},
                caps, K, placement=self._ingress_placement())
            self._cache[qsig] = queue

        t_h0 = time.perf_counter() if _trace.ENABLED else 0.0
        for t, f in enumerate(feeds):
            for nid in node_ids:
                queue.write(t, nid, f[nid])
        if _trace.ENABLED:
            _trace.evt("queue_write", t_h0, time.perf_counter() - t_h0,
                       args={"ticks": K, "slots": K * len(node_ids),
                             "inflight": queue.in_flight})
        stack = queue.stacked()
        gen = queue.seal()
        return StagedWindow(plan, caps, K, max_iters, queue, gen, stack,
                            qsig)

    def dispatch_window(self, sw: "StagedWindow"):
        """Middle of the window lifecycle: one device dispatch over the
        staged stack (DONATED to the program). Stores the program's
        returned zeroed pass-through stack on ``sw.fresh`` for
        :meth:`retire_window` — the dispatch itself is async, so a
        pipelined caller returns here while the device is still
        executing and can immediately stage the next window."""
        try:
            out = self._dispatch_many(sw.plan, sw.stack, sw.caps, sw.K,
                                      sw.max_iters, window=True, staged=sw)
        except Exception:
            # the stack was DONATED: if the dispatch died mid-flight the
            # queue's buffers are gone — drop it so the next window
            # allocates fresh instead of writing into deleted arrays
            self._cache.pop(sw.qsig, None)
            raise
        if out is None:
            # unreachable by construction (stage pre-builds the program);
            # nothing was donated, so un-seal the generation
            sw.queue.cancel(sw.gen)
            return None
        self.window_dispatches += 1
        return out

    def retire_window(self, sw: "StagedWindow") -> None:
        """Tail of the window lifecycle: hand the dispatched program's
        fresh zeroed stack back to the ingress queue, re-asserting
        placement and freeing the generation for restaging. Off the
        critical path — a pipelined pump runs this after the NEXT window
        is already in flight."""
        sw.queue.retire(sw.gen, sw.fresh)
        sw.fresh = None

    def cancel_window(self, sw: "StagedWindow") -> None:
        """Abandon a staged window whose dispatch never ran (nothing was
        donated): the generation goes straight back to the free list."""
        sw.queue.cancel(sw.gen)

    def _window_signature(self, plan, caps) -> Optional[tuple]:
        """Process-wide share key for a loop-free window program: the
        whole graph's structural tokens plus the plan positions and
        capacity buckets. None when sharing is off for this executor or
        any node resists tokenization (``_Unshareable``) — those fall
        back to the per-executor cache, never to a wrong share."""
        if not self._share_window_programs or self.graph is None:
            return None
        try:
            nodes = tuple(_node_token(n) for n in self.graph.nodes)
        except _Unshareable:
            return None
        return ("pass_many", nodes, tuple(n.id for n in plan),
                tuple(sorted(caps.items())))

    def _dispatch_many(self, plan, stack, caps, K, max_iters, *,
                       window: bool = False, staged=None):
        """Shared macro-tick dispatch tail: compile (or reuse) the K-tick
        scan program for ``plan``/``caps``, run it over the [K, C]
        ingress ``stack``, and return the scheduler-facing
        ``(passes_base, iters, rows, converged, extra_dirty)`` tuple
        (None when the fixpoint program lacks a fused ``call_many``).
        The stack is DONATED to the program; when ``staged`` (a
        :class:`StagedWindow`) is given, the program's returned fresh
        (zeroed) stack is parked on it for the retire step instead of
        being re-adopted inline — the queue and the window never hold
        two live copies either way. ``window=True`` tags the dispatch
        span as the mega-tick path and wraps it in a ``jax.profiler``
        annotation so Perfetto lines host stages up against device
        occupancy."""
        from reflow_tpu.utils.metrics import profile_annotation

        if not self.graph.loops:
            # loop-free sink-free graph (e.g. streaming TF-IDF): scan the
            # PLAIN pass program over the K stacked feeds — one device
            # execution for K ticks, zero per-tick egress by construction
            sig = ("pass_many", tuple(n.id for n in plan),
                   tuple(sorted(caps.items())))
            prog = self._cache.get(sig)
            if prog is None:
                shared_sig = self._window_signature(plan, caps)
                if shared_sig is not None:
                    with _SHARED_WINDOW_LOCK:
                        prog = _SHARED_WINDOW_PROGRAMS.get(shared_sig)
                if prog is not None:
                    # a structurally-identical tenant already traced this
                    # window — adopt its program (jax compiles per
                    # device/sharding underneath, so cross-device is fine)
                    self.megatick_cache_hits += 1
                else:
                    pass_fn = self.build_pass_fn(list(plan))

                    def scan_fn(op_states, ing_stack):
                        def body(states, ing):
                            states2, egress = pass_fn(states, ing)
                            if egress:  # trace-time structural check
                                raise RuntimeError("loop-free sink-free "
                                                   "pass produced egress")
                            return states2, ()

                        states, _ = jax.lax.scan(body, op_states, ing_stack)
                        # hand back a FRESH zeroed stack: the input was
                        # donated, and returning new zeros (not the dead
                        # input) lets XLA alias the donated memory while
                        # giving the ingress queue valid buffers to adopt
                        import jax.numpy as jnp
                        return states, jax.tree.map(jnp.zeros_like,
                                                    ing_stack)

                    prog = jax.jit(scan_fn, donate_argnums=(0, 1))
                    if shared_sig is not None:
                        with _SHARED_WINDOW_LOCK:
                            prog = _SHARED_WINDOW_PROGRAMS.setdefault(
                                shared_sig, prog)
                self._cache[sig] = prog
            self._track_arena(plan, caps)
            kind = "window" if window else "pass_many"
            t_d0 = time.perf_counter() if _trace.ENABLED else 0.0
            with profile_annotation(f"reflow.window[{K}]", enabled=window):
                self.states, fresh = prog(dict(self.states), stack)
            if staged is not None:
                staged.fresh = fresh
            if _trace.ENABLED:
                _trace.evt("device_dispatch", t_d0,
                           time.perf_counter() - t_d0,
                           args={"kind": kind, "ticks": K,
                                 "device": self.device_label})
            return K, 0, 0, True, set()

        sig = ("fx", tuple(n.id for n in plan),
               tuple(sorted(caps.items())), max_iters)
        prog = self._cache.get(sig)
        if prog is None:
            prog = self._build_fixpoint(plan, caps, max_iters)
            if prog is None:
                return None
            self._cache[sig] = prog
        if not hasattr(prog, "call_many"):
            return None

        st = self._fx_structure
        self._track_arena(plan, caps)
        if st.exit_plan:
            self._track_arena(
                list(st.exit_plan),
                {n.id: 2 * n.inputs[0].spec.key_space for n in st.boundary})

        kind = "window" if window else "fixpoint_many"
        t_d0 = time.perf_counter() if _trace.ENABLED else 0.0
        with profile_annotation(f"reflow.window[{K}]", enabled=window):
            new_states, (iters, rows, conv), fresh = prog.call_many(
                dict(self.states), stack, K)
        if staged is not None:
            staged.fresh = fresh
        if _trace.ENABLED:
            _trace.evt("device_dispatch", t_d0,
                       time.perf_counter() - t_d0,
                       args={"kind": kind, "ticks": K,
                             "device": self.device_label})
        self.states = new_states
        extra_dirty = set(st.region_ids) | {n.id for n in st.exit_plan}
        passes_base = K * (1 + (1 if st.exit_plan else 0))
        return passes_base, iters, rows, conv, extra_dirty

    def _stack_feeds(self, feeds):
        """Host-side [K, C] stacking of K per-tick ingress dicts: ONE
        transfer per ingress column instead of K separate uploads. The
        upload follows the executor's ingress placement (pinned device,
        or sharded capacity axis on the mesh subclass)."""
        import numpy as _np

        import jax.numpy as _jnp

        place = self._ingress_placement()

        def _up(x):
            if place is None:
                return _jnp.asarray(x)
            if isinstance(place, tuple):
                from jax.sharding import NamedSharding, PartitionSpec

                mesh, axis = place
                dims = (None, axis) + (None,) * (x.ndim - 2)
                return jax.device_put(
                    x, NamedSharding(mesh, PartitionSpec(*dims)))
            return jax.device_put(x, place)

        K = len(feeds)
        stack = {}
        caps = {}
        for nid in sorted(feeds[0]):
            spec = self.graph.nodes[nid].spec
            cap = max(bucket_capacity(len(f[nid])) for f in feeds)
            caps[nid] = cap
            keys = _np.zeros((K, cap), _np.int32)
            weights = _np.zeros((K, cap), _np.int32)
            values = _np.zeros((K, cap) + tuple(spec.value_shape),
                               spec.value_dtype)
            for t, f in enumerate(feeds):
                b = f[nid]
                check_weight_mass(b)   # same host-boundary guard as to_device
                n = len(b)
                if n:
                    keys[t, :n] = b.keys.astype(_np.int64)
                    weights[t, :n] = b.weights
                    values[t, :n] = _np.asarray(b.values).reshape(
                        (n,) + tuple(spec.value_shape))
            stack[nid] = DeviceDelta(_up(keys), _up(values), _up(weights))
        return stack, caps

    def _build_fixpoint(self, plan, caps, max_iters):
        """Pick the fused delta-vector program when the region's operator
        chain is declared linear; otherwise the row-based while_loop.
        Returns None (and disables fixpoint fusion) when neither fits."""
        from reflow_tpu.executors.fixpoint import FixpointProgram
        from reflow_tpu.executors.linear_fixpoint import (
            LinearFixpointProgram, analyze_linear)

        if self._linear_fixpoint:
            if self._linear_structure is None:
                self._linear_structure = analyze_linear(
                    self.graph, self._fx_structure)
                if self._linear_structure is None:
                    self._linear_fixpoint = False
            if self._linear_structure is not None:
                try:
                    return LinearFixpointProgram(
                        self, plan, caps, max_iters,
                        structure=self._fx_structure,
                        linear=self._linear_structure)
                except ValueError:
                    # shapes don't fit the fused-f32 representation; use
                    # the row-based program below
                    self._linear_fixpoint = False
                    self._linear_structure = None
        try:
            return FixpointProgram(self, plan, caps, max_iters,
                                   structure=self._fx_structure)
        except ValueError:
            self._fx_unsupported = True
            return None

    def materialize(self, batch) -> DeltaBatch:
        if isinstance(batch, DeviceDelta):
            self.materialize_count += 1
            return to_host(batch)
        return batch

    def update_params(self, node: Node, params) -> None:
        """Swap a params-bearing Map's parameter pytree in place.

        Because params are program *arguments* (op state), this triggers
        no recompilation — the next tick simply runs with the new values.
        """
        import jax.numpy as jnp

        if node.id not in self.states or "params" not in self.states[node.id]:
            raise GraphError(f"{node} holds no params state")
        fresh = {
            "params": jax.tree.map(lambda x: jnp.array(x, copy=True), params)}
        if self.device is not None:
            fresh = jax.device_put(fresh, self.device)
        self.states[node.id] = fresh

    def on_states_replaced(self) -> None:
        """Checkpoint restore swapped the state tree: drop the sorted-arena
        CSR caches. The (gen, rcount) validity predicate cannot detect a
        lineage swap whose counters line up (two histories can share a
        (gen, rcount) pair over different arena contents), so restore must
        invalidate explicitly — the next loop tick rebuilds in-program."""
        self._csr_cache.clear()

    def refresh_minmax(self, node: Node, batch: DeltaBatch) -> None:
        """Host-triggered latch refresh for a buffered min/max Reduce
        (ROADMAP r3 #3): ``batch`` replays the FULL live multiset of
        every key it mentions; those keys' candidate buffers rebuild
        from it and the monotone overflow latches reset. Pure
        maintenance — the aggregate cannot change (a contradicting
        replay sets the sticky error instead). Call between ticks, from
        the same host thread that ticks (node validation lives in the
        scheduler wrapper — the one call site)."""
        from reflow_tpu.executors.lowerings import minmax_refresh_core

        d = to_device(batch, node.inputs[0].spec, device=self.device)
        K = node.inputs[0].spec.key_space
        sig = ("mmrefresh", node.id, d.capacity)
        fn = self._cache.get(sig)
        if fn is None:
            op, oshape, odt = node.op, tuple(node.spec.value_shape), \
                node.spec.value_dtype

            def refresh_fn(st, dd):
                return minmax_refresh_core(op, K, oshape, odt, st, dd)

            fn = self._cache[sig] = jax.jit(refresh_fn, donate_argnums=0)
        self.states[node.id] = fn(self.states[node.id], d)

    def check_errors(self) -> None:
        # one batched device_get for all sticky flags: every join and
        # min/max reducer carries an 'error' leaf, and per-leaf bool()
        # round trips serialize (~0.1s each on a degraded tunnel)
        flagged = [(nid, st["error"]) for nid, st in self.states.items()
                   if isinstance(st, dict) and "error" in st]
        if not flagged:
            return
        vals = jax.device_get([e for _, e in flagged])
        for (nid, _), v in zip(flagged, vals):
            if v:
                node = self.graph.nodes[nid]
                raise RuntimeError(f"{node}: {self._error_reason(node)}")

    @staticmethod
    def _error_reason(node: Node) -> str:
        if (node.kind == "op" and node.op.kind == "reduce"
                and node.op.how in ("min", "max")):
            return ("device min/max error: retraction churn exhausted a "
                    "key's candidate buffer (the bounded exactness window "
                    "— raise Reduce(candidates=...)); this tick's state "
                    "is invalid — re-run on the CPU executor or widen "
                    "the buffer")
        if node.kind == "op" and node.op.kind == "join":
            return ("join sticky error: an arena overflowed (live rows + "
                    "appends exceeded capacity even after in-program "
                    "compaction — raise arena_capacity / "
                    "left_arena_capacity); or a multiset-left product "
                    "exceeded its pair budget (raise product_slack); or, "
                    "under a sharded executor, sparse routing overflowed "
                    "its per-destination budget (key skew — raise delta "
                    "capacity or rebalance the key space); or a downstream "
                    "GroupBy's stable_key=True declaration was violated "
                    "(its key_fn read the loop value — the fused fixpoint's "
                    "dense tier caught a precomputed/runtime destination "
                    "mismatch); this tick's state is invalid")
        return ("sticky device error flag set (sparse-route overflow: key "
                "skew exceeded the ROUTE_SLACK per-destination budget); "
                "this tick's state is invalid — raise the delta capacity "
                "or rebalance the key space")

    def read_table(self, node: Node):
        import numpy as np

        st = self.states.get(node.id)
        if st is None:
            raise KeyError(f"{node} holds no materialized state")
        if node.op.kind == "reduce":
            if "error" in st and bool(st["error"]):
                raise RuntimeError(f"{node}: {self._error_reason(node)}")
            has = np.asarray(st["emitted_has"])
            vals = np.asarray(st["emitted"])
            keys = np.nonzero(has)[0]
            return {int(k): vals[k] if vals.ndim > 1 else vals[k].item()
                    for k in keys}
        if node.op.kind == "join":
            if "error" in st and bool(st["error"]):
                raise RuntimeError(f"{node}: {self._error_reason(node)}")
            if "lkeys" in st:
                raise KeyError(
                    f"{node}: a multiset-left join has no unique left "
                    f"table to read; attach a sink to observe its output")
            lw = np.asarray(st["lw"])
            lval = np.asarray(st["lval"])
            keys = np.nonzero(lw > 0)[0]
            return {int(k): lval[k] if lval.ndim > 1 else lval[k].item()
                    for k in keys}
        if node.op.kind == "knn":
            has = np.asarray(st["em_has"])
            rows = np.asarray(st["emitted"])
            return {int(q): rows[q] for q in np.nonzero(has)[0]}
        raise KeyError(f"{node} ({node.op.kind}) has no table to read")

    def _track_arena(self, plan, ingress_caps: Dict[int, int]):
        """Static per-tick capacity sanity for Join arenas.

        The *dynamic* high-water check lives inside the compiled tick
        program: a ``lax.cond`` runs the compaction kernel when an append
        would cross capacity, and a genuine overflow sets the join state's
        sticky ``error`` flag (raised at the next sync point). No device
        value is ever read back here — streaming ticks stay pipelined.
        This host check only rejects the statically impossible case: one
        tick's right-delta capacity exceeding the whole (per-shard) arena.
        ``ingress_caps`` maps seeded node ids (sources, loops, fixpoint
        boundary producers) to their delta capacities. The propagation
        itself lives in :func:`arena.propagate_plan_caps` so the
        mega-tick ingress queue negotiates against the same rules.
        """
        from reflow_tpu.executors.arena import propagate_plan_caps

        propagate_plan_caps(plan, ingress_caps, self._arena_divisor)

    # -- trace & compile one pass program ----------------------------------

    def _lower(self, node: Node, state, ins):
        """Per-node lowering hook (sharded subclass swaps in shard-aware
        keyed-op kernels; the pass traversal itself is shared)."""
        return lower_node(node, state, ins)

    def _build(self, plan: List[Node]):
        # the state pytree is donated: every tick would otherwise copy the
        # full arena + dense tables (VERDICT r2: multi-GB copies per tick
        # were a prime suspect for the streaming-mode collapse). The caller
        # contract is run_pass's: old state refs are dropped immediately.
        return jax.jit(self.build_pass_fn(plan), donate_argnums=0)

    def build_pass_fn(self, plan: List[Node], extra_egress: Sequence[int] = ()):
        """The pure, jittable pass program: ``(states, ingress) -> (states',
        egress)`` over DeviceDelta pytrees. Exposed un-jitted so callers
        (``__graft_entry__``, the sharded executor) can wrap it with their
        own ``jax.jit`` / sharding annotations.

        ``extra_egress`` adds node ids whose outputs the program must also
        return — the stage-boundary handoff for topo-partitioned execution
        (parallel/topo.py)."""
        graph = self.graph
        sink_inputs = [(s.inputs[0].id, s.id) for s in graph.sinks]
        back_edges = [(l.back_input.id, l.id) for l in graph.loops
                      if l.back_input is not None]
        extra = tuple(extra_egress)

        def pass_fn(states, ingress):
            # ingress seeds *any* node's output (sources/loops in the normal
            # tick; boundary producers in the fixpoint exit pass; stage
            # boundaries under topo-partitioning) — seeded nodes are not
            # recomputed
            outs: Dict[int, DeviceDelta] = dict(ingress)
            new_states = dict(states)
            for node in plan:
                if node.id in outs or node.kind in ("source", "loop"):
                    continue
                if node.kind == "sink":
                    continue
                ins = [outs.get(i.id) for i in node.inputs]
                if all(x is None for x in ins):
                    continue
                # absent inputs stay None: lowerings skip the corresponding
                # work entirely (trace-static), e.g. a Join with no left
                # delta never sweeps its arena
                out, st = self._lower(node, new_states.get(node.id), ins)
                if st is not None:
                    new_states[node.id] = st
                outs[node.id] = out
            egress: Dict[int, DeviceDelta] = {}
            for src_id, sink_id in sink_inputs:
                if src_id in outs:
                    egress[sink_id] = outs[src_id]
            for back_id, loop_id in back_edges:
                if back_id in outs:
                    egress[loop_id] = outs[back_id]
            for nid in extra:
                if nid in outs:
                    egress[nid] = outs[nid]
            return new_states, egress

        return pass_fn
