"""TpuExecutor: one tick pass = one jit-compiled XLA step.

North star (BASELINE.json): the DirtyScheduler's per-tick batch of
invalidated nodes is lowered to a single ``jax.jit`` step — vmapped
Map/Filter, dense segment reductions for GroupBy/Reduce, table×arena
products for Join — with delta buffers device-resident and host callbacks
only at graph sources (``to_device``) and sinks (``to_host``). Back-edge
(loop) deltas stay on device between passes; the only mid-tick readback is
one scalar liveness count per pass for the scheduler's quiescence check
(removed entirely by the on-device ``lax.while_loop`` fixpoint path — see
``fixpoint.py``).

Compiled pass programs are cached per (plan, ingress-capacity-bucket)
signature, so steady-state ticks hit the cache and pay zero tracing cost.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors.base import Executor
from reflow_tpu.executors.device_delta import (DeviceDelta, bucket_capacity,
                                               to_device, to_host)
from reflow_tpu.executors.lowerings import (DEVICE_REDUCERS, join_state,
                                            lower_node, reduce_state)
from reflow_tpu.graph import FlowGraph, GraphError, Node

__all__ = ["TpuExecutor"]


class TpuExecutor(Executor):
    name = "tpu"

    def __init__(self):
        super().__init__()
        self._cache: Dict[tuple, object] = {}
        self._arena_used: Dict[int, int] = {}  # join node id -> host upper bound

    # -- bind: validate lowerability, build device state -------------------

    def bind(self, graph: FlowGraph) -> None:
        # compiled passes close over graph nodes: rebinding the *same* graph
        # (fresh state, e.g. a full-recompute baseline) keeps the jit cache;
        # a different graph invalidates it
        if graph is not self.graph:
            self._cache.clear()
        self.graph = graph
        self.states = {}
        self._arena_used.clear()
        for node in graph.nodes:
            if node.kind != "op":
                continue
            op = node.op
            if op.kind in ("map", "filter", "groupby", "union"):
                continue
            in_specs = [i.spec for i in node.inputs]
            for s in in_specs:
                if s.key_space <= 0:
                    raise GraphError(
                        f"{node}: TPU lowering needs key_space > 0 on every "
                        f"keyed-op input Spec")
            if op.kind == "reduce":
                if op.how not in DEVICE_REDUCERS:
                    raise GraphError(
                        f"{node}: reducer {op.how!r} has no device lowering "
                        f"yet (have {DEVICE_REDUCERS}); run it on the cpu "
                        f"executor")
                self.states[node.id] = reduce_state(op, in_specs[0], node.spec)
            elif op.kind == "join":
                if not in_specs[0].unique:
                    raise GraphError(
                        f"{node}: device Join requires a unique-keyed left "
                        f"input (Spec.unique=True, e.g. a Reduce output)")
                if op.merge is None:
                    raise GraphError(
                        f"{node}: device Join requires an explicit "
                        f"vectorized merge(keys, va, vb) function")
                self.states[node.id] = join_state(op, in_specs[0], in_specs[1])
                self._arena_used[node.id] = 0
            else:
                raise GraphError(f"{node}: no TPU lowering for {op.kind}")

    # -- one pass ----------------------------------------------------------

    def run_pass(self, plan: Sequence[Node],
                 ingress: Dict[int, DeltaBatch]) -> Dict[int, object]:
        nodes_by_id = {n.id: n for n in self.graph.nodes}
        dev_ingress: Dict[int, DeviceDelta] = {}
        for nid, b in ingress.items():
            if isinstance(b, DeviceDelta):
                dev_ingress[nid] = b
            else:
                dev_ingress[nid] = to_device(b, nodes_by_id[nid].spec)

        sig = (
            tuple(n.id for n in plan),
            tuple(sorted((nid, d.capacity) for nid, d in dev_ingress.items())),
        )
        fn = self._cache.get(sig)
        if fn is None:
            fn = self._build(list(plan))
            self._cache[sig] = fn

        self._track_arena(plan, dev_ingress)  # fail loudly BEFORE truncation
        op_states = {nid: st for nid, st in self.states.items()}
        new_states, egress_dev = fn(op_states, dev_ingress)
        self.states = new_states

        # everything stays device-resident: sink batches are materialized
        # lazily by the scheduler once per tick, loop back-edges feed the
        # next pass directly on device
        return dict(egress_dev)

    def materialize(self, batch) -> DeltaBatch:
        if isinstance(batch, DeviceDelta):
            return to_host(batch)
        return batch

    def read_table(self, node: Node):
        import numpy as np

        st = self.states.get(node.id)
        if st is None:
            raise KeyError(f"{node} holds no materialized state")
        if node.op.kind == "reduce":
            has = np.asarray(st["emitted_has"])
            vals = np.asarray(st["emitted"])
            keys = np.nonzero(has)[0]
            return {int(k): vals[k] if vals.ndim > 1 else vals[k].item()
                    for k in keys}
        if node.op.kind == "join":
            lw = np.asarray(st["lw"])
            lval = np.asarray(st["lval"])
            keys = np.nonzero(lw > 0)[0]
            return {int(k): lval[k] if lval.ndim > 1 else lval[k].item()
                    for k in keys}
        raise KeyError(f"{node} ({node.op.kind}) has no table to read")

    def _track_arena(self, plan, dev_ingress):
        """Host-side conservative overflow check for Join arenas.

        The append count is data-dependent (on device); we bound it by the
        right input's capacity and fail loudly *before* silent truncation.
        """
        outs_cap: Dict[int, int] = {}
        for node in plan:
            if node.kind in ("source", "loop"):
                if node.id in dev_ingress:
                    outs_cap[node.id] = dev_ingress[node.id].capacity
                continue
            if node.kind == "sink":
                continue
            caps = [outs_cap.get(i.id, 0) for i in node.inputs]
            if all(c == 0 for c in caps):
                continue
            if node.op.kind == "join":
                self._arena_used[node.id] += caps[1]
                if self._arena_used[node.id] > node.op.arena_capacity:
                    raise GraphError(
                        f"{node}: join arena may overflow "
                        f"({self._arena_used[node.id]} appended rows vs "
                        f"capacity {node.op.arena_capacity}); raise "
                        f"arena_capacity")
                outs_cap[node.id] = 2 * node.op.arena_capacity + caps[1]
            elif node.op.kind == "reduce":
                K = node.inputs[0].spec.key_space
                outs_cap[node.id] = 2 * K if caps[0] >= K else 2 * caps[0]
            elif node.op.kind == "union":
                outs_cap[node.id] = sum(caps)
            else:
                outs_cap[node.id] = caps[0]

    # -- trace & compile one pass program ----------------------------------

    def _build(self, plan: List[Node]):
        return jax.jit(self.build_pass_fn(plan))

    def build_pass_fn(self, plan: List[Node]):
        """The pure, jittable pass program: ``(states, ingress) -> (states',
        egress)`` over DeviceDelta pytrees. Exposed un-jitted so callers
        (``__graft_entry__``, the sharded executor) can wrap it with their
        own ``jax.jit`` / sharding annotations."""
        graph = self.graph
        sink_inputs = [(s.inputs[0].id, s.id) for s in graph.sinks]
        back_edges = [(l.back_input.id, l.id) for l in graph.loops
                      if l.back_input is not None]

        def pass_fn(states, ingress):
            outs: Dict[int, DeviceDelta] = {}
            new_states = dict(states)
            for node in plan:
                if node.kind in ("source", "loop"):
                    if node.id in ingress:
                        outs[node.id] = ingress[node.id]
                    continue
                if node.kind == "sink":
                    continue
                ins = [outs.get(i.id) for i in node.inputs]
                if all(x is None for x in ins):
                    continue
                ins = [x if x is not None else DeviceDelta.empty(i.spec)
                       for x, i in zip(ins, node.inputs)]
                out, st = lower_node(node, new_states.get(node.id), ins)
                if st is not None:
                    new_states[node.id] = st
                outs[node.id] = out
            egress: Dict[int, DeviceDelta] = {}
            for src_id, sink_id in sink_inputs:
                if src_id in outs:
                    egress[sink_id] = outs[src_id]
            for back_id, loop_id in back_edges:
                if back_id in outs:
                    egress[loop_id] = outs[back_id]
            return new_states, egress

        return pass_fn
