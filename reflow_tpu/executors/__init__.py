"""Executor plugin interface (SURVEY.md §2 items 9–10).

Execution of a tick's dirty batch is pluggable: the NumPy/dict
:class:`CpuExecutor` is the default path and correctness oracle; the JAX
:class:`TpuExecutor` lowers each pass to one jit-compiled XLA step.
Executors are registered by name so the choice is a config flag
(SURVEY.md §5: the one load-bearing flag).
"""

from reflow_tpu.executors.base import Executor, register_executor, get_executor
from reflow_tpu.executors.cpu import CpuExecutor

__all__ = ["Executor", "CpuExecutor", "register_executor", "get_executor"]


def _lazy_tpu():
    # Imported lazily so host-only use never pays the jax import.
    try:
        from reflow_tpu.executors.tpu import TpuExecutor  # noqa: F401
    except ImportError as e:
        raise NotImplementedError(
            "the 'tpu' executor requires jax and reflow_tpu.executors.tpu "
            f"(import failed: {e})") from e
    return TpuExecutor


def _lazy_sharded():
    try:
        from reflow_tpu.parallel.shard import ShardedTpuExecutor  # noqa: F401
    except ImportError as e:
        raise NotImplementedError(
            "the 'sharded' executor requires jax "
            f"(import failed: {e})") from e
    return ShardedTpuExecutor


def _lazy_staged():
    try:
        from reflow_tpu.parallel.topo import StagedTpuExecutor  # noqa: F401
    except ImportError as e:
        raise NotImplementedError(
            "the 'staged' executor requires jax "
            f"(import failed: {e})") from e
    return StagedTpuExecutor


register_executor("cpu", CpuExecutor)
register_executor("tpu", _lazy_tpu)
register_executor("sharded", _lazy_sharded)
register_executor("staged", _lazy_staged)
