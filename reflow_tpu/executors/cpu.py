"""CpuExecutor: the default path and correctness oracle (SURVEY.md §2 #10).

Interprets each dirty node with the op's exact host-side semantics
(``ops/core.py``): dict/Counter state, arbitrary hashable keys and values.
Deliberately simple — this is the baseline the TPU executor is
differentially tested against and benchmarked against (north star: ≥20×).
"""

from __future__ import annotations

import time
from typing import Dict, Sequence

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors.base import Executor
from reflow_tpu.graph import Node
from reflow_tpu.obs import trace as _trace

__all__ = ["CpuExecutor"]


class CpuExecutor(Executor):
    name = "cpu"

    def run_pass(self, plan: Sequence[Node],
                 ingress: Dict[int, DeltaBatch]) -> Dict[int, DeltaBatch]:
        t0 = time.perf_counter() if _trace.ENABLED else 0.0
        outputs: Dict[int, DeltaBatch] = {}
        egress: Dict[int, DeltaBatch] = {}
        for node in plan:
            if node.kind in ("source", "loop"):
                out = ingress.get(node.id, DeltaBatch.empty())
            elif node.kind == "sink":
                (inp,) = node.inputs
                out = outputs.get(inp.id, DeltaBatch.empty())
                egress[node.id] = out.consolidate()
                continue
            else:
                ins = [outputs.get(i.id, DeltaBatch.empty()) for i in node.inputs]
                if all(len(b) == 0 for b in ins):
                    continue
                out = node.op.apply(self.states[node.id], ins)
            if len(out):
                outputs[node.id] = out
        # back-edges: deltas arriving at loop variables drive the next pass
        for loop in self.graph.loops:
            if loop.back_input is not None and loop.back_input.id in outputs:
                back = outputs[loop.back_input.id].consolidate()
                if len(back):
                    egress[loop.id] = back
        if _trace.ENABLED:
            _trace.evt("cpu_pass", t0, time.perf_counter() - t0,
                       args={"nodes": len(plan)})
        return egress
