"""Device-resident delta buffers: padded, columnar, shape-static.

SURVEY.md §2 item 7 (TPU-native equivalent of reflow's Python-object delta
buffers) and §7 hard part (a): XLA needs static shapes, so device deltas are
fixed-capacity columns with **weight-0 padding** — a zero-weight row is a
no-op of the multiset algebra, so every kernel can process all ``capacity``
slots uniformly with no masking beyond the weights themselves. Padding rows
carry key 0 so scatter/gather indices stay in range (their weight of 0 makes
them vanish).

Capacities are bucketed to powers of two to bound jit recompiles
(§7 hard part (a): recompile-on-capacity-growth, bucketed).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from reflow_tpu.delta import DeltaBatch, Spec

__all__ = ["DeviceDelta", "bucket_capacity", "to_device", "to_host"]

MIN_CAPACITY = 64


def bucket_capacity(n: int) -> int:
    """Next power-of-two capacity ≥ n (min MIN_CAPACITY)."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    return 1 << (int(n) - 1).bit_length()


class DeviceDelta(NamedTuple):
    """A padded delta batch on device (a jax pytree).

    ``keys``:    int32[C]   — key ids in [0, key_space); 0 on padding rows
    ``values``:  dtype[C, *value_shape]
    ``weights``: int32[C]   — 0 marks padding / cancelled rows
    """

    keys: jax.Array
    values: jax.Array
    weights: jax.Array

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    def nonzero(self) -> jax.Array:
        """Number of live (weight != 0) rows — device scalar."""
        return jnp.sum((self.weights != 0).astype(jnp.int32))

    def __len__(self) -> int:  # host-side: forces a scalar readback
        return int(self.nonzero())

    @staticmethod
    def empty(spec: Spec, capacity: int = MIN_CAPACITY) -> "DeviceDelta":
        return DeviceDelta(
            keys=jnp.zeros((capacity,), jnp.int32),
            values=jnp.zeros((capacity,) + tuple(spec.value_shape),
                             spec.value_dtype),
            weights=jnp.zeros((capacity,), jnp.int32),
        )


#: per-batch |w| mass bound of the device path's exact-f32 fold
MAX_BATCH_WEIGHT_MASS = 1 << 24


def check_weight_mass_value(total_mass) -> None:
    """The ONE definition of the f32-exactness mass guard (threshold and
    message), shared by every ingestion path — single-device, pre-sharded
    chunks, and process-local multi-controller batches."""
    if total_mass >= MAX_BATCH_WEIGHT_MASS:
        raise ValueError(
            "batch weight mass >= 2**24 exceeds the device path's exact "
            "float32 range; split the batch across ticks")


def check_weight_mass(batch: DeltaBatch) -> None:
    """Reject batches the device path cannot fold exactly.

    The device Reduce folds weights through a fused float32 scatter-add
    (lowerings._scatter_contribs); a per-batch |w| mass beyond 2**24
    would be silently inexact — fail loudly at the host boundary. Every
    host->device ingestion path (to_device, the macro-tick stacker) must
    call this."""
    if len(batch):
        check_weight_mass_value(int(np.abs(batch.weights).sum()))


def to_device(batch: DeltaBatch, spec: Spec,
              capacity: Optional[int] = None,
              device=None) -> DeviceDelta:
    """Host DeltaBatch -> padded DeviceDelta (the source host boundary).

    ``device`` places the columns directly on a specific device in one
    host->device hop (the pre-sharded ingestion path,
    ``parallel.mesh.shard_batch``); None uses the default device.
    """
    n = len(batch)
    cap = capacity if capacity is not None else bucket_capacity(n)
    if n > cap:
        raise ValueError(f"batch of {n} rows exceeds capacity {cap}")
    check_weight_mass(batch)
    keys = np.zeros(cap, np.int32)
    weights = np.zeros(cap, np.int32)
    values = np.zeros((cap,) + tuple(spec.value_shape), spec.value_dtype)
    if n:
        keys[:n] = batch.keys.astype(np.int64)
        weights[:n] = batch.weights
        values[:n] = np.asarray(
            np.stack([np.asarray(v) for v in batch.values])
            if batch.values.dtype == object else batch.values
        ).reshape((n,) + tuple(spec.value_shape))
    if device is not None:
        return DeviceDelta(*jax.device_put((keys, values, weights), device))
    return DeviceDelta(jnp.asarray(keys), jnp.asarray(values), jnp.asarray(weights))


def to_host(d: DeviceDelta) -> DeltaBatch:
    """DeviceDelta -> host DeltaBatch, dropping padding (the sink boundary)."""
    keys = np.asarray(d.keys)
    values = np.asarray(d.values)
    weights = np.asarray(d.weights)
    live = weights != 0
    return DeltaBatch(keys[live].astype(np.int64), values[live], weights[live])
