"""Fused delta-vector fixpoint: frontier-proportional loop passes.

The row-based on-device fixpoint (``fixpoint.py``) does O(arena) work per
loop pass: the Join sweeps its whole append arena and the Reduce
scatter-adds the full product, regardless of how many keys actually
changed. Profiling the north-star PageRank churn tick (100k nodes / 1M
edges / 1% churn, real chip) shows why that hurts: the live frontier is
160k-900k edges for the first ~6 passes and then collapses to a few
thousand, while the row-based program pays for ~4.9M product rows on
every one of its ~17 passes.

This module exploits a *declared-linear* loop region to make per-pass cost
proportional to the live frontier:

    loop L -> Join(left=L, linear_left) -> [GroupBy] -> [linear Maps]
           -> [Union with region-external streams] -> Reduce('sum', tol)
           -> close_loop(L, ...)

For such a region the per-pass delta stream through the chain is fully
determined by its *linear observables* per key — ``dval[k] = Σ w·v`` and
``dw[k] = Σ w`` of the loop delta — because every operator maps weighted
sums to weighted sums. The loop carry therefore collapses from padded
delta rows to one dense [K, P+1] array (``dval`` flattened + ``dw``), and
one pass becomes:

    1. frontier = keys with any nonzero observable and out-degree > 0
    2. gather exactly the frontier's arena rows (CSR over the arena) and
       push ``merge/key_fn/value_fn/maps`` through them —
       ``Σ_j sw_j·φ_j(dval[k])`` per consumed edge j
    3. one fused scatter-add of (value, weight) contributions into the
       Reduce's dense tables
    4. the Reduce's dense emission diff (tol-gated) becomes the next
       observables directly — no rows are ever materialized

Step 2's gather capacity adapts per pass: the exact frontier edge count
(a dot of the frontier mask with the degree vector) selects one of a few
static budget tiers via ``lax.switch``, with a full-arena dense branch as
the always-correct top tier. TPU random access runs at a few tens of
million rows/s, so everything row-shaped is fused into stacked-column
single gathers, and the ragged segment->slot mapping uses a
scatter-of-starts + cumsum (a measured ~13x over ``searchsorted``'s
binary-search loop at 1M slots).

**Persistent CSR (round 4).** The CSR over the arena used to be rebuilt
from scratch every tick (~25-30ms device at a 1.31M-row arena,
argsort-dominated — VERDICT r3 #2). The arena is an append-only log
between compactions, so the sorted base is now a cache that PERSISTS
across ticks on the program object: rows ``[0, count)`` stay sorted in
``svalw`` with their ``geo`` (start, degree) table, and each tick only
sorts the small append TAIL ``[count, rcount)`` into its own window CSR
(capacity ``Ft``, a fraction of the arena). A loop pass then pushes the
frontier through BOTH segments (two tier-switched gathers whose dense
contribution tables sum before one fold), which costs O(tail frontier)
extra instead of O(arena log arena) fixed. The cache self-invalidates:
compaction bumps the arena's ``gen`` counter, and a gen mismatch, a
shrunken ``rcount``, or a tail overflowing ``Ft`` forces an in-program
full rebuild (``lax.cond``). The cache is pure derived state — never
checkpointed, safe across rebinds, correct under program interleaving —
because validity is decided only against the live arena's (gen, rcount).

State transitions stay exactly the row-program's: the Reduce's
wsum/wcnt/emitted tables evolve identically (the linear observables are
all the row program ever folds into them), and the Join's left table is
patched densely at loop exit (``lval = emitted where live``,
``lw += has_final - has_entry`` — per-pass retract/insert pairs cancel;
``has_entry`` is the PRE-tick table because the loop folds phase A's
emission too). Boundary telescoping and the exit pass are inherited
unchanged from ``FixpointProgram``'s host structure.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.fixpoint import (FixpointStructure,
                                           _MacroTickMixin, _emitted_diff)
from reflow_tpu.executors.lowerings import (_agg_tables, _bcast_w, _differs,
                                            _masked_contrib)
from reflow_tpu.graph import FlowGraph, Node

__all__ = ["LinearFixpointProgram", "LinearStructure", "analyze_linear"]

#: offsets/degrees/keys ride in f32 columns of fused gathers; they must be
#: exactly representable
_F32_EXACT = 1 << 24


def _f32_roundtrip_safe(dtype) -> bool:
    """Whether every value of ``dtype`` survives a cast through float32.

    The budget tiers stack arena/loop values into f32 gather columns
    (ADVICE r2: int32 >= 2**24, int64, and f64 payloads would silently
    lose precision there and disagree with the dense tier).
    """
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating):
        return dt.itemsize <= 4   # f32 exact; bf16/f16 widen losslessly
    if jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_:
        return dt.itemsize <= 2   # int8/int16/uint* fit in f32's mantissa
    return False


@dataclasses.dataclass(frozen=True)
class LinearStructure:
    """A loop region matching the fused delta-vector pattern."""

    loop: Node                    # the loop variable (unique-keyed)
    join: Node                    # Join(left=loop, right external, linear)
    groupby: Optional[Node]       # optional re-key after the join
    maps: Tuple[Node, ...]        # linear Maps after the (re-keyed) join
    union: Optional[Node]         # optional Union with external streams
    reduce: Node                  # Reduce('sum'), closes the loop


def analyze_linear(graph: FlowGraph,
                   structure: FixpointStructure) -> Optional[LinearStructure]:
    """Match the region against the linear-chain pattern; None = no match."""
    if len(structure.loops) != 1:
        return None
    (loop,) = structure.loops
    region = {n.id: n for n in structure.loop_plan}

    # the loop's only region consumer must be a declared-linear Join with
    # the loop variable on the (unique-keyed) left and an external right
    consumers = [c for c, _ in graph.consumers(loop)]
    if len(consumers) != 1:
        return None
    join = consumers[0]
    if (join.kind != "op" or join.op.kind != "join"
            or not join.op.linear_left or join.op.merge is None
            or join.id not in region):
        return None
    if join.inputs[0] is not loop or not join.inputs[0].spec.unique:
        return None
    if join.inputs[1].id in region:
        return None  # arena must be static during the loop

    # walk the single-consumer chain join -> [groupby] -> maps* -> [union]
    # -> reduce
    groupby: Optional[Node] = None
    maps: List[Node] = []
    union: Optional[Node] = None
    node = join
    red: Optional[Node] = None
    while red is None:
        cons = [c for c, _ in graph.consumers(node) if c.id in region]
        if len(cons) != 1:
            return None
        prev, node = node, cons[0]
        if node.kind != "op":
            return None
        k = node.op.kind
        if k == "groupby":
            if groupby is not None or maps or union is not None:
                return None  # at most one, directly after the join
            groupby = node
        elif k == "map":
            if not node.op.linear or union is not None:
                return None
            maps.append(node)
        elif k == "union":
            if union is not None:
                return None
            # every other Union input must be region-external (quiet
            # during the loop)
            for inp in node.inputs:
                if inp is not prev and inp.id in region:
                    return None
            union = node
        elif k == "reduce":
            red = node
        else:
            return None

    if red.op.how != "sum" or loop.back_input is not red:
        return None
    # the Reduce must be the region's only boundary node (telescoping)
    if any(b is not red for b in structure.boundary):
        return None
    # every region node must be on the recognized chain
    chain_ids = {loop.id, join.id, red.id}
    chain_ids.update(m.id for m in maps)
    if groupby is not None:
        chain_ids.add(groupby.id)
    if union is not None:
        chain_ids.add(union.id)
    if set(region) != chain_ids:
        return None
    # the loop variable and the Reduce emission are the same collection
    if (loop.spec.key_space != red.spec.key_space
            or tuple(loop.spec.value_shape) != tuple(red.spec.value_shape)):
        return None
    return LinearStructure(loop=loop, join=join, groupby=groupby,
                           maps=tuple(maps), union=union, reduce=red)


def _rowfn(fn: Callable, vectorized: bool) -> Callable:
    if vectorized:
        return fn
    return jax.vmap(fn)


def _edge_budget_tiers(arena_capacity: int) -> List[int]:
    """Static gather budgets, large to small; the dense full-arena branch
    sits above the largest. Measured regime (v5e, 1.31M-row arena,
    round-4 microbench): a budget pass costs ~2ms of O(K) machinery +
    ~55ns/slot of gathers+scatter (17.5ms at EB=262144, 3.6ms at 8192);
    the dense branch costs ~23-25ms destination-sorted (segment_sum
    16.2ms vs scatter-add 24.3ms for the fold alone) and ~34ms raw.
    Crossover is therefore near arena/3; the ladder starts at arena/4
    (clear budget win) and steps by ratio 2, bounding wasted gather
    slots to 2x the live frontier. Six tiers keep the lax.switch small;
    frontiers below the floor ride the smallest tier cheaply."""
    tiers = []
    c = 1 << (max(arena_capacity // 4, 1).bit_length() - 1)
    while c >= 2048 and len(tiers) < 6:
        tiers.append(c)
        c //= 2
    return tiers


def _tail_tiers(Ft: int) -> List[int]:
    """Budget ladder for the tail segment. The top tier is ``Ft`` itself
    (the tail's frontier edge count can never exceed its row count, so a
    dense fallback is unnecessary); smaller tiers halve down like the
    base ladder."""
    tiers = [Ft]
    c = Ft // 2
    while c >= 2048 and len(tiers) < 6:
        tiers.append(c)
        c //= 2
    return tiers


class LinearFixpointProgram(_MacroTickMixin):
    """One compiled tick for a linear loop region: row-based phase A +
    fused delta-vector while_loop + row-based exit pass.

    Drop-in alternative to ``FixpointProgram`` (same call contract);
    built by the executor when :func:`analyze_linear` matches. Raises
    ValueError when shapes don't fit the fused path's representation
    (caller falls back to the row program).
    """

    def __init__(self, executor, plan: Sequence[Node],
                 ingress_caps: Dict[int, int], max_iters: int, *,
                 structure: FixpointStructure,
                 linear: LinearStructure):
        graph = executor.graph
        self.structure = structure
        self.linear = linear
        self.max_iters = max_iters
        self.sink_ids = [s.id for s in graph.sinks]

        L, J, R = linear.loop, linear.join, linear.reduce
        if (L.spec.key_space >= _F32_EXACT
                or J.op.arena_capacity >= _F32_EXACT
                or R.inputs[0].spec.key_space >= _F32_EXACT):
            raise ValueError("key space / arena too large for fused-f32 "
                             "index columns")
        for what, dt in (("arena value", J.inputs[1].spec.value_dtype),
                         ("join output value", J.spec.value_dtype),
                         ("loop value", L.spec.value_dtype),
                         ("reduce value", R.spec.value_dtype)):
            if not _f32_roundtrip_safe(dt):
                raise ValueError(
                    f"{what} dtype {jnp.dtype(dt).name} does not round-trip "
                    f"exactly through the fused loop's float32 columns; "
                    f"using the row-based fixpoint")

        full_pass = executor.build_pass_fn(list(plan))
        exit_pass = (executor.build_pass_fn(list(structure.exit_plan))
                     if structure.exit_plan else None)

        gb = linear.groupby
        K = L.spec.key_space                   # loop/left key space
        KR = R.inputs[0].spec.key_space        # reduce key space
        odtype = J.spec.value_dtype
        rdtype = R.spec.value_dtype
        vdtype = J.inputs[1].spec.value_dtype  # arena value dtype
        tol = R.op.tol
        loop_vshape = tuple(L.spec.value_shape)
        P = 1
        for s in loop_vshape:
            P *= s
        arena_vshape = tuple(J.inputs[1].spec.value_shape)
        Q = 1
        for s in arena_vshape:
            Q *= s
        #: cross-tick residual deferral (close_loop defer_passes): cap the
        #: while_loop at ``defer`` passes per tick and carry the live
        #: observables ``xw`` across ticks in the loop node's ``resid``
        #: state leaf instead of iterating to quiescence. The left-table
        #: patch then tracks the FOLDED collection A = emitted - resid
        #: (in-flight emission rows have not passed through the Join yet),
        #: which keeps the schedule exactly equal to a host loop that
        #: stops after the same passes. Accuracy contract: docs/guide.md.
        defer = L.defer_passes
        mi = min(max_iters, defer) if defer else max_iters
        # shard context: under a ShardedTpuExecutor the whole loop runs
        # inside ONE shard_map region — per-shard CSR over the local arena
        # slice (arena keys are shard-local by construction of the routed
        # Join), a GLOBAL-domain contribution scatter combined with one
        # psum_scatter per pass onto the owned key slice, and globally
        # uniform tier selection so the collectives inside lax.switch
        # branches can never diverge across devices (VERDICT r2 item 5)
        mesh = getattr(executor, "mesh", None)
        axis = getattr(executor, "axis", None) if mesh is not None else None
        nsh = executor.n if axis is not None else 1
        if K % nsh or J.op.arena_capacity % nsh:
            raise ValueError("key space / arena not divisible by mesh size")
        Rl = J.op.arena_capacity // nsh
        tiers = _edge_budget_tiers(Rl)
        #: tail window capacity: appends since the last full CSR rebuild
        #: accumulate here; overflow forces a rebuild. Rl/8 amortizes the
        #: rebuild over ~8 windows of appends while keeping the per-tick
        #: tail sort small.
        Ft = min(Rl, max(2048, Rl // 8))
        tail_tiers = _tail_tiers(Ft)
        merge = J.op.merge
        #: destination-sorted dense tier: available when every arena row's
        #: output key is loop-value-independent (GroupBy(stable_key=True),
        #: or no re-key at all — then the destination IS the join key).
        #: The dense sweep's contribution scatter becomes a sorted
        #: segment_sum (measured 16.2ms vs 24.3ms scatter-add at 1.31M
        #: rows, v5e), with per-row destinations precomputed at CSR build.
        stable_dst = gb is None or gb.op.stable_key
        key_fn = _rowfn(gb.op.key_fn, gb.op.vectorized) if gb else None
        value_fn = (_rowfn(gb.op.value_fn, gb.op.vectorized)
                    if gb is not None and gb.op.value_fn is not None else None)
        map_fns = [_rowfn(m.op.fn, m.op.vectorized) for m in linear.maps]
        boundary = structure.boundary
        loop_id, join_id, red_id = L.id, J.id, R.id

        def push(src_keys, x, dwx, vb, ew):
            """Per-edge contributions of the frontier push.

            src_keys [E'] global join keys; x [E', *loop_vshape] per-key
            dval gathered per edge; dwx [E'] per-key net weight; vb
            [E', *arena_vshape] arena values; ew [E'] arena row weights
            (0 = dead or out-of-budget). -> (okey, wsum_c, wcnt_c).
            """
            merged = jnp.asarray(merge(src_keys, x, vb), odtype)
            if key_fn is not None:
                okey = jnp.asarray(key_fn(src_keys, merged), jnp.int32)
            else:
                okey = src_keys
            okey = jnp.where(ew == 0, 0, okey)
            val = merged
            if value_fn is not None:
                val = value_fn(src_keys, merged)
            for fn in map_fns:
                val = fn(val)
            wv = _masked_contrib(ew, jnp.asarray(val, jnp.float32))
            return okey, wv, (dwx * ew).astype(jnp.float32)

        def scatter_tab(okey, wv, wc):
            """One fused scatter-add of a push's contributions into a
            GLOBAL-key-domain [KR, P+1] table (okey is a global dst id).
            Segments (base/tail) each produce a table; the tables SUM
            before the single fold + psum_scatter of the pass."""
            flat = wv.reshape(wv.shape[0], -1)
            upd = jnp.concatenate([flat, wc[:, None]], axis=-1)
            return jnp.zeros((KR, upd.shape[1]), jnp.float32
                             ).at[okey].add(upd, mode="drop")

        def fold(rstate, tab):
            """Fold one pass's summed contribution table into the Reduce's
            running tables, then the dense emission diff (exactly
            _lower_reduce's dense mode, expressed on the vectors).

            Sharded: one tiled psum_scatter both sums cross-shard
            contributions and hands each shard its owned slice — the
            fold, diff, and next observables are then local.
            """
            if axis is not None:
                tab = jax.lax.psum_scatter(tab, axis, scatter_dimension=0,
                                           tiled=True)
            Ko = tab.shape[0]              # owned key rows (KR / nsh)
            vshape = loop_vshape
            wsum = rstate["wsum"] + tab[:, :-1].reshape((Ko,) + vshape)
            wcnt = rstate["wcnt"] + tab[:, -1].astype(jnp.int32)

            emitted, em_has = rstate["emitted"], rstate["emitted_has"]
            agg, exists = _agg_tables(R.op, wsum, wcnt, rdtype)
            changed = _differs(agg, emitted, tol)
            ins_m = exists & (~em_has | changed)
            ret_m = em_has & (~exists | changed)
            new_emitted = jnp.where(_bcast_w(ins_m, agg), agg, emitted)
            new_has = jnp.where(ins_m, True,
                                jnp.where(ret_m & ~exists, False, em_has))
            # next-pass linear observables of the emission delta:
            # rows are (emitted_old, -1)[ret] + (agg, +1)[ins]
            dval = (jnp.where(_bcast_w(ins_m, agg), agg.astype(jnp.float32),
                              0.0)
                    - jnp.where(_bcast_w(ret_m, emitted),
                                emitted.astype(jnp.float32), 0.0))
            dwv = (ins_m.astype(jnp.float32) - ret_m.astype(jnp.float32))
            xw = jnp.concatenate([dval.reshape(Ko, P), dwv[:, None]], axis=1)
            rows = jnp.sum(ins_m.astype(jnp.int32) + ret_m.astype(jnp.int32))
            if axis is not None:
                rows = jax.lax.psum(rows, axis)
            new_rstate = dict(rstate)
            new_rstate.update(wsum=wsum, wcnt=wcnt, emitted=new_emitted,
                              emitted_has=new_has)
            return new_rstate, xw, rows

        def budget_tab(EB, geo, svalw, xw, base):
            """Frontier-compacted push at static gather budget EB over one
            CSR segment (base or tail) -> contribution table.

            One gather builds the compacted frontier table, a
            scatter-of-starts + cumsum assigns segment slots to frontier
            segments, one gather expands the frontier table per slot, one
            gather fetches the segment's sorted rows, one scatter applies
            contributions. All indices are LOCAL to this shard's key
            slice; ``base`` rebases them to global ids for merge/key_fn.
            """
            Klc = geo.shape[0]
            deg = geo[:, 1]
            mask = jnp.any(xw != 0, axis=1) & (deg > 0)
            # compact frontier keys; count <= frontier edge count <= EB
            # because every compacted key has deg >= 1
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            tgt = jnp.where(mask, pos, EB)
            ids = jnp.full((EB,), Klc, jnp.int32).at[tgt].set(
                jnp.arange(Klc, dtype=jnp.int32), mode="drop")
            ids_c = jnp.minimum(ids, Klc - 1)
            # one fused gather: offsets, deg, key, observables per frontier
            ftab = jnp.concatenate(
                [geo, jnp.arange(Klc, dtype=jnp.float32)[:, None], xw],
                axis=1)
            fr = ftab[ids_c]                   # [EB, 3 + P + 1]
            fdeg = jnp.where(ids < Klc, fr[:, 1], 0.0)
            cum = jnp.cumsum(fdeg)
            total = cum[-1]
            start = cum - fdeg
            # slot j belongs to the frontier entry whose segment starts at
            # or before j: scatter segment starts, running-sum them
            spos = jnp.where(fdeg > 0, start.astype(jnp.int32), EB)
            marks = jnp.zeros((EB,), jnp.int32).at[spos].add(1, mode="drop")
            owner = jnp.cumsum(marks) - 1
            owner = jnp.clip(owner, 0, EB - 1)
            # expand the frontier table per slot (one gather), with the
            # segment start appended so each slot finds its sorted row
            frs = jnp.concatenate([fr, start[:, None]], axis=1)[owner]
            j = jnp.arange(EB, dtype=jnp.float32)
            valid = (j < total) & (frs[:, 1] > 0)
            eidx = (frs[:, 0] + (j - frs[:, -1])).astype(jnp.int32)
            eidx = jnp.where(valid, eidx, 0)
            src = frs[:, 2].astype(jnp.int32)
            src = jnp.clip(src, 0, Klc - 1)
            x = frs[:, 3:3 + P].reshape((EB,) + loop_vshape)
            dwx = frs[:, 3 + P]
            sv = svalw[eidx]                   # [EB, Q+1]
            vb = jnp.asarray(sv[:, :Q], vdtype).reshape((EB,) + arena_vshape)
            ew = jnp.where(valid, sv[:, Q].astype(jnp.int32), 0)
            okey, wv, wc = push(src + base, jnp.asarray(x, jnp.float32),
                                dwx, vb, ew)
            return scatter_tab(okey, wv, wc), jnp.zeros((), jnp.bool_)

        def dense_tab(arena, xw, base):
            """Full-arena push — the always-correct top tier. Sweeps the
            RAW arena rows (base and tail alike), so when this branch is
            selected the tail switch must contribute zeros."""
            rk, rv, rw = arena
            g = xw[rk]                          # [Rl, P+1] one gather
            x = g[:, :P].reshape((rk.shape[0],) + loop_vshape)
            okey, wv, wc = push(rk + base, x, g[:, P], rv, rw)
            return scatter_tab(okey, wv, wc), jnp.zeros((), jnp.bool_)

        def dense_sorted_tab(dokey, dsrc, dvalw, xw, base):
            """Base-rows dense push over the destination-SORTED copy: the
            contribution fold is a sorted segment_sum instead of a random
            scatter-add. Covers only rows [0, count) — the tail switch
            must run alongside (tail rows are not in the sorted copy)."""
            Rl_ = dsrc.shape[0]
            src_c = jnp.clip(dsrc, 0, xw.shape[0] - 1)
            g = xw[src_c]                       # [Rl, P+1] one gather
            x = g[:, :P].reshape((Rl_,) + loop_vshape)
            vb = jnp.asarray(dvalw[:, :Q], vdtype).reshape(
                (Rl_,) + arena_vshape)
            ew = dvalw[:, Q].astype(jnp.int32)
            # stable_key declares the runtime okey equals the precomputed
            # (sorted) destination. The declaration is near-free to CHECK
            # here (okey is already computed): a key_fn that actually
            # reads the loop value would otherwise corrupt ranks
            # tier-selection-dependently (ADVICE r4) — route the mismatch
            # into the join's sticky error instead.
            okey, wv, wc = push(src_c + base, x, g[:, P], vb, ew)
            bad = jnp.any((okey != dokey) & (ew != 0))
            upd = jnp.concatenate([wv.reshape(Rl_, -1), wc[:, None]],
                                  axis=-1)
            return jax.ops.segment_sum(upd, dokey, num_segments=KR,
                                       indices_are_sorted=True), bad

        def loop_region(jstate, rstate, csr, ld, has_entry, resid):
            """Phase B on one shard's slices (the whole mesh's arrays when
            single-device): observables from the loop delta, CSR cache
            validation + tail build, the while_loop, and the Join
            left-table patch. ``ld`` rows are owner-aligned by
            construction (loop deltas are always Reduce emissions, which
            each shard emits over its owned key range). ``resid`` (defer
            mode only, else None) is the carried [Klc, P+1] observable
            block from the previous tick; the final ``xw`` is returned as
            the next tick's carry."""
            Klc = rstate["emitted_has"].shape[0]   # local loop/key rows
            if axis is not None:
                base = (jax.lax.axis_index(axis) * Klc).astype(jnp.int32)
            else:
                base = jnp.zeros((), jnp.int32)

            # loop delta rows -> dense linear observables (local keys)
            dval = jnp.zeros((Klc,) + loop_vshape, jnp.float32)
            dw = jnp.zeros((Klc,), jnp.int32)
            lk = ld.keys - base
            contrib = _masked_contrib(ld.weights, ld.values.astype(jnp.float32))
            dval = dval.at[lk].add(contrib, mode="drop")
            dw = dw.at[lk].add(ld.weights, mode="drop")
            xw = jnp.concatenate(
                [dval.reshape(Klc, P), dw.astype(jnp.float32)[:, None]],
                axis=1)
            if resid is not None:
                # carried residue joins the loop-delta stream at the FIRST
                # loop pass (pushed against the post-churn arena) — the
                # exact schedule a host loop resuming its stashed back-edge
                # rows would run, since the region is linear and the Join
                # bilinear (phase A already joined deltas against the
                # folded A, which excludes the in-flight rows)
                xw = xw + resid

            rk, rv, rw = jstate["rkeys"], jstate["rvals"], jstate["rw"]
            Rcap = rk.shape[0]
            rc = jnp.reshape(jstate["rcount"], (-1,))[0]
            gen = jnp.reshape(jstate["gen"], (-1,))[0]
            c_count = csr["count"][0]
            c_gen = csr["gen"][0]

            # CSR cache validity: the base ordering survives only while
            # the arena is append-only past ``c_count`` under the same
            # generation, and the un-sorted tail must fit its window
            rebuild = ((c_gen != gen) | (c_count > rc)
                       | (rc - c_count > Ft))

            def do_rebuild(_):
                # full rebuild: argsort the whole (per-shard) arena slice,
                # dead rows to the sentinel; bounds via scatter-count +
                # cumsum (identical to searchsorted over the sorted keys
                # at a third of the cost — tools/profile_tick.py)
                skey = jnp.where(rw != 0, rk, Klc)
                order = jnp.argsort(skey)
                svalw = jnp.concatenate(
                    [rv[order].reshape(Rcap, Q).astype(jnp.float32),
                     rw[order].astype(jnp.float32)[:, None]], axis=1)
                deg_i = jnp.zeros((Klc + 1,), jnp.int32).at[skey].add(
                    1, mode="drop")[:Klc]
                starts = jnp.cumsum(deg_i) - deg_i
                geo = jnp.stack([starts, deg_i], axis=1).astype(jnp.float32)
                out = (geo, svalw, rc)
                if stable_dst:
                    # per-row output keys with the loop value zeroed (the
                    # stable_key contract makes them loop-independent);
                    # live rows outside [0, KR) mirror scatter_tab's drop
                    gk = jnp.clip(rk, 0, Klc - 1) + base
                    x0 = jnp.zeros((Rcap,) + loop_vshape, jnp.float32)
                    merged0 = jnp.asarray(merge(gk, x0, rv), odtype)
                    if key_fn is not None:
                        ok0 = jnp.asarray(key_fn(gk, merged0), jnp.int32)
                    else:
                        ok0 = gk
                    ok_valid = (rw != 0) & (ok0 >= 0) & (ok0 < KR)
                    ok0 = jnp.where(ok_valid, ok0, 0)
                    dorder = jnp.argsort(ok0)
                    dokey = ok0[dorder]
                    dsrc = rk[dorder]
                    dvalw = jnp.concatenate(
                        [rv[dorder].reshape(Rcap, Q).astype(jnp.float32),
                         jnp.where(ok_valid[dorder], rw[dorder], 0
                                   ).astype(jnp.float32)[:, None]], axis=1)
                    out = out + (dokey, dsrc, dvalw)
                return out

            def keep(_):
                out = (csr["geo"], csr["svalw"], c_count)
                if stable_dst:
                    out = out + (csr["dokey"], csr["dsrc"], csr["dvalw"])
                return out

            built = jax.lax.cond(rebuild, do_rebuild, keep, None)
            geo_b, svalw_b, bcount = built[:3]
            if stable_dst:
                dokey_b, dsrc_b, dvalw_b = built[3:]

            # tail CSR over the fresh rows [bcount, rc): a small argsort
            # window (appends are live-compacted by join_core, so the
            # window holds only live rows below rc). Append-free ticks
            # (rc == bcount — e.g. pure left-side deltas) skip the build
            # entirely via lax.cond instead of sorting Ft sentinels.
            def build_tail(_):
                fidx = bcount + jnp.arange(Ft, dtype=jnp.int32)
                fvalid = fidx < rc
                fi_c = jnp.minimum(fidx, Rcap - 1)
                tk = jnp.where(fvalid & (rw[fi_c] != 0), rk[fi_c], Klc)
                torder = jnp.argsort(tk)
                stk = tk[torder]
                fi_s = fi_c[torder]
                svalw_t = jnp.concatenate(
                    [rv[fi_s].reshape(Ft, Q).astype(jnp.float32),
                     jnp.where(stk < Klc, rw[fi_s].astype(jnp.float32), 0.0
                               )[:, None]], axis=1)
                deg_t_i = jnp.zeros((Klc + 1,), jnp.int32).at[tk].add(
                    1, mode="drop")[:Klc]
                starts_t = jnp.cumsum(deg_t_i) - deg_t_i
                geo_t = jnp.stack([starts_t, deg_t_i], axis=1
                                  ).astype(jnp.float32)
                return geo_t, svalw_t, deg_t_i

            def empty_tail(_):
                return (jnp.zeros((Klc, 2), jnp.float32),
                        jnp.zeros((Ft, Q + 1), jnp.float32),
                        jnp.zeros((Klc,), jnp.int32))

            geo_t, svalw_t, deg_t_i = jax.lax.cond(
                rc > bcount, build_tail, empty_tail, None)

            deg_b_i = geo_b[:, 1].astype(jnp.int32)
            arena = (jnp.minimum(rk, Klc - 1), rv, rw)

            branches_b = [
                (lambda xw, EB=EB: budget_tab(EB, geo_b, svalw_b, xw, base))
                for EB in tiers
            ]
            if stable_dst:
                branches_b.append(
                    lambda xw: dense_sorted_tab(dokey_b, dsrc_b, dvalw_b,
                                                xw, base))
            else:
                branches_b.append(lambda xw: dense_tab(arena, xw, base))
            dense_ix = len(tiers)
            branches_t = [
                (lambda xw, EB=EB: budget_tab(EB, geo_t, svalw_t, xw, base))
                for EB in tail_tiers
            ]
            branches_t.append(
                lambda xw: (jnp.zeros((KR, P + 1), jnp.float32),
                            jnp.zeros((), jnp.bool_)))
            zero_ix = len(tail_tiers)

            def live(xw):
                l = jnp.any(xw != 0)
                if axis is not None:
                    # globally uniform predicate: every shard must agree
                    # on the trip count (collectives inside the body)
                    l = jax.lax.psum(l.astype(jnp.int32), axis) > 0
                return l

            def cond(c):
                rst, xw, it, rows, err = c
                return jnp.logical_and(it < mi, live(xw))

            def body(c):
                rst, xw, it, rows, err = c
                fmask = jnp.any(xw != 0, axis=1)
                if tiers:
                    nedges = jnp.sum(jnp.where(fmask, deg_b_i, 0))
                    if axis is not None:
                        # uniform tier: the worst shard picks for everyone,
                        # so lax.switch branches (which contain collectives
                        # downstream) never diverge across devices
                        nedges = jax.lax.pmax(nedges, axis)
                    # descending budgets; pick the smallest that fits.
                    # Scalar compares over the static tier list — never a
                    # materialized s32[k] literal: the remote-device runtime
                    # drops into a degraded dispatch mode (~88ms/dispatch,
                    # process-wide, permanent) after executing any program
                    # whose HLO carries a multi-element constant.
                    n_fits = sum(((jnp.int32(t) >= nedges).astype(jnp.int32)
                                  for t in tiers), jnp.zeros((), jnp.int32))
                    ix_b = jnp.where(n_fits > 0, n_fits - 1, dense_ix)
                else:
                    ix_b = jnp.full((), dense_ix, jnp.int32)
                tab, bad_b = jax.lax.switch(ix_b, branches_b, xw)
                # tail segment: skipped when the frontier doesn't touch
                # any tail source (nt == 0 — the common late-pass case
                # once the wave moves past the churned keys). The RAW
                # dense branch also sweeps tail rows, so it skips the
                # tail too; the destination-sorted dense branch covers
                # only base rows and needs the tail alongside.
                nt = jnp.sum(jnp.where(fmask, deg_t_i, 0))
                if axis is not None:
                    nt = jax.lax.pmax(nt, axis)
                nt_fits = sum(((jnp.int32(t) >= nt).astype(jnp.int32)
                               for t in tail_tiers),
                              jnp.zeros((), jnp.int32))
                # the top tail tier is Ft itself, so nt always fits
                skip_t = (nt == 0) if stable_dst else (
                    (ix_b == dense_ix) | (nt == 0))
                ix_t = jnp.where(skip_t, zero_ix,
                                 jnp.maximum(nt_fits - 1, 0))
                tab_t, bad_t = jax.lax.switch(ix_t, branches_t, xw)
                tab = tab + tab_t
                rst2, xw2, prows = fold(rst, tab)
                return (rst2, xw2, it + 1, rows + prows,
                        err | bad_b | bad_t)

            rstate, xw, iters, rows, skerr = jax.lax.while_loop(
                cond, body, (rstate, xw, jnp.zeros((), jnp.int32),
                             jnp.zeros((), jnp.int32),
                             jnp.zeros((), jnp.bool_)))
            converged = ~live(xw)
            if axis is not None:
                skerr = jax.lax.pmax(skerr.astype(jnp.int32), axis) > 0

            # patch the Join's left table densely (per-pass retract/insert
            # pairs cancel; only entry-vs-exit existence and value matter)
            has_f = rstate["emitted_has"]
            em_f = rstate["emitted"]
            new_jstate = dict(jstate)
            # a violated stable_key declaration surfaces as the join's
            # sticky error at the next sync — loudly, before corrupt
            # ranks reach any view (ADVICE r4)
            new_jstate["error"] = jstate["error"] | skerr
            if resid is None:
                new_jstate["lval"] = jnp.where(
                    _bcast_w(has_f, em_f),
                    jnp.asarray(em_f, jstate["lval"].dtype), jstate["lval"])
                new_jstate["lw"] = (jstate["lw"] + has_f.astype(jnp.int32)
                                    - has_entry.astype(jnp.int32))
            else:
                # defer mode: the final xw is still in flight, so the
                # FOLDED collection lags the emitted table by exactly its
                # observables: A = emitted - xw. Invariant at entry was
                # lw = has_entry - resid_dw (same formula, last tick), so
                # the weight delta nets the two residues. lval for keys
                # without an emission (pure retraction in flight) keeps
                # its old folded value — the where() leaves it alone.
                rout_dval = xw[:, :P].reshape((Klc,) + loop_vshape)
                lval_t = em_f.astype(jnp.float32) - rout_dval
                new_jstate["lval"] = jnp.where(
                    _bcast_w(has_f, em_f),
                    jnp.asarray(lval_t, jstate["lval"].dtype),
                    jstate["lval"])
                ddw = jnp.round(xw[:, P] - resid[:, P]).astype(jnp.int32)
                new_jstate["lw"] = (jstate["lw"] + has_f.astype(jnp.int32)
                                    - has_entry.astype(jnp.int32) - ddw)
            new_csr = {"geo": geo_b, "svalw": svalw_b,
                       "count": bcount[None], "gen": gen[None]}
            if stable_dst:
                new_csr.update(dokey=dokey_b, dsrc=dsrc_b, dvalw=dvalw_b)
            if resid is None:
                return new_jstate, rstate, new_csr, iters, rows, converged
            return new_jstate, rstate, new_csr, iters, rows, converged, xw

        def run_loop(jstate, rstate, csr, ld, has_entry, resid):
            if axis is None:
                return loop_region(jstate, rstate, csr, ld, has_entry, resid)
            from jax.sharding import PartitionSpec as PS

            jspec = executor._state_tree_specs({join_id: jstate})[join_id]
            rspec = executor._state_tree_specs({red_id: rstate})[red_id]
            cspec = {k: PS(axis) for k in csr}
            dspec = DeviceDelta(PS(axis), PS(axis), PS(axis))
            # resid (defer mode) adds one key-sharded operand and the
            # carried-out observables; None is spec'd as a leafless pytree
            rs_in = PS(axis) if resid is not None else None
            out_specs = (jspec, rspec, cspec, PS(), PS(), PS())
            if resid is not None:
                out_specs = out_specs + (PS(axis),)
            from reflow_tpu.parallel.shard import shard_map

            fn = shard_map(
                loop_region, mesh=mesh,
                in_specs=(jspec, rspec, cspec, dspec, PS(axis), rs_in),
                out_specs=out_specs, check_vma=False)
            return fn(jstate, rstate, csr, ld, has_entry, resid)

        def tick_fn(op_states, csr, ingress):
            # the loop folds every emission from phase A's onward into the
            # join's left table, so the exit patch diffs existence against
            # the PRE-tick table, not the post-phase-A one
            has_entry = op_states[red_id]["emitted_has"]
            states, eg_a = full_pass(op_states, ingress)
            snaps = {n.id: (states[n.id]["emitted"],
                            states[n.id]["emitted_has"]) for n in boundary}

            ld = eg_a.get(loop_id)
            if defer and ld is None:
                # carried residue may still be live even when phase A
                # emitted no loop delta: run the loop with an empty delta
                # (trace-static shape; weight-0 rows are no-ops)
                from reflow_tpu.executors.device_delta import MIN_CAPACITY
                ld = DeviceDelta.empty(L.spec, MIN_CAPACITY)
            if ld is not None:
                resid = states[loop_id]["resid"] if defer else None
                out = run_loop(states[join_id], states[red_id], csr, ld,
                               has_entry, resid)
                states = dict(states)
                if defer:
                    (new_jstate, rstate, csr, iters, rows, converged,
                     resid_out) = out
                    states[loop_id] = {"resid": resid_out}
                else:
                    new_jstate, rstate, csr, iters, rows, converged = out
                states[join_id] = new_jstate
                states[red_id] = rstate
            else:
                # phase A emitted no loop delta: the region is already
                # quiescent and the left-table patch would be an identity.
                # The CSR cache passes through; any phase-A appends land
                # in the next loop tick's tail via the count delta.
                iters = jnp.zeros((), jnp.int32)
                rows = jnp.zeros((), jnp.int32)
                converged = jnp.ones((), jnp.bool_)

            eg_b = {}
            if exit_pass is not None:
                diffs = {n.id: _emitted_diff(snaps[n.id], states[n.id], n)
                         for n in boundary}
                states, eg_b = exit_pass(states, diffs)

            sink_egress = {}
            for sid in self.sink_ids:
                batches = []
                if sid in eg_a:
                    batches.append(eg_a[sid])
                if sid in eg_b:
                    batches.append(eg_b[sid])
                if batches:
                    sink_egress[sid] = tuple(batches)
            return states, csr, sink_egress, iters, rows, converged

        # donate the state pytree AND the CSR cache: the arena, dense
        # tables, and sorted base update in place instead of being copied
        # every tick
        self.tick_fn = tick_fn
        self._fn = jax.jit(tick_fn, donate_argnums=(0, 1))
        self._executor = executor
        self._join_id = join_id
        self._csr_shape = (K, J.op.arena_capacity, Q, nsh, KR, stable_dst)

    def _take_csr(self):
        """Fetch (or lazily build) the ONE sorted-arena cache this join
        shares across every program signature — held on the EXECUTOR, so
        alternating ingress buckets advance one copy instead of each
        re-sorting appends the other already covered. Pure derived state:
        never part of the durable state tree, never checkpointed
        (restore/rebind drop it via the executor hooks). count=0 / gen=-1
        forces a rebuild on the first loop tick."""
        csr = self._executor._csr_cache.pop(self._join_id, None)
        if csr is not None:
            return csr
        K, R, Q, nsh, KR, stable_dst = self._csr_shape
        csr0 = {
            "geo": jnp.zeros((K, 2), jnp.float32),
            "svalw": jnp.zeros((R, Q + 1), jnp.float32),
            "count": jnp.zeros((nsh,), jnp.int32),
            "gen": jnp.full((nsh,), -1, jnp.int32),
        }
        if stable_dst:
            csr0.update(
                dokey=jnp.zeros((R,), jnp.int32),
                dsrc=jnp.zeros((R,), jnp.int32),
                dvalw=jnp.zeros((R, Q + 1), jnp.float32),
            )
        mesh = getattr(self._executor, "mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            axis = self._executor.axis
            csr0 = {k: jax.device_put(v, NamedSharding(mesh, PS(axis)))
                    for k, v in csr0.items()}
        return csr0

    def __call__(self, op_states, dev_ingress):
        """-> (states', {sink_id: (DeviceDelta, ...)}, carry, iters,
        loop_rows, converged) — the FixpointProgram call contract. The
        CSR cache threads through invisibly (held on the executor,
        donated here). carry is None: this program's in-flight loop
        state is dense observables, carried in the loop node's ``resid``
        state under defer_passes (resumable by construction); a
        max_iters halt WITHOUT defer_passes is non-resumable here
        (use defer_passes when halting mid-fixpoint is expected)."""
        states, csr, eg, iters, rows, conv = self._fn(
            op_states, self._take_csr(), dev_ingress)
        self._executor._csr_cache[self._join_id] = csr
        return states, eg, None, iters, rows, conv

    def call_many(self, op_states, ing_stack, n_ticks: int):
        """K ticks in ONE device execution, CSR cache carried through the
        scan. -> (states', (iters[K], rows[K], converged[K]),
        fresh_stack) — the ingress stack is donated (mega-tick queue
        buffers stop living across the dispatch) and the zeroed
        replacement rides back for the queue to re-bind."""
        cache = getattr(self, "_many_cache", None)
        if cache is None:
            cache = self._many_cache = {}
        prog = cache.get(n_ticks)
        if prog is None:
            tick_fn = self.tick_fn

            def scan_fn(op_states, csr, ing_stack):
                def body(carry, ing):
                    st, c = carry
                    st2, c2, sink_eg, iters, rows, conv = tick_fn(st, c, ing)
                    if sink_eg:  # trace-time structural check
                        raise RuntimeError(
                            "macro-tick requires a sink-free graph")
                    return (st2, c2), (iters, rows, conv)

                (states, csr), ys = jax.lax.scan(body, (op_states, csr),
                                                 ing_stack)
                return states, csr, ys, jax.tree.map(jnp.zeros_like,
                                                     ing_stack)

            prog = cache[n_ticks] = jax.jit(scan_fn,
                                            donate_argnums=(0, 1, 2))
        states, csr, ys, fresh = prog(op_states, self._take_csr(),
                                      ing_stack)
        self._executor._csr_cache[self._join_id] = csr
        return states, ys, fresh
