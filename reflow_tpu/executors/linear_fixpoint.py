"""Fused delta-vector fixpoint: frontier-proportional loop passes.

The row-based on-device fixpoint (``fixpoint.py``) does O(arena) work per
loop pass: the Join sweeps its whole append arena and the Reduce
scatter-adds the full product, regardless of how many keys actually
changed. Profiling the north-star PageRank churn tick (100k nodes / 1M
edges / 1% churn, real chip) shows why that hurts: the live frontier is
160k-900k edges for the first ~6 passes and then collapses to a few
thousand, while the row-based program pays for ~4.9M product rows on
every one of its ~17 passes.

This module exploits a *declared-linear* loop region to make per-pass cost
proportional to the live frontier:

    loop L -> Join(left=L, linear_left) -> [GroupBy] -> [linear Maps]
           -> [Union with region-external streams] -> Reduce('sum', tol)
           -> close_loop(L, ...)

For such a region the per-pass delta stream through the chain is fully
determined by its *linear observables* per key — ``dval[k] = Σ w·v`` and
``dw[k] = Σ w`` of the loop delta — because every operator maps weighted
sums to weighted sums. The loop carry therefore collapses from padded
delta rows to one dense [K, P+1] array (``dval`` flattened + ``dw``), and
one pass becomes:

    1. frontier = keys with any nonzero observable and out-degree > 0
    2. gather exactly the frontier's arena rows (CSR over the arena,
       rebuilt once per tick) and push ``merge/key_fn/value_fn/maps``
       through them — ``Σ_j sw_j·φ_j(dval[k])`` per consumed edge j
    3. one fused scatter-add of (value, weight) contributions into the
       Reduce's dense tables
    4. the Reduce's dense emission diff (tol-gated) becomes the next
       observables directly — no rows are ever materialized

Step 2's gather capacity adapts per pass: the exact frontier edge count
(a dot of the frontier mask with the degree vector) selects one of a few
static budget tiers via ``lax.switch``, with a full-arena dense branch as
the always-correct top tier. TPU random access runs at a few tens of
million rows/s, so everything row-shaped is fused into stacked-column
single gathers, and the ragged segment->slot mapping uses a
scatter-of-starts + cumsum (a measured ~13x over ``searchsorted``'s
binary-search loop at 1M slots).

State transitions stay exactly the row-program's: the Reduce's
wsum/wcnt/emitted tables evolve identically (the linear observables are
all the row program ever folds into them), and the Join's left table is
patched densely at loop exit (``lval = emitted where live``,
``lw += has_final - has_entry`` — per-pass retract/insert pairs cancel;
``has_entry`` is the PRE-tick table because the loop folds phase A's
emission too). Boundary telescoping and the exit pass are inherited
unchanged from ``FixpointProgram``'s host structure.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.executors.fixpoint import (FixpointStructure,
                                           _MacroTickMixin, _emitted_diff)
from reflow_tpu.executors.lowerings import (_agg_tables, _bcast_w, _differs,
                                            _masked_contrib)
from reflow_tpu.graph import FlowGraph, Node

__all__ = ["LinearFixpointProgram", "LinearStructure", "analyze_linear"]

#: offsets/degrees/keys ride in f32 columns of fused gathers; they must be
#: exactly representable
_F32_EXACT = 1 << 24


def _f32_roundtrip_safe(dtype) -> bool:
    """Whether every value of ``dtype`` survives a cast through float32.

    The budget tiers stack arena/loop values into f32 gather columns
    (ADVICE r2: int32 >= 2**24, int64, and f64 payloads would silently
    lose precision there and disagree with the dense tier).
    """
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating):
        return dt.itemsize <= 4   # f32 exact; bf16/f16 widen losslessly
    if jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_:
        return dt.itemsize <= 2   # int8/int16/uint* fit in f32's mantissa
    return False


@dataclasses.dataclass(frozen=True)
class LinearStructure:
    """A loop region matching the fused delta-vector pattern."""

    loop: Node                    # the loop variable (unique-keyed)
    join: Node                    # Join(left=loop, right external, linear)
    groupby: Optional[Node]       # optional re-key after the join
    maps: Tuple[Node, ...]        # linear Maps after the (re-keyed) join
    union: Optional[Node]         # optional Union with external streams
    reduce: Node                  # Reduce('sum'), closes the loop


def analyze_linear(graph: FlowGraph,
                   structure: FixpointStructure) -> Optional[LinearStructure]:
    """Match the region against the linear-chain pattern; None = no match."""
    if len(structure.loops) != 1:
        return None
    (loop,) = structure.loops
    region = {n.id: n for n in structure.loop_plan}

    # the loop's only region consumer must be a declared-linear Join with
    # the loop variable on the (unique-keyed) left and an external right
    consumers = [c for c, _ in graph.consumers(loop)]
    if len(consumers) != 1:
        return None
    join = consumers[0]
    if (join.kind != "op" or join.op.kind != "join"
            or not join.op.linear_left or join.op.merge is None
            or join.id not in region):
        return None
    if join.inputs[0] is not loop or not join.inputs[0].spec.unique:
        return None
    if join.inputs[1].id in region:
        return None  # arena must be static during the loop

    # walk the single-consumer chain join -> [groupby] -> maps* -> [union]
    # -> reduce
    groupby: Optional[Node] = None
    maps: List[Node] = []
    union: Optional[Node] = None
    node = join
    red: Optional[Node] = None
    while red is None:
        cons = [c for c, _ in graph.consumers(node) if c.id in region]
        if len(cons) != 1:
            return None
        prev, node = node, cons[0]
        if node.kind != "op":
            return None
        k = node.op.kind
        if k == "groupby":
            if groupby is not None or maps or union is not None:
                return None  # at most one, directly after the join
            groupby = node
        elif k == "map":
            if not node.op.linear or union is not None:
                return None
            maps.append(node)
        elif k == "union":
            if union is not None:
                return None
            # every other Union input must be region-external (quiet
            # during the loop)
            for inp in node.inputs:
                if inp is not prev and inp.id in region:
                    return None
            union = node
        elif k == "reduce":
            red = node
        else:
            return None

    if red.op.how != "sum" or loop.back_input is not red:
        return None
    # the Reduce must be the region's only boundary node (telescoping)
    if any(b is not red for b in structure.boundary):
        return None
    # every region node must be on the recognized chain
    chain_ids = {loop.id, join.id, red.id}
    chain_ids.update(m.id for m in maps)
    if groupby is not None:
        chain_ids.add(groupby.id)
    if union is not None:
        chain_ids.add(union.id)
    if set(region) != chain_ids:
        return None
    # the loop variable and the Reduce emission are the same collection
    if (loop.spec.key_space != red.spec.key_space
            or tuple(loop.spec.value_shape) != tuple(red.spec.value_shape)):
        return None
    return LinearStructure(loop=loop, join=join, groupby=groupby,
                           maps=tuple(maps), union=union, reduce=red)


def _rowfn(fn: Callable, vectorized: bool) -> Callable:
    if vectorized:
        return fn
    return jax.vmap(fn)


def _edge_budget_tiers(arena_capacity: int) -> List[int]:
    """Static gather budgets, large to small; the dense full-arena branch
    sits above the largest. Measured regime (v5e, 1.31M-row arena): the
    contribution scatter (~74M rows/s) dominates both branches and scales
    with the branch's row count, and the budget pass's frontier-table
    gather-expand costs ~22ns/row of HBM traffic — a budget pass runs at
    ~40ns/row total vs the dense sweep's ~17.5ns/row over the FULL arena.
    Crossover is therefore near arena/2, where a budget pass only ties
    the dense sweep (measured: 25ms vs 23ms) — so the ladder starts at
    arena/4 (clear win, ~11ms) and steps by ratio 2, bounding wasted
    gather slots to 2x the live frontier. Six tiers keep the lax.switch
    small; frontiers below the floor ride the smallest tier cheaply."""
    tiers = []
    c = 1 << (max(arena_capacity // 4, 1).bit_length() - 1)
    while c >= 2048 and len(tiers) < 6:
        tiers.append(c)
        c //= 2
    return tiers


class LinearFixpointProgram(_MacroTickMixin):
    """One compiled tick for a linear loop region: row-based phase A +
    fused delta-vector while_loop + row-based exit pass.

    Drop-in alternative to ``FixpointProgram`` (same call contract);
    built by the executor when :func:`analyze_linear` matches. Raises
    ValueError when shapes don't fit the fused path's representation
    (caller falls back to the row program).
    """

    def __init__(self, executor, plan: Sequence[Node],
                 ingress_caps: Dict[int, int], max_iters: int, *,
                 structure: FixpointStructure,
                 linear: LinearStructure):
        graph = executor.graph
        self.structure = structure
        self.linear = linear
        self.max_iters = max_iters
        self.sink_ids = [s.id for s in graph.sinks]

        L, J, R = linear.loop, linear.join, linear.reduce
        if (L.spec.key_space >= _F32_EXACT
                or J.op.arena_capacity >= _F32_EXACT
                or R.inputs[0].spec.key_space >= _F32_EXACT):
            raise ValueError("key space / arena too large for fused-f32 "
                             "index columns")
        for what, dt in (("arena value", J.inputs[1].spec.value_dtype),
                         ("join output value", J.spec.value_dtype),
                         ("loop value", L.spec.value_dtype),
                         ("reduce value", R.spec.value_dtype)):
            if not _f32_roundtrip_safe(dt):
                raise ValueError(
                    f"{what} dtype {jnp.dtype(dt).name} does not round-trip "
                    f"exactly through the fused loop's float32 columns; "
                    f"using the row-based fixpoint")

        full_pass = executor.build_pass_fn(list(plan))
        exit_pass = (executor.build_pass_fn(list(structure.exit_plan))
                     if structure.exit_plan else None)

        gb = linear.groupby
        K = L.spec.key_space                   # loop/left key space
        KR = R.inputs[0].spec.key_space        # reduce key space
        odtype = J.spec.value_dtype
        rdtype = R.spec.value_dtype
        vdtype = J.inputs[1].spec.value_dtype  # arena value dtype
        tol = R.op.tol
        loop_vshape = tuple(L.spec.value_shape)
        P = 1
        for s in loop_vshape:
            P *= s
        arena_vshape = tuple(J.inputs[1].spec.value_shape)
        Q = 1
        for s in arena_vshape:
            Q *= s
        mi = max_iters
        # shard context: under a ShardedTpuExecutor the whole loop runs
        # inside ONE shard_map region — per-shard CSR over the local arena
        # slice (arena keys are shard-local by construction of the routed
        # Join), a GLOBAL-domain contribution scatter combined with one
        # psum_scatter per pass onto the owned key slice, and globally
        # uniform tier selection so the collectives inside lax.switch
        # branches can never diverge across devices (VERDICT r2 item 5)
        mesh = getattr(executor, "mesh", None)
        axis = getattr(executor, "axis", None) if mesh is not None else None
        nsh = executor.n if axis is not None else 1
        if K % nsh or J.op.arena_capacity % nsh:
            raise ValueError("key space / arena not divisible by mesh size")
        tiers = _edge_budget_tiers(J.op.arena_capacity // nsh)
        merge = J.op.merge
        key_fn = _rowfn(gb.op.key_fn, gb.op.vectorized) if gb else None
        value_fn = (_rowfn(gb.op.value_fn, gb.op.vectorized)
                    if gb is not None and gb.op.value_fn is not None else None)
        map_fns = [_rowfn(m.op.fn, m.op.vectorized) for m in linear.maps]
        boundary = structure.boundary
        loop_id, join_id, red_id = L.id, J.id, R.id

        def push(src_keys, x, dwx, vb, ew):
            """Per-edge contributions of the frontier push.

            src_keys [E'] global join keys; x [E', *loop_vshape] per-key
            dval gathered per edge; dwx [E'] per-key net weight; vb
            [E', *arena_vshape] arena values; ew [E'] arena row weights
            (0 = dead or out-of-budget). -> (okey, wsum_c, wcnt_c).
            """
            merged = jnp.asarray(merge(src_keys, x, vb), odtype)
            if key_fn is not None:
                okey = jnp.asarray(key_fn(src_keys, merged), jnp.int32)
            else:
                okey = src_keys
            okey = jnp.where(ew == 0, 0, okey)
            val = merged
            if value_fn is not None:
                val = value_fn(src_keys, merged)
            for fn in map_fns:
                val = fn(val)
            wv = _masked_contrib(ew, jnp.asarray(val, jnp.float32))
            return okey, wv, (dwx * ew).astype(jnp.float32)

        def apply_contribs(rstate, okey, wv, wc):
            """One fused scatter-add into the Reduce's running tables,
            then the dense emission diff (exactly _lower_reduce's dense
            mode, expressed on the vectors). Returns the next carry.

            Sharded: the scatter table covers the GLOBAL key domain (okey
            is a global dst id) and one tiled psum_scatter per pass both
            sums cross-shard contributions and hands each shard its owned
            slice — the fold, diff, and next observables are then local.
            """
            flat = wv.reshape(wv.shape[0], -1)
            upd = jnp.concatenate([flat, wc[:, None]], axis=-1)
            tab = jnp.zeros((KR, upd.shape[1]), jnp.float32
                            ).at[okey].add(upd, mode="drop")
            if axis is not None:
                tab = jax.lax.psum_scatter(tab, axis, scatter_dimension=0,
                                           tiled=True)
            Ko = tab.shape[0]              # owned key rows (KR / nsh)
            vshape = wv.shape[1:]
            wsum = rstate["wsum"] + tab[:, :-1].reshape((Ko,) + vshape)
            wcnt = rstate["wcnt"] + tab[:, -1].astype(jnp.int32)

            emitted, em_has = rstate["emitted"], rstate["emitted_has"]
            agg, exists = _agg_tables(R.op, wsum, wcnt, rdtype)
            changed = _differs(agg, emitted, tol)
            ins_m = exists & (~em_has | changed)
            ret_m = em_has & (~exists | changed)
            new_emitted = jnp.where(_bcast_w(ins_m, agg), agg, emitted)
            new_has = jnp.where(ins_m, True,
                                jnp.where(ret_m & ~exists, False, em_has))
            # next-pass linear observables of the emission delta:
            # rows are (emitted_old, -1)[ret] + (agg, +1)[ins]
            dval = (jnp.where(_bcast_w(ins_m, agg), agg.astype(jnp.float32),
                              0.0)
                    - jnp.where(_bcast_w(ret_m, emitted),
                                emitted.astype(jnp.float32), 0.0))
            dwv = (ins_m.astype(jnp.float32) - ret_m.astype(jnp.float32))
            xw = jnp.concatenate([dval.reshape(Ko, P), dwv[:, None]], axis=1)
            rows = jnp.sum(ins_m.astype(jnp.int32) + ret_m.astype(jnp.int32))
            if axis is not None:
                rows = jax.lax.psum(rows, axis)
            new_rstate = dict(rstate)
            new_rstate.update(wsum=wsum, wcnt=wcnt, emitted=new_emitted,
                              emitted_has=new_has)
            return new_rstate, xw, rows

        def budget_body(EB, rstate, csr, xw, base):
            """Frontier-compacted push at static gather budget EB.

            One gather builds the compacted frontier table, a
            scatter-of-starts + cumsum assigns arena slots to frontier
            segments, one gather expands the frontier table per slot, one
            gather fetches arena rows, one scatter applies contributions.
            All indices are LOCAL to this shard's key slice; ``base``
            rebases them to global ids for merge/key_fn.
            """
            geo, svalw = csr                   # [Kl,2] f32, [Rl, Q+1] f32
            Klc = geo.shape[0]
            deg = geo[:, 1]
            mask = jnp.any(xw != 0, axis=1) & (deg > 0)
            # compact frontier keys; count <= frontier edge count <= EB
            # because every compacted key has deg >= 1
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            tgt = jnp.where(mask, pos, EB)
            ids = jnp.full((EB,), Klc, jnp.int32).at[tgt].set(
                jnp.arange(Klc, dtype=jnp.int32), mode="drop")
            ids_c = jnp.minimum(ids, Klc - 1)
            # one fused gather: offsets, deg, key, observables per frontier
            ftab = jnp.concatenate(
                [geo, jnp.arange(Klc, dtype=jnp.float32)[:, None], xw],
                axis=1)
            fr = ftab[ids_c]                   # [EB, 3 + P + 1]
            fdeg = jnp.where(ids < Klc, fr[:, 1], 0.0)
            cum = jnp.cumsum(fdeg)
            total = cum[-1]
            start = cum - fdeg
            # slot j belongs to the frontier entry whose segment starts at
            # or before j: scatter segment starts, running-sum them
            spos = jnp.where(fdeg > 0, start.astype(jnp.int32), EB)
            marks = jnp.zeros((EB,), jnp.int32).at[spos].add(1, mode="drop")
            owner = jnp.cumsum(marks) - 1
            owner = jnp.clip(owner, 0, EB - 1)
            # expand the frontier table per slot (one gather), with the
            # segment start appended so each slot finds its arena row
            frs = jnp.concatenate([fr, start[:, None]], axis=1)[owner]
            j = jnp.arange(EB, dtype=jnp.float32)
            valid = (j < total) & (frs[:, 1] > 0)
            eidx = (frs[:, 0] + (j - frs[:, -1])).astype(jnp.int32)
            eidx = jnp.where(valid, eidx, 0)
            src = frs[:, 2].astype(jnp.int32)
            src = jnp.clip(src, 0, Klc - 1)
            x = frs[:, 3:3 + P].reshape((EB,) + loop_vshape)
            dwx = frs[:, 3 + P]
            sv = svalw[eidx]                   # [EB, Q+1]
            vb = jnp.asarray(sv[:, :Q], vdtype).reshape((EB,) + arena_vshape)
            ew = jnp.where(valid, sv[:, Q].astype(jnp.int32), 0)
            okey, wv, wc = push(src + base, jnp.asarray(x, jnp.float32),
                                dwx, vb, ew)
            return apply_contribs(rstate, okey, wv, wc)

        def dense_body(rstate, arena, xw, base):
            """Full-arena push — the always-correct top tier."""
            rk, rv, rw = arena
            g = xw[rk]                          # [Rl, P+1] one gather
            x = g[:, :P].reshape((rk.shape[0],) + loop_vshape)
            okey, wv, wc = push(rk + base, x, g[:, P], rv, rw)
            return apply_contribs(rstate, okey, wv, wc)

        def loop_region(jstate, rstate, ld, has_entry):
            """Phase B on one shard's slices (the whole mesh's arrays when
            single-device): observables from the loop delta, per-slice CSR,
            the while_loop, and the Join left-table patch. ``ld`` rows are
            owner-aligned by construction (loop deltas are always Reduce
            emissions, which each shard emits over its owned key range)."""
            Klc = rstate["emitted_has"].shape[0]   # local loop/key rows
            if axis is not None:
                base = (jax.lax.axis_index(axis) * Klc).astype(jnp.int32)
            else:
                base = jnp.zeros((), jnp.int32)

            # loop delta rows -> dense linear observables (local keys)
            dval = jnp.zeros((Klc,) + loop_vshape, jnp.float32)
            dw = jnp.zeros((Klc,), jnp.int32)
            lk = ld.keys - base
            contrib = _masked_contrib(ld.weights, ld.values.astype(jnp.float32))
            dval = dval.at[lk].add(contrib, mode="drop")
            dw = dw.at[lk].add(ld.weights, mode="drop")
            xw = jnp.concatenate(
                [dval.reshape(Klc, P), dw.astype(jnp.float32)[:, None]],
                axis=1)

            # per-tick CSR over the live arena slice (static in the loop;
            # arena keys are local under sharding — see join routing).
            # Rebuilt from scratch each tick (~25-30ms device at 1.31M
            # rows, argsort-dominated — tools/profile_tick.py)
            # deliberately: maintaining it incrementally would either
            # rewrite the full sorted table per tick (same cost as the
            # rebuild) or carry a fresh-rows tail swept densely by every
            # pass, which at 1% churn x ~12 passes costs what the rebuild
            # does — measured wash, so the simple form stays
            rk, rv, rw = jstate["rkeys"], jstate["rvals"], jstate["rw"]
            Rcap = rk.shape[0]
            skey = jnp.where(rw != 0, rk, Klc)
            order = jnp.argsort(skey)
            svalw = jnp.concatenate(
                [rv[order].reshape(Rcap, Q).astype(jnp.float32),
                 rw[order].astype(jnp.float32)[:, None]], axis=1)
            # segment starts by scatter-count + exclusive cumsum, not
            # searchsorted over the sorted keys: identical bounds (the
            # sort groups equal keys contiguously, so start(k) = #keys<k)
            # at a third of the cost (profiled 34ms -> 12ms at a 1.31M
            # arena — tools/profile_tick.py)
            deg_i = jnp.zeros((Klc + 1,), jnp.int32).at[skey].add(
                1, mode="drop")[:Klc]
            starts = jnp.cumsum(deg_i) - deg_i
            geo = jnp.stack([starts, deg_i], axis=1).astype(jnp.float32)
            csr = (geo, svalw)
            arena = (jnp.minimum(rk, Klc - 1), rv, rw)

            branches = [
                (lambda c, EB=EB: budget_body(EB, c[0], csr, c[1], base))
                for EB in tiers
            ]
            branches.append(lambda c: dense_body(c[0], arena, c[1], base))
            dense_ix = len(tiers)

            def live(xw):
                l = jnp.any(xw != 0)
                if axis is not None:
                    # globally uniform predicate: every shard must agree
                    # on the trip count (collectives inside the body)
                    l = jax.lax.psum(l.astype(jnp.int32), axis) > 0
                return l

            def cond(c):
                rst, xw, it, rows = c
                return jnp.logical_and(it < mi, live(xw))

            def body(c):
                rst, xw, it, rows = c
                if tiers:
                    fmask = jnp.any(xw != 0, axis=1) & (deg_i > 0)
                    nedges = jnp.sum(jnp.where(fmask, deg_i, 0))
                    if axis is not None:
                        # uniform tier: the worst shard picks for everyone,
                        # so lax.switch branches (which contain psum_scatter)
                        # never diverge across devices
                        nedges = jax.lax.pmax(nedges, axis)
                    # descending budgets; pick the smallest that fits.
                    # Scalar compares over the static tier list — never a
                    # materialized s32[k] literal: the remote-device runtime
                    # drops into a degraded dispatch mode (~88ms/dispatch,
                    # process-wide, permanent) after executing any program
                    # whose HLO carries a multi-element constant.
                    n_fits = sum(((jnp.int32(t) >= nedges).astype(jnp.int32)
                                  for t in tiers), jnp.zeros((), jnp.int32))
                    ix = jnp.where(n_fits > 0, n_fits - 1, dense_ix)
                    rst2, xw2, prows = jax.lax.switch(ix, branches, (rst, xw))
                else:
                    rst2, xw2, prows = dense_body(rst, arena, xw, base)
                return rst2, xw2, it + 1, rows + prows

            rstate, xw, iters, rows = jax.lax.while_loop(
                cond, body, (rstate, xw, jnp.zeros((), jnp.int32),
                             jnp.zeros((), jnp.int32)))
            converged = ~live(xw)

            # patch the Join's left table densely (per-pass retract/insert
            # pairs cancel; only entry-vs-exit existence and value matter)
            has_f = rstate["emitted_has"]
            em_f = rstate["emitted"]
            new_jstate = dict(jstate)
            new_jstate["lval"] = jnp.where(
                _bcast_w(has_f, em_f),
                jnp.asarray(em_f, jstate["lval"].dtype), jstate["lval"])
            new_jstate["lw"] = (jstate["lw"] + has_f.astype(jnp.int32)
                                - has_entry.astype(jnp.int32))
            return new_jstate, rstate, iters, rows, converged

        def run_loop(jstate, rstate, ld, has_entry):
            if axis is None:
                return loop_region(jstate, rstate, ld, has_entry)
            from jax.sharding import PartitionSpec as PS

            jspec = executor._state_tree_specs({join_id: jstate})[join_id]
            rspec = executor._state_tree_specs({red_id: rstate})[red_id]
            dspec = DeviceDelta(PS(axis), PS(axis), PS(axis))
            fn = jax.shard_map(
                loop_region, mesh=mesh,
                in_specs=(jspec, rspec, dspec, PS(axis)),
                out_specs=(jspec, rspec, PS(), PS(), PS()),
                check_vma=False)
            return fn(jstate, rstate, ld, has_entry)

        def tick_fn(op_states, ingress):
            # the loop folds every emission from phase A's onward into the
            # join's left table, so the exit patch diffs existence against
            # the PRE-tick table, not the post-phase-A one
            has_entry = op_states[red_id]["emitted_has"]
            states, eg_a = full_pass(op_states, ingress)
            snaps = {n.id: (states[n.id]["emitted"],
                            states[n.id]["emitted_has"]) for n in boundary}

            if loop_id in eg_a:
                new_jstate, rstate, iters, rows, converged = run_loop(
                    states[join_id], states[red_id], eg_a[loop_id],
                    has_entry)
                states = dict(states)
                states[join_id] = new_jstate
                states[red_id] = rstate
            else:
                # phase A emitted no loop delta: the region is already
                # quiescent and the left-table patch would be an identity
                iters = jnp.zeros((), jnp.int32)
                rows = jnp.zeros((), jnp.int32)
                converged = jnp.ones((), jnp.bool_)

            eg_b = {}
            if exit_pass is not None:
                diffs = {n.id: _emitted_diff(snaps[n.id], states[n.id], n)
                         for n in boundary}
                states, eg_b = exit_pass(states, diffs)

            sink_egress = {}
            for sid in self.sink_ids:
                batches = []
                if sid in eg_a:
                    batches.append(eg_a[sid])
                if sid in eg_b:
                    batches.append(eg_b[sid])
                if batches:
                    sink_egress[sid] = tuple(batches)
            return states, sink_egress, iters, rows, converged

        # donate the state pytree: the arena and dense tables update in
        # place instead of being copied every tick
        self.tick_fn = tick_fn
        self._fn = jax.jit(tick_fn, donate_argnums=0)

    def __call__(self, op_states, dev_ingress):
        """-> (states', {sink_id: (DeviceDelta, ...)}, iters, loop_rows,
        converged) — the FixpointProgram call contract."""
        return self._fn(op_states, dev_ingress)
