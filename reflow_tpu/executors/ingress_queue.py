"""Device-resident ingress queue for compiled mega-ticks.

One K-tick commit window is one device execution
(``TpuExecutor.run_window``): the scan body consumes one queue *slot*
— a ``(tick, source)`` cell of a preallocated, statically-shaped delta
buffer — per tick per source. The queue replaces the host-side
``_stack_feeds`` restack (allocate + copy + upload [K, C] arrays every
window) with index-updates into persistent device buffers:

- buffers are allocated ONCE per (plan, capacity, K) signature and
  reused window after window (they live in the executor's program
  cache, invalidated with it on rebind);
- each host micro-batch is padded to its source's capacity bucket and
  written into its slot with a jitted ``.at[t].set`` (the slot index is
  a traced scalar, so writes never recompile);
- an empty slot (window padding — a tick where this source had no
  deltas) is overwritten from a cached device-resident zero image: no
  host transfer at all, and no stale rows from the previous window can
  leak (every slot is written every window);
- capacity is negotiated with the arena up front: the caller validates
  the per-source caps through the same static propagation the per-tick
  path uses (``arena.propagate_plan_caps``) BEFORE any device memory is
  reserved.

The buffers ARE donated to the window program (alongside the state
pytree): the program hands back a fresh zeroed stack in (potentially)
the same device memory, and the caller hands it back via the retire
step (:meth:`DeviceIngressQueue.retire`), so the window no longer
holds an extra live copy of every source buffer across the dispatch.

**Generation rotation (pipelined windows).** The buffers come in
*generations* — independent full buffer sets. ``write`` targets the
current *staging* generation; :meth:`seal` hands that generation to a
dispatch (its buffers now belong to the in-flight window program via
donation) and the next ``write`` rotates onto a free generation, so
window N+1's slot writes NEVER touch a buffer set an in-flight program
owns. :meth:`retire` re-adopts the program's returned zeroed stack
into the sealed generation and frees it for reuse. Generations are
allocated lazily: a depth-1 caller (seal → dispatch → retire → seal)
ping-pongs on generation 0 forever and pays for exactly one buffer
set, same as before pipelining; a depth-D pump allocates at most D
sets. The pump bounds the in-flight depth — the queue just rotates.

``placement`` pins the buffers: a ``jax.Device`` commits them (and the
zero images, and therefore every slot write and the window program
itself) to that device — the serve tier's tenant-placement path — and a
``(mesh, axis)`` pair gives them a ``NamedSharding`` along the delta
(capacity) axis, so slot writes and padding land shard-local and the
window program runs SPMD over the mesh (the sharded hot-tenant path).
Bucketed capacities are powers of two >= MIN_CAPACITY >= the mesh size,
so the capacity axis always divides.

``slot_nbytes`` is the admission-side view of the same reservation: the
device bytes one host batch will occupy in its queue slot, used by the
serve frontend to key the ``AdmissionBudget`` on device memory pressure
instead of host payload bytes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax

from reflow_tpu.executors.device_delta import (DeviceDelta, bucket_capacity,
                                               check_weight_mass)
from reflow_tpu.utils.faults import DeliveryError

__all__ = ["DeviceIngressQueue", "slot_nbytes"]

_I32 = np.iinfo(np.int32)


def slot_nbytes(spec, rows: int) -> int:
    """Device bytes a host batch of ``rows`` reserves in its queue slot:
    the capacity bucket times the per-row footprint (int32 key + int32
    weight + the value payload). This is what admission should charge
    when backpressure tracks device memory, not host payload size."""
    cap = bucket_capacity(int(rows))
    per_val = int(np.prod(spec.value_shape)) if spec.value_shape else 1
    return cap * (4 + 4 + per_val * np.dtype(spec.value_dtype).itemsize)


def _write_slot(bufs: DeviceDelta, t, keys, values, weights) -> DeviceDelta:
    # t is traced (dynamic_update_slice), so one compilation covers every
    # slot of a buffer shape; donated bufs make the update in place
    return DeviceDelta(bufs.keys.at[t].set(keys),
                       bufs.values.at[t].set(values),
                       bufs.weights.at[t].set(weights))


# one writer for every queue: jax caches the compiled update per
# (shape, dtype, sharding), so same-shaped queues across graphs (and
# devices) share the compilation instead of re-jitting per queue
_WRITER = jax.jit(_write_slot, donate_argnums=0)

#: does this backend COPY host numpy arguments when they enter a
#: computation? jaxlib's CPU client can zero-copy aligned host buffers
#: in some versions, in which case a reused scratch array would alias
#: live device data and mutating it between slot writes would corrupt
#: an in-flight window. Probed once, lazily.
_SCRATCH_REUSE_SAFE: Optional[bool] = None


def _scratch_reuse_safe() -> bool:
    global _SCRATCH_REUSE_SAFE
    if _SCRATCH_REUSE_SAFE is None:
        import jax.numpy as jnp

        probe = np.arange(32, dtype=np.int32)
        dev = jnp.asarray(probe)
        probe[:] = -1
        dev.block_until_ready()
        _SCRATCH_REUSE_SAFE = not bool((np.asarray(dev) < 0).any())
    return _SCRATCH_REUSE_SAFE


class DeviceIngressQueue:
    """Per-source [K, cap] delta buffers plus their jitted slot writer.

    ``specs``/``caps`` map source node ids to their Spec and padded
    per-tick row capacity; ``k`` is the window length in ticks.
    ``placement`` is None (default device), a ``jax.Device`` (commit the
    buffers — and every dispatch over them — to that device), or a
    ``(mesh, axis)`` pair (NamedSharding the capacity axis over the
    mesh's ``axis``).
    """

    def __init__(self, specs: Dict[int, object], caps: Dict[int, int],
                 k: int, placement=None):
        import jax.numpy as jnp

        self.k = int(k)
        self.caps = dict(caps)
        self._specs = dict(specs)
        self.placement = placement
        self.writes = 0
        self.zero_writes = 0
        self.generations = 0
        self.nbytes = 0
        self.gen_nbytes = sum(k * slot_nbytes(specs[nid], cap)
                              for nid, cap in caps.items())
        self._zero: Dict[int, tuple] = {}
        for nid, cap in sorted(caps.items()):
            spec = specs[nid]
            vshape = tuple(spec.value_shape)
            # the padding image: device-resident so an empty slot's write
            # is a pure on-device index-update (zero host bytes moved);
            # shared read-only across generations
            self._zero[nid] = (
                self._put(jnp.zeros((cap,), jnp.int32), stacked=False),
                self._put(jnp.zeros((cap,) + vshape, spec.value_dtype),
                          stacked=False),
                self._put(jnp.zeros((cap,), jnp.int32), stacked=False))
        #: generation -> {nid: DeviceDelta}; _staging is the generation
        #: writes land in, _inflight the sealed (donated, program-owned)
        #: ones in dispatch order, _free the reusable ones (LIFO so the
        #: depth-1 flow ping-pongs on generation 0)
        self._gens: List[Dict[int, DeviceDelta]] = []
        self._free: List[int] = []
        self._inflight: List[int] = []
        self._staging: Optional[int] = None
        self._alloc_gen()  # generation 0, eagerly — same memory as before
        #: host-side padded staging arrays, one set per source, reused
        #: across every slot write (kills the three-np.zeros-per-slot
        #: churn); only when the backend copies host args at dispatch
        self._scratch: Dict[int, tuple] = {}
        self._scratch_rows: Dict[int, int] = {}
        self._writer = _WRITER

    def _alloc_gen(self) -> int:
        import jax.numpy as jnp

        bufs: Dict[int, DeviceDelta] = {}
        for nid, cap in sorted(self.caps.items()):
            spec = self._specs[nid]
            vshape = tuple(spec.value_shape)
            bufs[nid] = DeviceDelta(
                self._put(jnp.zeros((self.k, cap), jnp.int32), stacked=True),
                self._put(jnp.zeros((self.k, cap) + vshape, spec.value_dtype),
                          stacked=True),
                self._put(jnp.zeros((self.k, cap), jnp.int32), stacked=True))
        gen = len(self._gens)
        self._gens.append(bufs)
        self._free.append(gen)
        self.generations += 1
        self.nbytes += self.gen_nbytes
        return gen

    def _put(self, x, *, stacked: bool):
        """Apply the queue's placement to one freshly-allocated buffer:
        commit to the pinned device, or shard the capacity axis (dim 1 of
        a [K, cap, ...] stack, dim 0 of a [cap, ...] zero image) over the
        mesh. None = wherever jax's default device is."""
        if self.placement is None:
            return x
        if isinstance(self.placement, tuple):
            from jax.sharding import NamedSharding, PartitionSpec

            mesh, axis = self.placement
            dims = ((None, axis) if stacked else (axis,))
            dims = dims + (None,) * (x.ndim - len(dims))
            return jax.device_put(x, NamedSharding(mesh,
                                                   PartitionSpec(*dims)))
        return jax.device_put(x, self.placement)

    # -- generation rotation -----------------------------------------------

    @property
    def in_flight(self) -> int:
        """Sealed generations currently owned by dispatched programs."""
        return len(self._inflight)

    def _ensure_staging(self) -> int:
        if self._staging is None:
            if not self._free:
                self._alloc_gen()
            self._staging = self._free.pop()
        return self._staging

    def seal(self) -> int:
        """Hand the staging generation to a dispatch: its buffers now
        belong to the window program (donation) and the next ``write``
        rotates onto a free generation. Returns the generation id the
        caller must :meth:`retire` (or :meth:`cancel`) later."""
        gen = self._ensure_staging()
        self._staging = None
        self._inflight.append(gen)
        return gen

    def retire(self, gen: int, stacked: Dict[int, DeviceDelta]) -> None:
        """Adopt the window program's returned (zeroed, donated-memory)
        stack back into generation ``gen`` and free it for restaging.
        The stack the program consumed was DONATED — the old buffer
        handles are dead — so the caller must hand the pass-through
        output back here before the generation is written again."""
        if gen not in self._inflight:
            raise ValueError(f"generation {gen} is not in flight")
        if sorted(stacked) != sorted(self.caps):
            raise ValueError(
                f"retire stack keys {sorted(stacked)} != queue sources "
                f"{sorted(self.caps)}")
        # re-assert the queue's placement on the adopted buffers: the
        # compiler picks the window program's output sharding freely, so
        # a sharded stack can come back replicated — a no-op when the
        # sharding already matches, a one-time reshard when it doesn't
        # (without it, every later slot write loses shard-locality).
        if self.placement is not None:
            stacked = {nid: jax.tree.map(
                lambda x: self._put(x, stacked=True), dd)
                for nid, dd in stacked.items()}
        self._gens[gen] = dict(stacked)
        self._inflight.remove(gen)
        self._free.append(gen)

    def cancel(self, gen: int) -> None:
        """Un-seal a generation whose dispatch never happened. Its
        buffers are still live (nothing was donated), so it goes
        straight back to the free list — every slot is rewritten every
        window, so stale rows can't leak."""
        if gen in self._inflight:
            self._inflight.remove(gen)
            self._free.append(gen)

    def rebind(self, stacked: Dict[int, DeviceDelta]) -> None:
        """Legacy single-generation surface: retire the OLDEST in-flight
        generation (the depth-1 flow seals exactly one at a time)."""
        if not self._inflight:
            raise ValueError("rebind with no sealed generation in flight")
        self.retire(self._inflight[0], stacked)

    # -- slot writes --------------------------------------------------------

    def write(self, t: int, nid: int, batch) -> None:
        """Fill slot ``(t, nid)`` of the staging generation from a host
        batch (zero-row batches write the cached zero image). Every slot
        must be written every window — the buffers persist, so a skipped
        slot would replay a previous window's rows."""
        cap = self.caps[nid]
        n = len(batch)
        if n > cap:
            raise ValueError(
                f"batch of {n} rows exceeds queue slot capacity {cap} "
                f"for node {nid}")
        gen = self._ensure_staging()
        bufs = self._gens[gen]
        if n == 0:
            keys, values, weights = self._zero[nid]
            self.zero_writes += 1
        else:
            check_weight_mass(batch)   # same host-boundary guard as upload
            bkeys = np.asarray(batch.keys)
            if bkeys.size and (int(bkeys.max()) > _I32.max
                               or int(bkeys.min()) < _I32.min):
                # the slot buffers are int32: assigning int64 keys would
                # silently wrap anything >= 2^31 — refuse at the host
                # boundary instead of folding a corrupted key
                raise DeliveryError(
                    f"node {nid}: batch keys exceed the int32 ingress "
                    f"key range [{_I32.min}, {_I32.max}] "
                    f"(max {int(bkeys.max())}, min {int(bkeys.min())})")
            keys, values, weights = self._pad_host(nid, n, cap, bkeys, batch)
        bufs[nid] = self._writer(bufs[nid], t, keys, values, weights)
        self.writes += 1

    def _pad_host(self, nid: int, n: int, cap: int, bkeys, batch):
        """Capacity-padded host images of one batch's columns. Reuses a
        per-source preallocated scratch set (zeroing only the tail the
        previous fill dirtied) when the backend copies host args at
        dispatch; falls back to fresh allocations on an aliasing
        backend, where a reused array could be mutated under an
        in-flight transfer."""
        spec = self._specs[nid]
        vshape = tuple(spec.value_shape)
        if _scratch_reuse_safe():
            sc = self._scratch.get(nid)
            if sc is None:
                sc = self._scratch[nid] = (
                    np.zeros(cap, np.int32),
                    np.zeros((cap,) + vshape, spec.value_dtype),
                    np.zeros(cap, np.int32))
                self._scratch_rows[nid] = 0
            keys, values, weights = sc
            prev = self._scratch_rows[nid]
            if prev > n:
                keys[n:prev] = 0
                values[n:prev] = 0
                weights[n:prev] = 0
            self._scratch_rows[nid] = n
        else:
            keys = np.zeros(cap, np.int32)
            values = np.zeros((cap,) + vshape, spec.value_dtype)
            weights = np.zeros(cap, np.int32)
        keys[:n] = bkeys
        weights[:n] = batch.weights
        values[:n] = np.asarray(batch.values).reshape((n,) + vshape)
        return keys, values, weights

    def stacked(self) -> Dict[int, DeviceDelta]:
        """The staging generation's contents as the [K, cap] ingress
        stack the window program scans — same pytree shape
        ``_stack_feeds`` produces, so the compiled programs are shared
        between paths."""
        return dict(self._gens[self._ensure_staging()])
