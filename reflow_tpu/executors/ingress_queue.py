"""Device-resident ingress queue for compiled mega-ticks.

One K-tick commit window is one device execution
(``TpuExecutor.run_window``): the scan body consumes one queue *slot*
— a ``(tick, source)`` cell of a preallocated, statically-shaped delta
buffer — per tick per source. The queue replaces the host-side
``_stack_feeds`` restack (allocate + copy + upload [K, C] arrays every
window) with index-updates into persistent device buffers:

- buffers are allocated ONCE per (plan, capacity, K) signature and
  reused window after window (they live in the executor's program
  cache, invalidated with it on rebind);
- each host micro-batch is padded to its source's capacity bucket and
  written into its slot with a jitted ``.at[t].set`` (the slot index is
  a traced scalar, so writes never recompile);
- an empty slot (window padding — a tick where this source had no
  deltas) is overwritten from a cached device-resident zero image: no
  host transfer at all, and no stale rows from the previous window can
  leak (every slot is written every window);
- capacity is negotiated with the arena up front: the caller validates
  the per-source caps through the same static propagation the per-tick
  path uses (``arena.propagate_plan_caps``) BEFORE any device memory is
  reserved.

The buffers ARE donated to the window program (alongside the state
pytree): the program hands back a fresh zeroed stack in (potentially)
the same device memory, and the caller re-binds it into the queue
(:meth:`DeviceIngressQueue.rebind`), so the window no longer holds an
extra live copy of every source buffer across the dispatch.

``placement`` pins the buffers: a ``jax.Device`` commits them (and the
zero images, and therefore every slot write and the window program
itself) to that device — the serve tier's tenant-placement path — and a
``(mesh, axis)`` pair gives them a ``NamedSharding`` along the delta
(capacity) axis, so slot writes and padding land shard-local and the
window program runs SPMD over the mesh (the sharded hot-tenant path).
Bucketed capacities are powers of two >= MIN_CAPACITY >= the mesh size,
so the capacity axis always divides.

``slot_nbytes`` is the admission-side view of the same reservation: the
device bytes one host batch will occupy in its queue slot, used by the
serve frontend to key the ``AdmissionBudget`` on device memory pressure
instead of host payload bytes.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax

from reflow_tpu.executors.device_delta import (DeviceDelta, bucket_capacity,
                                               check_weight_mass)

__all__ = ["DeviceIngressQueue", "slot_nbytes"]


def slot_nbytes(spec, rows: int) -> int:
    """Device bytes a host batch of ``rows`` reserves in its queue slot:
    the capacity bucket times the per-row footprint (int32 key + int32
    weight + the value payload). This is what admission should charge
    when backpressure tracks device memory, not host payload size."""
    cap = bucket_capacity(int(rows))
    per_val = int(np.prod(spec.value_shape)) if spec.value_shape else 1
    return cap * (4 + 4 + per_val * np.dtype(spec.value_dtype).itemsize)


def _write_slot(bufs: DeviceDelta, t, keys, values, weights) -> DeviceDelta:
    # t is traced (dynamic_update_slice), so one compilation covers every
    # slot of a buffer shape; donated bufs make the update in place
    return DeviceDelta(bufs.keys.at[t].set(keys),
                       bufs.values.at[t].set(values),
                       bufs.weights.at[t].set(weights))


# one writer for every queue: jax caches the compiled update per
# (shape, dtype, sharding), so same-shaped queues across graphs (and
# devices) share the compilation instead of re-jitting per queue
_WRITER = jax.jit(_write_slot, donate_argnums=0)


class DeviceIngressQueue:
    """Per-source [K, cap] delta buffers plus their jitted slot writer.

    ``specs``/``caps`` map source node ids to their Spec and padded
    per-tick row capacity; ``k`` is the window length in ticks.
    ``placement`` is None (default device), a ``jax.Device`` (commit the
    buffers — and every dispatch over them — to that device), or a
    ``(mesh, axis)`` pair (NamedSharding the capacity axis over the
    mesh's ``axis``).
    """

    def __init__(self, specs: Dict[int, object], caps: Dict[int, int],
                 k: int, placement=None):
        import jax.numpy as jnp

        self.k = int(k)
        self.caps = dict(caps)
        self._specs = dict(specs)
        self.placement = placement
        self._bufs: Dict[int, DeviceDelta] = {}
        self._zero: Dict[int, tuple] = {}
        self.writes = 0
        self.zero_writes = 0
        self.nbytes = 0
        for nid, cap in sorted(caps.items()):
            spec = specs[nid]
            vshape = tuple(spec.value_shape)
            self._bufs[nid] = DeviceDelta(
                self._put(jnp.zeros((k, cap), jnp.int32), stacked=True),
                self._put(jnp.zeros((k, cap) + vshape, spec.value_dtype),
                          stacked=True),
                self._put(jnp.zeros((k, cap), jnp.int32), stacked=True))
            # the padding image: device-resident so an empty slot's write
            # is a pure on-device index-update (zero host bytes moved)
            self._zero[nid] = (
                self._put(jnp.zeros((cap,), jnp.int32), stacked=False),
                self._put(jnp.zeros((cap,) + vshape, spec.value_dtype),
                          stacked=False),
                self._put(jnp.zeros((cap,), jnp.int32), stacked=False))
            self.nbytes += k * slot_nbytes(spec, cap)
        self._writer = _WRITER

    def _put(self, x, *, stacked: bool):
        """Apply the queue's placement to one freshly-allocated buffer:
        commit to the pinned device, or shard the capacity axis (dim 1 of
        a [K, cap, ...] stack, dim 0 of a [cap, ...] zero image) over the
        mesh. None = wherever jax's default device is."""
        if self.placement is None:
            return x
        if isinstance(self.placement, tuple):
            from jax.sharding import NamedSharding, PartitionSpec

            mesh, axis = self.placement
            dims = ((None, axis) if stacked else (axis,))
            dims = dims + (None,) * (x.ndim - len(dims))
            return jax.device_put(x, NamedSharding(mesh,
                                                   PartitionSpec(*dims)))
        return jax.device_put(x, self.placement)

    def write(self, t: int, nid: int, batch) -> None:
        """Fill slot ``(t, nid)`` from a host batch (zero-row batches
        write the cached zero image). Every slot must be written every
        window — the buffers persist, so a skipped slot would replay the
        previous window's rows."""
        cap = self.caps[nid]
        n = len(batch)
        if n > cap:
            raise ValueError(
                f"batch of {n} rows exceeds queue slot capacity {cap} "
                f"for node {nid}")
        if n == 0:
            keys, values, weights = self._zero[nid]
            self.zero_writes += 1
        else:
            check_weight_mass(batch)   # same host-boundary guard as upload
            spec = self._specs[nid]
            vshape = tuple(spec.value_shape)
            keys = np.zeros(cap, np.int32)
            keys[:n] = batch.keys.astype(np.int64)
            weights = np.zeros(cap, np.int32)
            weights[:n] = batch.weights
            values = np.zeros((cap,) + vshape, spec.value_dtype)
            values[:n] = np.asarray(batch.values).reshape((n,) + vshape)
        self._bufs[nid] = self._writer(self._bufs[nid], t, keys, values,
                                       weights)
        self.writes += 1

    def stacked(self) -> Dict[int, DeviceDelta]:
        """The queue's current contents as the [K, cap] ingress stack the
        window program scans — same pytree shape ``_stack_feeds``
        produces, so the compiled programs are shared between paths."""
        return dict(self._bufs)

    def rebind(self, stacked: Dict[int, DeviceDelta]) -> None:
        """Adopt the window program's returned (zeroed, donated-memory)
        stack as the queue's buffers. The stack the program consumed was
        DONATED — the old buffer handles are dead — so the caller must
        hand the pass-through output back here before the next write."""
        if sorted(stacked) != sorted(self._bufs):
            raise ValueError(
                f"rebind stack keys {sorted(stacked)} != queue sources "
                f"{sorted(self._bufs)}")
        # re-assert the queue's placement on the adopted buffers: the
        # compiler picks the window program's output sharding freely, so
        # a sharded stack can come back replicated — a no-op when the
        # sharding already matches, a one-time reshard when it doesn't
        # (without it, every later slot write loses shard-locality).
        if self.placement is not None:
            stacked = {nid: jax.tree.map(
                lambda x: self._put(x, stacked=True), dd)
                for nid, dd in stacked.items()}
        self._bufs = dict(stacked)
