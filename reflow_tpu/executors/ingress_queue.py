"""Device-resident ingress queue for compiled mega-ticks.

One K-tick commit window is one device execution
(``TpuExecutor.run_window``): the scan body consumes one queue *slot*
— a ``(tick, source)`` cell of a preallocated, statically-shaped delta
buffer — per tick per source. The queue replaces the host-side
``_stack_feeds`` restack (allocate + copy + upload [K, C] arrays every
window) with index-updates into persistent device buffers:

- buffers are allocated ONCE per (plan, capacity, K) signature and
  reused window after window (they live in the executor's program
  cache, invalidated with it on rebind);
- each host micro-batch is padded to its source's capacity bucket and
  written into its slot with a jitted ``.at[t].set`` (the slot index is
  a traced scalar, so writes never recompile);
- an empty slot (window padding — a tick where this source had no
  deltas) is overwritten from a cached device-resident zero image: no
  host transfer at all, and no stale rows from the previous window can
  leak (every slot is written every window);
- capacity is negotiated with the arena up front: the caller validates
  the per-source caps through the same static propagation the per-tick
  path uses (``arena.propagate_plan_caps``) BEFORE any device memory is
  reserved.

The buffers are deliberately NOT donated to the window program (only
the state pytree is), so they survive the dispatch and the next window
writes in place. Donating them (saving one aliasing copy per window) is
a known follow-up.

``slot_nbytes`` is the admission-side view of the same reservation: the
device bytes one host batch will occupy in its queue slot, used by the
serve frontend to key the ``AdmissionBudget`` on device memory pressure
instead of host payload bytes.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax

from reflow_tpu.executors.device_delta import (DeviceDelta, bucket_capacity,
                                               check_weight_mass)

__all__ = ["DeviceIngressQueue", "slot_nbytes"]


def slot_nbytes(spec, rows: int) -> int:
    """Device bytes a host batch of ``rows`` reserves in its queue slot:
    the capacity bucket times the per-row footprint (int32 key + int32
    weight + the value payload). This is what admission should charge
    when backpressure tracks device memory, not host payload size."""
    cap = bucket_capacity(int(rows))
    per_val = int(np.prod(spec.value_shape)) if spec.value_shape else 1
    return cap * (4 + 4 + per_val * np.dtype(spec.value_dtype).itemsize)


def _write_slot(bufs: DeviceDelta, t, keys, values, weights) -> DeviceDelta:
    # t is traced (dynamic_update_slice), so one compilation covers every
    # slot of a buffer shape; donated bufs make the update in place
    return DeviceDelta(bufs.keys.at[t].set(keys),
                       bufs.values.at[t].set(values),
                       bufs.weights.at[t].set(weights))


class DeviceIngressQueue:
    """Per-source [K, cap] delta buffers plus their jitted slot writer.

    ``specs``/``caps`` map source node ids to their Spec and padded
    per-tick row capacity; ``k`` is the window length in ticks.
    """

    def __init__(self, specs: Dict[int, object], caps: Dict[int, int],
                 k: int):
        import jax.numpy as jnp

        self.k = int(k)
        self.caps = dict(caps)
        self._specs = dict(specs)
        self._bufs: Dict[int, DeviceDelta] = {}
        self._zero: Dict[int, tuple] = {}
        self.writes = 0
        self.zero_writes = 0
        self.nbytes = 0
        for nid, cap in sorted(caps.items()):
            spec = specs[nid]
            vshape = tuple(spec.value_shape)
            self._bufs[nid] = DeviceDelta(
                jnp.zeros((k, cap), jnp.int32),
                jnp.zeros((k, cap) + vshape, spec.value_dtype),
                jnp.zeros((k, cap), jnp.int32))
            # the padding image: device-resident so an empty slot's write
            # is a pure on-device index-update (zero host bytes moved)
            self._zero[nid] = (jnp.zeros((cap,), jnp.int32),
                               jnp.zeros((cap,) + vshape, spec.value_dtype),
                               jnp.zeros((cap,), jnp.int32))
            self.nbytes += k * slot_nbytes(spec, cap)
        self._writer = jax.jit(_write_slot, donate_argnums=0)

    def write(self, t: int, nid: int, batch) -> None:
        """Fill slot ``(t, nid)`` from a host batch (zero-row batches
        write the cached zero image). Every slot must be written every
        window — the buffers persist, so a skipped slot would replay the
        previous window's rows."""
        cap = self.caps[nid]
        n = len(batch)
        if n > cap:
            raise ValueError(
                f"batch of {n} rows exceeds queue slot capacity {cap} "
                f"for node {nid}")
        if n == 0:
            keys, values, weights = self._zero[nid]
            self.zero_writes += 1
        else:
            check_weight_mass(batch)   # same host-boundary guard as upload
            spec = self._specs[nid]
            vshape = tuple(spec.value_shape)
            keys = np.zeros(cap, np.int32)
            keys[:n] = batch.keys.astype(np.int64)
            weights = np.zeros(cap, np.int32)
            weights[:n] = batch.weights
            values = np.zeros((cap,) + vshape, spec.value_dtype)
            values[:n] = np.asarray(batch.values).reshape((n,) + vshape)
        self._bufs[nid] = self._writer(self._bufs[nid], t, keys, values,
                                       weights)
        self.writes += 1

    def stacked(self) -> Dict[int, DeviceDelta]:
        """The queue's current contents as the [K, cap] ingress stack the
        window program scans — same pytree shape ``_stack_feeds``
        produces, so the compiled programs are shared between paths."""
        return dict(self._bufs)
