"""Join-arena compaction (GC): bound the arena by LIVE rows, not lifetime.

The device Join stores its right side as an append-only log: retractions
append negative-weight rows rather than freeing their match, so without
reclamation ``arena_capacity`` must cover the *lifetime* append count and
a long-running stream eventually dies on the overflow check (round-1
VERDICT item 7).

``compact_arena`` cancels matched pairs on device: rows are lex-sorted by
(key, value bytes), equal (key, value) runs are weight-summed, and groups
with net weight 0 vanish; survivors are repacked to the front with their
net weight. Exactness contract: a retraction carries the SAME value bytes
as the insert it cancels (true by construction for host-driven deltas —
the retract batch replays the original row with weight -1; float values
are compared bitwise, so NaNs and signed zeros cancel only their
bit-identical twins).

Compaction triggers IN-PROGRAM: ``join_core`` wraps this kernel in a
``lax.cond`` guarded by ``rcount + appends > capacity``, so the
high-water decision is data-dependent on device and never reads a value
back to the host (SURVEY.md §7 hard part d — streaming ticks stay
pipelined). A genuine overflow (live + appends > capacity even after
compaction) sets the join state's sticky ``error`` flag, raised at the
next sync point. Sharded executors reach this through the same path:
``join_core`` runs per shard under ``shard_map`` (rows never migrate;
each shard compacts its slice and its slot of ``rcount``).

``propagate_plan_caps`` is the host-side static counterpart: the
pre-dispatch capacity walk that rejects statically impossible ingress
sizes and sizes the mega-tick ingress queue against the arenas.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from reflow_tpu.graph import GraphError

__all__ = ["compact_arena", "propagate_plan_caps"]


def propagate_plan_caps(plan, ingress_caps: Dict[int, int],
                        divisor: int = 1) -> Dict[int, int]:
    """Static per-tick capacity propagation against the Join arenas.

    Walks ``plan`` in topo order carrying worst-case per-node egress row
    counts from the seeded ``ingress_caps`` (sources, loops, fixpoint
    boundary producers), and raises :class:`GraphError` for the
    statically impossible case: one tick's delta capacity exceeding the
    whole (per-shard, via ``divisor``) arena. The *dynamic* high-water
    check stays inside the compiled program (``lax.cond`` compaction +
    sticky error flag) — nothing here reads a device value back.

    This is both the per-tick executor's pre-dispatch sanity check and
    the mega-tick ingress queue's capacity negotiation: queue slots are
    only allocated for capacities this propagation accepts.
    """
    outs_cap: Dict[int, int] = dict(ingress_caps)
    for node in plan:
        if node.kind in ("source", "loop") or node.id in ingress_caps:
            continue
        if node.kind == "sink":
            continue
        caps = [outs_cap.get(i.id, 0) for i in node.inputs]
        if all(c == 0 for c in caps):
            continue
        if node.op.kind == "join":
            cap = node.op.arena_capacity // divisor
            if caps[1] > cap:
                raise GraphError(
                    f"{node}: a single tick's right-delta capacity "
                    f"({caps[1]} rows) exceeds the per-shard arena "
                    f"capacity {cap}; raise arena_capacity")
            if not node.inputs[0].spec.unique:
                La = ((node.op.left_arena_capacity
                       or node.op.arena_capacity) // divisor)
                if caps[0] > La:
                    raise GraphError(
                        f"{node}: a single tick's left-delta capacity "
                        f"({caps[0]} rows) exceeds the per-shard left "
                        f"arena capacity {La}; raise "
                        f"left_arena_capacity")
                # both products are budget-bounded pair enumerations
                outs_cap[node.id] = (node.op.product_slack
                                     * (caps[0] + caps[1]) * divisor)
                continue
            # an absent left delta skips the arena sweep entirely;
            # sharded: each of the n shards emits 2*R/n + caps[1] rows
            # (the right delta is all_gather'd), so global egress is
            # 2*R + n*caps[1]
            outs_cap[node.id] = (
                (2 * node.op.arena_capacity if caps[0] else 0) +
                divisor * caps[1])
        elif node.op.kind == "reduce":
            K = node.inputs[0].spec.key_space
            outs_cap[node.id] = 2 * K if caps[0] >= K else 2 * caps[0]
        elif node.op.kind == "knn":
            outs_cap[node.id] = 2 * node.inputs[0].spec.key_space
        elif node.op.kind == "union":
            outs_cap[node.id] = sum(caps)
        else:
            outs_cap[node.id] = caps[0]
    return outs_cap


def compact_arena(state: dict) -> dict:
    """Pure kernel: (join state) -> (join state with arena compacted).

    Only the arena fields (rkeys/rvals/rw/rcount) change; the left table
    passes through untouched. Shapes are static; runs under jit or as a
    shard_map body.
    """
    rk, rv, rw = state["rkeys"], state["rvals"], state["rw"]
    R = rk.shape[0]
    vcols = rv.reshape(R, -1)
    # bitwise value identity at NATIVE width (ADVICE r2: narrowing 64-bit
    # payloads to 32 bits before the compare can alias distinct values and
    # corrupt non-matching rows): 64-bit dtypes bitcast to two int32
    # columns, 32-bit to one, 16-bit through int16; sub-4-byte ints widen
    # losslessly
    itemsize = jnp.dtype(vcols.dtype).itemsize
    if itemsize >= 4:
        bits = jax.lax.bitcast_convert_type(vcols, jnp.int32).reshape(R, -1)
    elif itemsize == 2:
        bits = jax.lax.bitcast_convert_type(
            vcols, jnp.int16).astype(jnp.int32).reshape(R, -1)
    elif jnp.issubdtype(vcols.dtype, jnp.floating):
        # 1-byte floats (f8 variants): widen losslessly, then bitcast —
        # a numeric int cast would truncate distinct values to one bucket
        bits = jax.lax.bitcast_convert_type(
            vcols.astype(jnp.float32), jnp.int32).reshape(R, -1)
    else:
        bits = vcols.astype(jnp.int32)
    live = rw != 0
    skey = jnp.where(live, rk, jnp.iinfo(jnp.int32).max)

    # lex order: key primary, then value columns (np.lexsort: LAST key is
    # primary)
    order = jnp.lexsort(tuple(bits[:, q] for q in range(bits.shape[1] - 1,
                                                        -1, -1)) + (skey,))
    sk = skey[order]
    sb = bits[order]
    sv = rv[order]
    sw = rw[order]

    prev_same = jnp.concatenate([
        jnp.zeros((1,), jnp.bool_),
        (sk[1:] == sk[:-1]) & jnp.all(sb[1:] == sb[:-1], axis=-1),
    ])
    first = ~prev_same
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    netw = jnp.zeros((R,), jnp.int32).at[gid].add(sw)
    keep = first & (netw[gid] != 0) & (sk != jnp.iinfo(jnp.int32).max)

    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, pos, R)
    nk = jnp.zeros_like(rk).at[tgt].set(sk, mode="drop")
    nv = jnp.zeros_like(rv).at[tgt].set(sv, mode="drop")
    nw = jnp.zeros_like(rw).at[tgt].set(netw[gid], mode="drop")
    ncount = jnp.sum(keep.astype(jnp.int32))

    out = dict(state)
    out.update(rkeys=nk, rvals=nv, rw=nw,
               rcount=jnp.broadcast_to(ncount, state["rcount"].shape
                                       ).astype(state["rcount"].dtype))
    if "gen" in state:
        # compaction reorders rows: bump the generation so any persistent
        # CSR cache over the old ordering invalidates (linear_fixpoint)
        out["gen"] = state["gen"] + 1
    return out
