"""Join-arena compaction (GC): bound the arena by LIVE rows, not lifetime.

The device Join stores its right side as an append-only log: retractions
append negative-weight rows rather than freeing their match, so without
reclamation ``arena_capacity`` must cover the *lifetime* append count and
a long-running stream eventually dies on the overflow check (round-1
VERDICT item 7).

``compact_arena`` cancels matched pairs on device: rows are lex-sorted by
(key, value bytes), equal (key, value) runs are weight-summed, and groups
with net weight 0 vanish; survivors are repacked to the front with their
net weight. Exactness contract: a retraction carries the SAME value bytes
as the insert it cancels (true by construction for host-driven deltas —
the retract batch replays the original row with weight -1; float values
are compared bitwise, so NaNs and signed zeros cancel only their
bit-identical twins).

Compaction triggers IN-PROGRAM: ``join_core`` wraps this kernel in a
``lax.cond`` guarded by ``rcount + appends > capacity``, so the
high-water decision is data-dependent on device and never reads a value
back to the host (SURVEY.md §7 hard part d — streaming ticks stay
pipelined). A genuine overflow (live + appends > capacity even after
compaction) sets the join state's sticky ``error`` flag, raised at the
next sync point. Sharded executors reach this through the same path:
``join_core`` runs per shard under ``shard_map`` (rows never migrate;
each shard compacts its slice and its slot of ``rcount``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["compact_arena"]


def compact_arena(state: dict) -> dict:
    """Pure kernel: (join state) -> (join state with arena compacted).

    Only the arena fields (rkeys/rvals/rw/rcount) change; the left table
    passes through untouched. Shapes are static; runs under jit or as a
    shard_map body.
    """
    rk, rv, rw = state["rkeys"], state["rvals"], state["rw"]
    R = rk.shape[0]
    vcols = rv.reshape(R, -1)
    # bitwise value identity at NATIVE width (ADVICE r2: narrowing 64-bit
    # payloads to 32 bits before the compare can alias distinct values and
    # corrupt non-matching rows): 64-bit dtypes bitcast to two int32
    # columns, 32-bit to one, 16-bit through int16; sub-4-byte ints widen
    # losslessly
    itemsize = jnp.dtype(vcols.dtype).itemsize
    if itemsize >= 4:
        bits = jax.lax.bitcast_convert_type(vcols, jnp.int32).reshape(R, -1)
    elif itemsize == 2:
        bits = jax.lax.bitcast_convert_type(
            vcols, jnp.int16).astype(jnp.int32).reshape(R, -1)
    elif jnp.issubdtype(vcols.dtype, jnp.floating):
        # 1-byte floats (f8 variants): widen losslessly, then bitcast —
        # a numeric int cast would truncate distinct values to one bucket
        bits = jax.lax.bitcast_convert_type(
            vcols.astype(jnp.float32), jnp.int32).reshape(R, -1)
    else:
        bits = vcols.astype(jnp.int32)
    live = rw != 0
    skey = jnp.where(live, rk, jnp.iinfo(jnp.int32).max)

    # lex order: key primary, then value columns (np.lexsort: LAST key is
    # primary)
    order = jnp.lexsort(tuple(bits[:, q] for q in range(bits.shape[1] - 1,
                                                        -1, -1)) + (skey,))
    sk = skey[order]
    sb = bits[order]
    sv = rv[order]
    sw = rw[order]

    prev_same = jnp.concatenate([
        jnp.zeros((1,), jnp.bool_),
        (sk[1:] == sk[:-1]) & jnp.all(sb[1:] == sb[:-1], axis=-1),
    ])
    first = ~prev_same
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    netw = jnp.zeros((R,), jnp.int32).at[gid].add(sw)
    keep = first & (netw[gid] != 0) & (sk != jnp.iinfo(jnp.int32).max)

    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, pos, R)
    nk = jnp.zeros_like(rk).at[tgt].set(sk, mode="drop")
    nv = jnp.zeros_like(rv).at[tgt].set(sv, mode="drop")
    nw = jnp.zeros_like(rw).at[tgt].set(netw[gid], mode="drop")
    ncount = jnp.sum(keep.astype(jnp.int32))

    out = dict(state)
    out.update(rkeys=nk, rvals=nv, rw=nw,
               rcount=jnp.broadcast_to(ncount, state["rcount"].shape
                                       ).astype(state["rcount"].dtype))
    if "gen" in state:
        # compaction reorders rows: bump the generation so any persistent
        # CSR cache over the old ordering invalidates (linear_fixpoint)
        out["gen"] = state["gen"] + 1
    return out
