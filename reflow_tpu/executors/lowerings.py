"""Per-op XLA lowerings: one tick pass = pure array code (SURVEY.md §7.7).

Each lowering is a pure function ``(op, node, state, in_deltas) ->
(out_delta, state')`` over :class:`DeviceDelta` buffers and dense keyed
state tables. Design rules (tpu-first):

- **No data-dependent shapes.** Emission capacities are static functions of
  input capacities and key-space sizes; dead rows carry weight 0.
- **No host round-trips.** Everything here runs inside one ``jax.jit`` step.
- **NaN hygiene.** Padding rows may hold garbage values; every consumption
  multiplies through a ``where(w == 0, 0, ...)`` guard so garbage never
  reaches live state.

Keyed-state representations:

- Reduce (linear reducers sum/count/mean): dense tables over the key space —
  ``wsum[K,*V]`` (Σ w·v), ``wcnt[K]`` (Σ w), ``emitted[K,*V]`` +
  ``emitted_has[K]`` (the last aggregate actually emitted downstream, for
  retract-correctness under ``tol`` — mirrors the host oracle exactly).
- Join: left side a unique-keyed dense table (``lval[K,*VA]``, ``lw[K]``);
  right side an append-log arena (``rkeys[R]``, ``rvals[R,*VB]``,
  ``rw[R]``, ``rcount``). δ(A⋈B) = δA⋈B + (A+δA)⋈δB, with δA split into
  its retract/insert halves scattered to dense temp tables so the arena-side
  product is a pure gather (this is the SpMV shape the MXU/VPU wants).

Non-linear reducers (min/max) lower to a bounded per-key candidate buffer
(``minmax_core``) holding the R lex-best distinct value rows per key with
their multiset weights: retractions stay EXACT while the answer is
derivable from the buffer, and cross into a sticky loud error when churn
exhausts it (SURVEY.md §7 hard part c: bounded per-key multisets, loud
failure beyond the bound). Scalar and vector values share the kernel —
rows are ordered lexicographically, the host oracle's tuple order.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from reflow_tpu.delta import Spec
from reflow_tpu.executors.device_delta import DeviceDelta
from reflow_tpu.graph import Node
from reflow_tpu.ops import Filter, GroupBy, Join, Map, Reduce, Union

__all__ = ["lower_node", "reduce_state", "join_state", "join_core",
           "knn_state", "minmax_core", "minmax_refresh_core",
           "DEVICE_REDUCERS"]

#: sum/count/mean lower to linear scatter-adds; min/max lower to the
#: bounded candidate-buffer kernel (retraction-exact within the per-key
#: buffer, sticky loud error beyond it — raise Reduce(candidates=...) or
#: run pathological churn on the CPU oracle)
DEVICE_REDUCERS = ("sum", "count", "mean", "min", "max")
LINEAR_DEVICE_REDUCERS = ("sum", "count", "mean")


# -- state builders --------------------------------------------------------

def reduce_state(op: Reduce, in_spec: Spec, out_spec: Spec) -> dict:
    K = in_spec.key_space
    vshape = tuple(in_spec.value_shape)
    oshape = tuple(out_spec.value_shape)
    if op.how not in LINEAR_DEVICE_REDUCERS:
        # min/max, scalar AND vector: retraction-capable candidate buffer
        # with lexicographic row ordering (the host oracle's tuple order)
        return minmax_state(op, K, vshape, oshape, out_spec.value_dtype)
    return {
        "wsum": jnp.zeros((K,) + vshape, jnp.float32),
        "wcnt": jnp.zeros((K,), jnp.int32),
        "emitted": jnp.zeros((K,) + oshape, out_spec.value_dtype),
        "emitted_has": jnp.zeros((K,), jnp.bool_),
    }


def join_state(op: Join, left_spec: Spec, right_spec: Spec) -> dict:
    K = left_spec.key_space
    R = op.arena_capacity
    if not left_spec.unique:
        # MULTISET left (ROADMAP r4 #2 / VERDICT r4 #5): the left side is
        # a second append arena mirroring the right side's log; both
        # δ-products are key-matched delta×arena pair enumerations at a
        # static budget (see _keyed_product). No dense lval/lw tables —
        # a multiset has no per-key value to store densely.
        La = op.left_arena_capacity or op.arena_capacity
        return {
            "lkeys": jnp.zeros((La,), jnp.int32),
            "lvals": jnp.zeros((La,) + tuple(left_spec.value_shape),
                               left_spec.value_dtype),
            "lrw": jnp.zeros((La,), jnp.int32),
            "lcount": jnp.zeros((), jnp.int32),
            "lgen": jnp.zeros((), jnp.int32),
            "rkeys": jnp.zeros((R,), jnp.int32),
            "rvals": jnp.zeros((R,) + tuple(right_spec.value_shape),
                               right_spec.value_dtype),
            "rw": jnp.zeros((R,), jnp.int32),
            "rcount": jnp.zeros((), jnp.int32),
            "gen": jnp.zeros((), jnp.int32),
            "error": jnp.zeros((), jnp.bool_),
        }
    return {
        "lval": jnp.zeros((K,) + tuple(left_spec.value_shape),
                          left_spec.value_dtype),
        "lw": jnp.zeros((K,), jnp.int32),
        "rkeys": jnp.zeros((R,), jnp.int32),
        "rvals": jnp.zeros((R,) + tuple(right_spec.value_shape),
                           right_spec.value_dtype),
        "rw": jnp.zeros((R,), jnp.int32),
        "rcount": jnp.zeros((), jnp.int32),
        # arena generation: bumped by every compaction (which reorders
        # rows). The linear fixpoint's persistent CSR cache keys its
        # validity on (gen, rcount): a gen mismatch means the base
        # ordering is gone and the CSR must rebuild.
        "gen": jnp.zeros((), jnp.int32),
        # sticky: set when an append overflows the arena even after the
        # in-program compaction pass (checked loudly at the next sync)
        "error": jnp.zeros((), jnp.bool_),
    }


# -- helpers ---------------------------------------------------------------

def _bcast_w(w: jax.Array, values: jax.Array) -> jax.Array:
    """weights [C] broadcast against values [C, *V]."""
    return w.reshape(w.shape + (1,) * (values.ndim - 1))


def _masked_contrib(w: jax.Array, values: jax.Array) -> jax.Array:
    """w·v with an explicit zero at w==0 so padding NaNs never propagate."""
    wb = _bcast_w(w, values)
    return jnp.where(wb == 0, 0, wb.astype(values.dtype) * values)


def _differs(a: jax.Array, b: jax.Array, tol: float) -> jax.Array:
    """Per-key 'aggregates differ' over trailing value axes."""
    if tol > 0.0:
        d = jnp.abs(a - b) > tol
    else:
        d = a != b
    if d.ndim > 1:
        d = jnp.any(d, axis=tuple(range(1, d.ndim)))
    return d


# -- Map / Filter / GroupBy / Union ----------------------------------------

def _apply_rowfn(fn, vectorized: bool, *cols):
    if vectorized:
        return fn(*cols)
    return jax.vmap(fn)(*cols)


def _lower_map(op: Map, node: Node, state, ins) -> Tuple[DeviceDelta, None]:
    (d,) = ins
    if op.params is not None:
        # params flow in as op STATE (a program argument), never as traced
        # constants — program size stays independent of the model size and
        # params swap without recompiling. State passes through unchanged.
        p = state["params"]
        if op.vectorized:
            vals = op.fn(p, d.values)
        else:
            vals = jax.vmap(op.fn, in_axes=(None, 0))(p, d.values)
        return (DeviceDelta(d.keys, jnp.asarray(vals, node.spec.value_dtype),
                            d.weights), state)
    vals = _apply_rowfn(op.fn, op.vectorized, d.values)
    vals = jnp.asarray(vals, node.spec.value_dtype)
    return DeviceDelta(d.keys, vals, d.weights), None


def _lower_filter(op: Filter, node: Node, state, ins) -> Tuple[DeviceDelta, None]:
    (d,) = ins
    keep = _apply_rowfn(op.pred, op.vectorized, d.values)
    w = jnp.where(jnp.asarray(keep, jnp.bool_), d.weights, 0)
    return DeviceDelta(d.keys, d.values, w), None


def _lower_groupby(op: GroupBy, node: Node, state, ins) -> Tuple[DeviceDelta, None]:
    (d,) = ins
    keys = jnp.asarray(
        _apply_rowfn(op.key_fn, op.vectorized, d.keys, d.values), jnp.int32)
    # keep padding rows at key 0 so downstream scatters stay in range
    keys = jnp.where(d.weights == 0, 0, keys)
    vals = d.values
    if op.value_fn is not None:
        vals = jnp.asarray(
            _apply_rowfn(op.value_fn, op.vectorized, d.keys, d.values),
            node.spec.value_dtype)
    return DeviceDelta(keys, vals, d.weights), None


def _lower_union(op: Union, node: Node, state, ins) -> Tuple[DeviceDelta, None]:
    live = [d for d in ins if d is not None]  # absent streams vanish
    return DeviceDelta(
        jnp.concatenate([d.keys for d in live]),
        jnp.concatenate([d.values for d in live]),
        jnp.concatenate([d.weights for d in live]),
    ), None


# -- Reduce ----------------------------------------------------------------

def _agg_tables(op: Reduce, wsum, wcnt, vdtype):
    """(aggregate, exists) per key from the running linear tables.

    Existence mirrors the host oracle's linear-observable rule (see
    ``Reduce._aggregate``): a group exists iff Σw != 0 or Σw·v != 0. For
    sum with ``tol > 0`` the Σw·v test is tol-guarded, so float scatter-add
    residue after a full retraction doesn't leave a phantom group behind
    (with tol == 0 the contract is exact float equality; use a small tol
    for float workloads on device).
    """
    if op.how == "sum":
        agg = jnp.asarray(wsum, vdtype)
        nz = jnp.abs(wsum) > op.tol if op.tol > 0.0 else wsum != 0
        if nz.ndim > 1:
            nz = jnp.any(nz, axis=tuple(range(1, nz.ndim)))
        exists = (wcnt != 0) | nz
    elif op.how == "count":
        agg = jnp.asarray(wcnt, vdtype)
        exists = wcnt != 0
    elif op.how == "mean":
        denom = jnp.where(wcnt == 0, 1, wcnt)
        agg = jnp.asarray(wsum / _bcast_w(denom, wsum), vdtype)
        exists = wcnt != 0
    else:  # pragma: no cover - validated at bind
        raise NotImplementedError(op.how)
    return agg, exists


def _lex_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic ``a < b`` over the trailing axis (equal -> False).

    The host oracle's min/max of vector values is the MIN of value
    TUPLES (ops/core.py ``_agg_min``: Python tuple ordering), so the
    device path orders candidate rows lexicographically too — NOT
    elementwise extrema, which would fabricate a vector that is in no
    row of the multiset.
    """
    neq = a != b
    has = jnp.any(neq, axis=-1)
    fi = jnp.argmax(neq, axis=-1)
    av = jnp.take_along_axis(a, fi[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(b, fi[..., None], axis=-1)[..., 0]
    return jnp.where(has, av < bv, False)


def minmax_state(op: Reduce, K: int, in_vshape, out_vshape, odtype) -> dict:
    """State for the retraction-capable min/max (candidate buffer),
    scalar and vector values alike (a scalar is the V=1 row case).

    Values ride sign-normalized (``sign*v``, sign = +1 for min / -1 for
    max) so one lex-MIN kernel serves both. ``cand_v``/``cand_w`` hold
    the R lex-smallest (normalized) distinct value ROWS per key with
    their multiset weights (any sign: anti-rows are legal transients),
    stored in ascending lex order — the kernel's rank-ordered rebuild
    maintains that invariant. ``over_lo`` is a MONOTONE watermark row:
    the lex-smallest value ever evicted; ``over_maybe_pos`` latches
    whether any positive-net row was ever evicted. Together they bound
    what the buffer can prove: the buffered minimum is global only while
    strictly lex-below the watermark, and group existence is decidable
    only while positive support cannot be hiding in the overflow
    (SURVEY.md §7 hard part c: bounded per-key multisets, loud failure
    beyond the bound). Buffer memory is K x R x V floats — the device
    path is meant for modest V; huge-vector extrema belong on the CPU
    oracle.
    """
    R = op.candidates
    V = 1
    for s in in_vshape:
        V *= s
    return {
        "cand_v": jnp.full((K, R, V), jnp.inf, jnp.float32),
        "cand_w": jnp.zeros((K, R), jnp.int32),
        # monotone per-key latches — overflow rows lose their identity,
        # so nothing can ever clear them (see utils refresh for the
        # host-triggered reset path)
        "over_lo": jnp.full((K, V), jnp.inf, jnp.float32),
        "over_maybe_pos": jnp.zeros((K,), jnp.bool_),
        "emitted": jnp.zeros((K,) + tuple(out_vshape), odtype),
        "emitted_has": jnp.zeros((K,), jnp.bool_),
        "error": jnp.zeros((), jnp.bool_),
    }


def minmax_core(op: Reduce, K: int, out_vshape, odtype, state,
                d: DeviceDelta, key_offset=0
                ) -> Tuple[DeviceDelta, dict]:
    """One tick of the buffered min/max over a (per-shard) key range;
    ``d`` carries keys local to ``[0, K)``. Scalar and VECTOR values
    share this kernel: a candidate is a distinct value ROW [V], ordered
    lexicographically (the host oracle's tuple ordering), and a scalar
    is simply V=1.

    Algorithm (all shape-static): compact the tick's touched keys into
    slots, gather their buffers, merge buffer rows + delta rows by
    (slot, normalized value row) with one multi-column lexsort, net
    bit-equal rows' weights, keep the R lex-best nonzero rows per slot
    (rank by running count — the buffer therefore stays rank-SORTED,
    which is what lets the aggregate read the first positive rank),
    evict the rest into the ``over_lo``/``over_maybe_pos`` latches,
    scatter the rebuilt buffers back. Exactness: the buffer's first
    positive entry is the true extremum iff it is strictly lex-below
    ``over_lo`` (everything ever evicted was no better than the buffer's
    worst AT EVICTION TIME, but later retractions can hollow the buffer
    past that point — then the answer is unknowable from bounded state
    and the sticky error raises). Negative-weight entries (retractions
    of evicted or not-yet-inserted values — legal multiset transients)
    occupy buffer slots as anti-rows and cancel against later inserts.
    """
    sign = jnp.float32(1.0 if op.how == "min" else -1.0)
    R = state["cand_v"].shape[1]
    V = state["cand_v"].shape[2]
    C = d.capacity
    INF = jnp.float32(jnp.inf)

    live = d.weights != 0
    dval = jnp.where(live[:, None],
                     sign * d.values.reshape(C, V).astype(jnp.float32),
                     INF)

    # touched keys -> dense slots [0, n_t)
    skey = jnp.where(live, d.keys, K)
    order = jnp.argsort(skey)
    sk = skey[order]
    prev = jnp.concatenate([jnp.full((1,), -1, sk.dtype), sk[:-1]])
    first = (sk != prev) & (sk < K)
    slot_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    # slot -> key
    tkeys = jnp.full((C,), K, jnp.int32).at[
        jnp.where(first, slot_sorted, C)].set(sk.astype(jnp.int32),
                                              mode="drop")
    # original row -> slot (dead rows -> C)
    row_slot = jnp.full((C,), C, jnp.int32).at[order].set(
        jnp.where(sk < K, slot_sorted, C))

    tk_c = jnp.minimum(tkeys, K - 1)
    tvalid = tkeys < K
    bw = jnp.where(tvalid[:, None], state["cand_w"][tk_c], 0)    # [C, R]
    bv = jnp.where((bw != 0)[:, :, None], state["cand_v"][tk_c], INF)

    # merged candidate rows: C*R buffer rows + C delta rows
    slot_b = jnp.where(bw.reshape(-1) != 0,
                       jnp.repeat(jnp.arange(C, dtype=jnp.int32), R), C)
    mslot = jnp.concatenate([slot_b, row_slot])
    mval = jnp.concatenate([bv.reshape(C * R, V), dval])         # [M, V]
    mw = jnp.concatenate([bw.reshape(-1), jnp.where(live, d.weights, 0)])
    M = mslot.shape[0]

    # lex order: slot primary, then value columns (np.lexsort: LAST key
    # is primary)
    o2 = jnp.lexsort(tuple(mval[:, q] for q in range(V - 1, -1, -1))
                     + (mslot,))
    s2, v2, w2 = mslot[o2], mval[o2], mw[o2]
    pv = jnp.concatenate([jnp.full((1,), -1, s2.dtype), s2[:-1]])
    pval = jnp.concatenate([jnp.full((1, V), -INF), v2[:-1]])
    first2 = ((s2 != pv) | jnp.any(v2 != pval, axis=1)) & (s2 < C)
    gid = jnp.cumsum(first2.astype(jnp.int32)) - 1
    gid_c = jnp.where(s2 < C, gid, M - 1)
    netw = jnp.zeros((M,), jnp.int32).at[gid_c].add(
        jnp.where(s2 < C, w2, 0))
    net_here = netw[gid_c]
    alive = first2 & (net_here != 0)

    # rank among alive rows within each slot
    ca = jnp.cumsum(alive.astype(jnp.int32))
    slot_start = (s2 != pv) & (s2 < C)
    base = jnp.zeros((C + 1,), jnp.int32).at[
        jnp.where(slot_start, s2, C)].set(ca - alive.astype(jnp.int32),
                                          mode="drop")
    rank = ca - 1 - base[jnp.minimum(s2, C)]
    keep = alive & (rank < R)
    evict = alive & (rank >= R)

    # rebuilt buffers per slot (rank-ordered: ascending lex)
    flat = jnp.where(keep, jnp.minimum(s2, C - 1) * R + rank, C * R)
    nb_v = jnp.full((C * R + 1, V), INF).at[flat].set(
        v2, mode="drop")[:C * R].reshape(C, R, V)
    nb_w = jnp.zeros((C * R + 1,), jnp.int32).at[flat].set(
        net_here, mode="drop")[:C * R].reshape(C, R)

    # evictions: the slot's FIRST evicted row (rank == R) is the
    # lex-smallest evicted (rows are sorted), and it lowers the over_lo
    # watermark; a positive-net eviction latches over_maybe_pos (both
    # monotone — overflow rows lose their identity, so these can never
    # be cleared)
    first_ev = evict & (rank == R)
    ev_lo = jnp.full((C + 1, V), INF).at[
        jnp.where(first_ev, s2, C)].set(v2, mode="drop")[:C]
    ev_pos = jnp.zeros((C + 1,), jnp.bool_).at[
        jnp.where(evict & (net_here > 0), s2, C)].set(
        True, mode="drop")[:C]

    sidx = jnp.where(tvalid, tkeys, K)
    cand_v = state["cand_v"].at[sidx].set(nb_v, mode="drop")
    cand_w = state["cand_w"].at[sidx].set(nb_w, mode="drop")
    lo_g = jnp.where(tvalid[:, None], state["over_lo"][tk_c], INF)
    new_lo = jnp.where(_lex_lt(ev_lo, lo_g)[:, None], ev_lo, lo_g)
    over_lo = state["over_lo"].at[sidx].set(new_lo, mode="drop")
    over_maybe_pos = state["over_maybe_pos"] | jnp.zeros(
        (K,), jnp.bool_).at[sidx].set(ev_pos, mode="drop")

    # dense aggregate over the key range. Existence mirrors the host
    # oracle's any(w > 0) positive-support rule: provable from the
    # buffer alone unless a positive row was ever evicted. Exactness of
    # the buffered minimum additionally needs bmin strictly lex-below
    # the eviction watermark: at equality an evicted ANTI-row at that
    # very value could cancel the buffered positive support.
    pos = cand_w > 0                                  # [K, R]
    has_pos = jnp.any(pos, axis=1)
    fi = jnp.argmax(pos, axis=1)
    bmin = jnp.take_along_axis(cand_v, fi[:, None, None],
                               axis=1)[:, 0]          # [K, V]
    unknown = ((~has_pos & over_maybe_pos)
               | (has_pos & ~_lex_lt(bmin, over_lo)))
    exists = has_pos
    # cand_w accumulates per-(key, value) net weights ACROSS ticks with
    # only the per-batch 2**24 mass guard upstream (check_weight_mass);
    # sustained re-insertion of one value could wrap int32 silently and
    # flip existence/min decisions (ADVICE r3). Latch loudly at 2**30 —
    # far below wrap, with room for any single legal batch on top.
    w_over = jnp.any(jnp.abs(nb_w) > (1 << 30))
    error = state["error"] | jnp.any(unknown) | w_over

    emitted, em_has = state["emitted"], state["emitted_has"]
    agg_rows = sign * jnp.where(has_pos[:, None], bmin, 0.0)
    aggv = jnp.asarray(agg_rows.reshape((K,) + tuple(out_vshape)), odtype)
    changed = _differs(aggv, emitted, op.tol)
    ins_m = exists & ~unknown & (~em_has | changed)
    ret_m = em_has & ((~exists | changed) & ~unknown)
    gkeys = key_offset + jnp.arange(K, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([gkeys, gkeys]),
        values=jnp.concatenate([emitted, aggv]),
        weights=jnp.concatenate(
            [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
    )
    new_emitted = jnp.where(_bcast_w(ins_m, aggv), aggv, emitted)
    new_has = jnp.where(ins_m, True,
                        jnp.where(ret_m & ~exists, False, em_has))
    return out, {"cand_v": cand_v, "cand_w": cand_w, "over_lo": over_lo,
                 "over_maybe_pos": over_maybe_pos, "emitted": new_emitted,
                 "emitted_has": new_has, "error": error}


def _scatter_contribs(d: DeviceDelta, K: int):
    """One fused scatter-add of (w*v, w) into a [K, F+1] table.

    TPU scatter cost scales with update rows, so stacking the weighted
    values and the weights into one update halves the dominant cost of
    large reduce passes vs two separate scatter-adds.
    """
    C = d.capacity
    vflat = _masked_contrib(d.weights, d.values).astype(
        jnp.float32).reshape(C, -1)
    upd = jnp.concatenate(
        [vflat, d.weights.astype(jnp.float32)[:, None]], axis=-1)
    table = jnp.zeros((K, upd.shape[1]), jnp.float32).at[d.keys].add(upd)
    vshape = d.values.shape[1:]
    dws = table[:, :-1].reshape((K,) + vshape)
    # weights are ints; their float32 sum is exact below 2**24 rows/key
    dwc = table[:, -1].astype(jnp.int32)
    return dws, dwc


def minmax_refresh_core(op: Reduce, K: int, out_vshape, odtype, state,
                        d: DeviceDelta, key_offset=0) -> dict:
    """Latch REFRESH (ROADMAP r3 #3): rebuild the candidate buffers of
    every key present in ``d`` from a user-supplied REPLAY of its full
    live multiset, resetting the monotone ``over_lo``/``over_maybe_pos``
    latches — the maintenance path that keeps a long-running
    heavy-churn key exact instead of eventually tripping the loud
    overflow error.

    Contract: for each key it mentions, ``d`` holds EVERY live row of
    that key's current collection (one +w row per multiset entry).
    Because the replay is the same collection the state already
    aggregates, the emitted aggregate cannot change: a live emission
    diff out of the replay means the replay contradicts the state
    (user error, or prior corruption) and sets the sticky error flag
    instead of silently re-emitting.
    """
    live = d.weights != 0
    touched = jnp.zeros((K,), jnp.bool_).at[
        jnp.where(live, d.keys, K)].set(True, mode="drop")
    st = dict(state)
    tb = touched[:, None]
    st["cand_v"] = jnp.where(touched[:, None, None], jnp.inf,
                             state["cand_v"])
    st["cand_w"] = jnp.where(tb, 0, state["cand_w"])
    st["over_lo"] = jnp.where(tb, jnp.inf, state["over_lo"])
    st["over_maybe_pos"] = jnp.where(touched, False,
                                     state["over_maybe_pos"])
    out, st2 = minmax_core(op, K, out_vshape, odtype, st, d, key_offset)
    st2["error"] = st2["error"] | jnp.any(out.weights != 0)
    return st2


def _lower_reduce(op: Reduce, node: Node, state, ins) -> Tuple[DeviceDelta, dict]:
    if op.how not in LINEAR_DEVICE_REDUCERS:
        (d,) = ins
        return minmax_core(op, node.inputs[0].spec.key_space,
                           tuple(node.spec.value_shape),
                           node.spec.value_dtype, state, d)
    (d,) = ins
    in_spec = node.inputs[0].spec
    K = in_spec.key_space
    C = d.capacity
    vdtype = node.spec.value_dtype

    emitted, em_has = state["emitted"], state["emitted_has"]

    if C >= K:
        dws, dwc = _scatter_contribs(d, K)
        wsum = state["wsum"] + dws
        wcnt = state["wcnt"] + dwc
        # dense mode: diff the whole aggregate table against what was
        # emitted — no sort, pure vector ops (the PageRank-iteration shape).
        agg, exists = _agg_tables(op, wsum, wcnt, vdtype)
        changed = _differs(agg, emitted, op.tol)
        ins_m = exists & (~em_has | changed)
        ret_m = em_has & (~exists | changed)
        all_keys = jnp.arange(K, dtype=jnp.int32)
        out = DeviceDelta(
            keys=jnp.concatenate([all_keys, all_keys]),
            values=jnp.concatenate([emitted, agg]),
            weights=jnp.concatenate(
                [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
        )
        ins_b = _bcast_w(ins_m, agg)
        new_emitted = jnp.where(ins_b, agg, emitted)
        new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~exists, False, em_has))
    else:
        # sparse mode: O(C) end to end, never O(K) — contributions
        # scatter-add straight into the persistent tables (no zeros[K]
        # staging table, no full-table add), and aggregation/emission
        # runs only on the gathered touched rows. This is what makes
        # small-edit streaming (config 2: 256-row edits into 2^20-key
        # tables) cost per-edit work instead of per-vocabulary work.
        contrib = _masked_contrib(d.weights, d.values).astype(jnp.float32)
        wsum = state["wsum"].at[d.keys].add(
            contrib.astype(state["wsum"].dtype))
        wcnt = state["wcnt"].at[d.keys].add(d.weights)

        live = d.weights != 0
        skey = jnp.where(live, d.keys, K)
        order = jnp.argsort(skey)
        sk = skey[order]
        prev = jnp.concatenate([jnp.full((1,), -1, sk.dtype), sk[:-1]])
        first = (sk != prev) & (sk < K)
        tk = jnp.where(sk < K, sk, 0).astype(jnp.int32)

        agg, exists = _agg_tables(op, wsum[tk], wcnt[tk], vdtype)
        em = emitted[tk]
        has = em_has[tk]
        changed = _differs(agg, em, op.tol)
        ins_m = first & exists & (~has | changed)
        ret_m = first & has & (~exists | changed)
        out = DeviceDelta(
            keys=jnp.concatenate([tk, tk]),
            values=jnp.concatenate([em, agg]),
            weights=jnp.concatenate(
                [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
        )
        set_ins = jnp.where(ins_m, tk, K)
        new_emitted = emitted.at[set_ins].set(agg, mode="drop")
        new_has = em_has.at[set_ins].set(True, mode="drop")
        set_ret = jnp.where(ret_m & ~exists, tk, K)
        new_has = new_has.at[set_ret].set(False, mode="drop")

    new_state = {"wsum": wsum, "wcnt": wcnt,
                 "emitted": new_emitted, "emitted_has": new_has}
    return out, new_state


# -- Join ------------------------------------------------------------------

def _lower_join(op: Join, node: Node, state, ins) -> Tuple[DeviceDelta, dict]:
    da, db = ins
    left_spec = node.inputs[0].spec
    return join_core(op, left_spec.key_space, op.arena_capacity,
                     node.spec.value_dtype, state, da, db,
                     oshape=tuple(node.spec.value_shape))


def _append_arena(arena: dict, keys, vals, w, R) -> Tuple[dict, jax.Array]:
    """Append live delta rows to an append-log arena (compacted: live
    rows first), compacting in-program when the append would cross
    capacity. -> (arena', overflow). Shared by the right arena and the
    multiset-left arena (the latter aliases its fields to the rkeys/...
    names this kernel and ``compact_arena`` use)."""
    from reflow_tpu.executors.arena import compact_arena

    live = w != 0
    n_app = jnp.sum(live.astype(jnp.int32))
    arena = jax.lax.cond(arena["rcount"] + n_app > R,
                         compact_arena, lambda s: s, arena)
    rank = jnp.cumsum(live.astype(jnp.int32)) - 1
    pos = jnp.where(live, arena["rcount"] + rank, R)
    out = dict(arena)
    out["rkeys"] = arena["rkeys"].at[pos].set(keys, mode="drop")
    out["rvals"] = arena["rvals"].at[pos].set(vals, mode="drop")
    out["rw"] = arena["rw"].at[pos].set(w, mode="drop")
    out["rcount"] = arena["rcount"] + n_app
    return out, out["rcount"] > R


def _join_core_multiset(op: Join, K: int, R: int, state,
                        da: Optional[DeviceDelta],
                        db: Optional[DeviceDelta], merge_v,
                        key_offset) -> Tuple[DeviceDelta, dict]:
    """Two-arena join: both sides are append logs; both δ-products are
    key-matched pair enumerations (δA against the old right arena, δB
    against the post-fold left arena — the bilinear update δA⋈B +
    (A+δA)⋈δB) at static budgets of ``product_slack x delta_capacity``
    pair slots. Sticky error on budget or arena overflow."""
    err = state["error"]
    new_state = dict(state)
    outs = []

    if da is not None:
        out_a, ovf = _keyed_product(
            da.keys, da.values, da.weights,
            state["rkeys"], state["rvals"], state["rw"],
            K, op.product_slack * da.capacity,
            lambda k, vd, va_: merge_v(k - key_offset, vd, va_),
            key_offset)
        err = err | ovf
        outs.append(out_a)
        larena = {"rkeys": state["lkeys"], "rvals": state["lvals"],
                  "rw": state["lrw"], "rcount": state["lcount"],
                  "gen": state["lgen"]}
        La = state["lkeys"].shape[0]
        larena, lovf = _append_arena(larena, da.keys, da.values,
                                     da.weights, La)
        err = err | lovf
        new_state.update(lkeys=larena["rkeys"], lvals=larena["rvals"],
                         lrw=larena["rw"], lcount=larena["rcount"],
                         lgen=larena["gen"])

    if db is not None:
        # (A + δA) ⋈ δB : delta is the RIGHT side, arena the LEFT — swap
        # the value argument order back to merge(k, va, vb)
        out_b, ovf = _keyed_product(
            db.keys, db.values, db.weights,
            new_state["lkeys"], new_state["lvals"], new_state["lrw"],
            K, op.product_slack * db.capacity,
            lambda k, vd, va_: merge_v(k - key_offset, va_, vd),
            key_offset)
        err = err | ovf
        outs.append(out_b)
        rarena = {"rkeys": state["rkeys"], "rvals": state["rvals"],
                  "rw": state["rw"], "rcount": state["rcount"],
                  "gen": state["gen"]}
        rarena, rovf = _append_arena(rarena, db.keys, db.values,
                                     db.weights, R)
        err = err | rovf
        new_state.update(rkeys=rarena["rkeys"], rvals=rarena["rvals"],
                         rw=rarena["rw"], rcount=rarena["rcount"],
                         gen=rarena["gen"])

    out = DeviceDelta(
        jnp.concatenate([o.keys for o in outs]),
        jnp.concatenate([o.values for o in outs]),
        jnp.concatenate([o.weights for o in outs]),
    )
    new_state["error"] = err
    return out, new_state


def _keyed_product(dk, dv, dw, ak, av, aw, K: int, T: int, emit,
                   key_offset) -> Tuple[DeviceDelta, jax.Array]:
    """Key-matched delta×arena pair enumeration at static budget ``T``.

    For each live delta row i, pair it with every live arena row sharing
    its key; pairs pack into ``T`` slots via the same scatter-of-starts +
    cumsum slot assignment the fused fixpoint's budget tiers use
    (linear_fixpoint.budget_tab — measured ~13x over searchsorted at 1M
    slots). A true pair count beyond ``T`` returns overflow=True (the
    caller sets the sticky error; never silent truncation).
    ``emit(keys_global, v_delta, v_arena)`` -> merged values [T, ...].
    """
    C = dk.shape[0]
    R = ak.shape[0]
    # CSR over the arena by key (sorted view; dead rows to the sentinel)
    skey = jnp.where(aw != 0, jnp.clip(ak, 0, K - 1), K)
    order = jnp.argsort(skey)
    deg = jnp.zeros((K + 1,), jnp.int32).at[skey].add(1, mode="drop")[:K]
    starts = jnp.cumsum(deg) - deg
    # per-delta-row segment geometry
    k_c = jnp.clip(dk, 0, K - 1)
    di = jnp.where(dw != 0, deg[k_c], 0)
    cum = jnp.cumsum(di)
    total = cum[-1]
    seg0 = cum - di
    overflow = total > T
    # slot -> owning delta ROW INDEX: scatter each segment's row index at
    # its start slot, forward-fill with a running max (row indices rise
    # with slot position, so cummax is exactly last-segment-started; a
    # segment-ORDINAL cumsum would be wrong whenever dead/unmatched delta
    # rows interleave with live ones, e.g. after sharded _localize)
    spos = jnp.where(di > 0, seg0, T)
    marks = jnp.zeros((T,), jnp.int32).at[spos].max(
        jnp.arange(C, dtype=jnp.int32), mode="drop")
    owner = jnp.clip(jax.lax.cummax(marks), 0, C - 1)
    j = jnp.arange(T, dtype=jnp.int32)
    within = j - seg0[owner]
    valid = (j < total) & (di[owner] > 0) & (within < di[owner])
    srow = jnp.clip(starts[k_c[owner]] + within, 0, R - 1)
    row = order[srow]
    k = k_c[owner]
    w = jnp.where(valid, dw[owner] * aw[row], 0)
    vals = emit(k + key_offset, dv[owner], av[row])
    return DeviceDelta(k + key_offset, vals, w), overflow


def join_core(op: Join, K: int, R: int, odtype, state,
              da: Optional[DeviceDelta], db: Optional[DeviceDelta],
              key_offset=0, oshape=None) -> Tuple[DeviceDelta, dict]:
    """The join kernel over a (possibly per-shard) key range.

    ``da``/``db`` carry keys LOCAL to this range ``[0, K)``;
    ``key_offset`` maps them back to global ids on emitted rows and in the
    arguments handed to ``merge`` (the sharded path passes the shard base;
    single-device passes 0). A ``None`` side is *statically* absent: the
    corresponding product, fold, and append are not traced at all — a tick
    that only delivers right-side deltas (the steady churn shape) never
    sweeps the arena, and a loop pass with no right deltas never appends.

    Unique-left state (dense ``lval``/``lw`` tables) takes the table×arena
    path below; multiset-left state (a second ``lkeys``/... append arena)
    takes :func:`_join_core_multiset`.
    """

    def merge_v(keys, va, vb):
        if op.merge is None:
            # default merge (multiset path): concatenate the flattened
            # value pair — the device encoding of the host oracle's
            # (va, vb) tuple (same flat components, same order)
            n = va.shape[0]
            out = jnp.concatenate(
                [jnp.asarray(va, odtype).reshape(n, -1),
                 jnp.asarray(vb, odtype).reshape(n, -1)], axis=-1)
            return out.reshape((n,) + tuple(oshape))
        out = op.merge(keys + key_offset, va, vb)
        return jnp.asarray(out, odtype)

    if "lkeys" in state:
        return _join_core_multiset(op, K, R, state, da, db, merge_v,
                                   key_offset)

    ak, av, aw = state["rkeys"], state["rvals"], state["rw"]
    lval, lw = state["lval"], state["lw"]
    outs = []

    if da is not None:
        # split δA into its retract / insert halves, scattered dense
        wa = da.weights
        ret_keys = jnp.where(wa < 0, da.keys, K)
        ins_keys = jnp.where(wa > 0, da.keys, K)
        zero_val = jnp.zeros((K,) + da.values.shape[1:], da.values.dtype)
        zero_w = jnp.zeros((K,), jnp.int32)
        dval_r = zero_val.at[ret_keys].set(da.values, mode="drop")
        dw_r = zero_w.at[ret_keys].set(wa, mode="drop")
        dval_i = zero_val.at[ins_keys].set(da.values, mode="drop")
        dw_i = zero_w.at[ins_keys].set(wa, mode="drop")

        # δA ⋈ B_old : pure gather over the arena (the SpMV)
        for tab, dw in ((dval_r, dw_r), (dval_i, dw_i)):
            w = dw[ak] * aw
            vals = merge_v(ak, tab[ak], av)
            outs.append(DeviceDelta(ak + key_offset, vals, w))

        # fold δA into the left table
        lw = lw.at[da.keys].add(wa)
        lval = lval.at[ins_keys].set(da.values, mode="drop")

    rkeys, rvals, rw, rcount = ak, av, aw, state["rcount"]
    err = state.get("error", jnp.zeros((), jnp.bool_))
    if db is not None:
        # (A + δA) ⋈ δB
        kb, vb, wb = db.keys, db.values, db.weights
        w = lw[kb] * wb
        vals = merge_v(kb, lval[kb], vb)
        outs.append(DeviceDelta(kb + key_offset, vals, w))

        # append δB to the arena (compacted: live rows first). The
        # high-water check is IN-PROGRAM: when the append would cross
        # capacity, a lax.cond runs the compaction kernel (cancel matched
        # insert/retract pairs) first — the decision never reads a device
        # value back to the host, so streaming ticks stay pipelined
        # (SURVEY.md §7 hard part d). A genuine overflow (live rows +
        # appends > capacity even after compaction) drops the excess rows
        # and sets the sticky error flag, raised at the next sync point.
        from reflow_tpu.executors.arena import compact_arena

        liveb = wb != 0
        n_app = jnp.sum(liveb.astype(jnp.int32))
        arena = {"rkeys": ak, "rvals": av, "rw": aw,
                 "rcount": state["rcount"], "gen": state["gen"]}
        arena = jax.lax.cond(arena["rcount"] + n_app > R,
                             compact_arena, lambda s: s, arena)
        rank = jnp.cumsum(liveb.astype(jnp.int32)) - 1
        pos = jnp.where(liveb, arena["rcount"] + rank, R)
        rkeys = arena["rkeys"].at[pos].set(kb, mode="drop")
        rvals = arena["rvals"].at[pos].set(vb, mode="drop")
        rw = arena["rw"].at[pos].set(wb, mode="drop")
        rcount = arena["rcount"] + n_app
        gen = arena["gen"]
        err = err | (rcount > R)
    else:
        gen = state["gen"]

    out = DeviceDelta(
        jnp.concatenate([o.keys for o in outs]),
        jnp.concatenate([o.values for o in outs]),
        jnp.concatenate([o.weights for o in outs]),
    )
    new_state = {"lval": lval, "lw": lw, "rkeys": rkeys, "rvals": rvals,
                 "rw": rw, "rcount": rcount, "gen": gen, "error": err}
    return out, new_state


# -- KnnIndex (SURVEY.md §2 item 14: vmapped cosine + Pallas top-k) --------

def knn_state(op, q_spec: Spec, d_spec: Spec) -> dict:
    Q, D = q_spec.key_space, d_spec.key_space
    dim, k = op.dim, op.k
    # vectors store at the SOURCE spec dtype: bf16 embeddings halve both
    # HBM residency and the host->device transfer per insert tick (the
    # bandwidth-bound cost of config 4) at ~1e-3 relative score error —
    # normalization and the scoring matmuls still accumulate in f32
    return {
        "qvec": jnp.zeros((Q, dim), q_spec.value_dtype),
        "qlive": jnp.zeros((Q,), jnp.bool_),
        "dvec": jnp.zeros((D, dim), d_spec.value_dtype),
        "dlive": jnp.zeros((D,), jnp.bool_),
        "emitted": jnp.zeros((Q, k, 2), jnp.float32),
        "em_has": jnp.zeros((Q,), jnp.bool_),
    }


def _norm_rows(v):
    n = jnp.sqrt(jnp.sum(v * v, axis=-1, keepdims=True))
    return jnp.where(n > 0, v / jnp.maximum(n, 1e-30), 0.0)


def _fold_vectors(vec, live, delta):
    """Retract-then-insert fold of vector deltas into a dense table (an
    in-tick update = retract + insert resolves to the insert)."""
    C = delta.capacity
    cap = vec.shape[0]
    ins = jnp.where(delta.weights > 0, delta.keys, cap)
    ret = jnp.where(delta.weights < 0, delta.keys, cap)
    if vec.dtype == jnp.int8:
        # int8 tables receive PRE-normalized, pre-quantized rows
        # (workloads/knn.quantize_int8): store raw — renormalizing a
        # round(unit*127) row would truncate it to zeros at int8
        vals8 = jnp.asarray(delta.values, jnp.int8)
        vec = vec.at[ins].set(vals8, mode="drop")
    else:
        # normalize in f32 regardless of storage dtype, store at table
        # dtype
        vals = _norm_rows(jnp.asarray(delta.values, jnp.float32))
        vec = vec.at[ins].set(jnp.asarray(vals, vec.dtype), mode="drop")
    live = live.at[ret].set(False, mode="drop").at[ins].set(True, mode="drop")
    return vec, live


def _lower_knn(op, node: Node, state, ins) -> Tuple[DeviceDelta, dict]:
    from reflow_tpu.kernels.topk import (NEG, chunked_corpus_topk,
                                         score_form, topk)

    dq, dd = ins
    if dq is None:
        dq = DeviceDelta.empty(node.inputs[0].spec)
    if dd is None:
        dd = DeviceDelta.empty(node.inputs[1].spec)
    Q = node.inputs[0].spec.key_space
    D = node.inputs[1].spec.key_space
    k = op.k

    # an insert whose doc id is ALREADY live is an in-place update: its
    # stale score may sit in a query's emitted top-k, and the
    # incremental merge would keep treating it as a valid candidate —
    # updates therefore rescan, exactly like retractions (checked
    # against the PRE-fold live mask; padding rows have weight 0)
    doc_update = jnp.any((dd.weights > 0) & state["dlive"][dd.keys])

    qvec, qlive = _fold_vectors(state["qvec"], state["qlive"], dq)
    dvec, dlive = _fold_vectors(state["dvec"], state["dlive"], dd)
    emitted, em_has = state["emitted"], state["em_has"]
    prec = (jax.lax.Precision.HIGHEST if op.precision == "highest"
            else jax.lax.Precision.DEFAULT)

    # fresh doc-insert and query-retract ticks take the incremental
    # merge (a retracted query just stops emitting); query
    # inserts/updates, doc retractions and doc UPDATES rescan the
    # corpus (chunked, MXU)
    need_full = (jnp.any(dd.weights < 0) | jnp.any(dq.weights > 0)
                 | doc_update)

    def full_path(_):
        return chunked_corpus_topk(qvec, dvec, dlive, k, op.scan_chunk,
                                   precision=prec)

    def incr_path(_):
        # current top-k rows stay valid (no retractions): merge them with
        # scores against just the delta docs
        em_ids = emitted[:, :, 0].astype(jnp.int32)            # [Q, k]
        em_vals = jnp.where(em_has[:, None] & (em_ids >= 0),
                            emitted[:, :, 1], NEG)
        di = dd.keys                                           # [Cd]
        s_new = jnp.dot(score_form(qvec), score_form(dvec[di]).T,
                        preferred_element_type=jnp.float32,
                        precision=prec)                        # [Q, Cd]
        s_new = jnp.where((dd.weights > 0)[None, :], s_new, NEG)
        cand_vals = jnp.concatenate([em_vals, s_new], axis=1)
        cand_ids = jnp.concatenate(
            [em_ids, jnp.broadcast_to(di, (Q, di.shape[0]))], axis=1)
        # order candidates by id so topk's first-index tie-break matches
        # the oracle's lowest-doc-id rule on exact score ties
        order = jnp.argsort(cand_ids, axis=1, stable=True)
        cand_ids = jnp.take_along_axis(cand_ids, order, axis=1)
        cand_vals = jnp.take_along_axis(cand_vals, order, axis=1)
        vals, sel = topk(cand_vals, k)
        ids = jnp.take_along_axis(cand_ids, sel, axis=1)
        return vals, ids

    vals, ids = jax.lax.cond(need_full, full_path, incr_path, None)
    ids = jnp.where(vals <= NEG, -1, ids)
    new_row = jnp.stack([ids.astype(jnp.float32), vals], axis=-1)  # [Q,k,2]

    changed = jnp.any(new_row != emitted, axis=(1, 2))
    ins_m = qlive & (~em_has | changed)
    ret_m = em_has & (~qlive | changed)
    qkeys = jnp.arange(Q, dtype=jnp.int32)
    out = DeviceDelta(
        keys=jnp.concatenate([qkeys, qkeys]),
        values=jnp.concatenate([emitted, new_row]),
        weights=jnp.concatenate(
            [-ret_m.astype(jnp.int32), ins_m.astype(jnp.int32)]),
    )
    new_emitted = jnp.where(ins_m[:, None, None], new_row, emitted)
    new_has = jnp.where(ins_m, True, jnp.where(ret_m & ~qlive, False, em_has))
    return out, {"qvec": qvec, "qlive": qlive, "dvec": dvec, "dlive": dlive,
                 "emitted": new_emitted, "em_has": new_has}


# -- dispatch --------------------------------------------------------------

_LOWERINGS = {
    "map": _lower_map,
    "filter": _lower_filter,
    "groupby": _lower_groupby,
    "union": _lower_union,
    "reduce": _lower_reduce,
    "join": _lower_join,
    "knn": _lower_knn,
}


def lower_node(node: Node, state, ins: Sequence[DeviceDelta]
               ) -> Tuple[DeviceDelta, Optional[dict]]:
    return _LOWERINGS[node.op.kind](node.op, node, state, ins)
