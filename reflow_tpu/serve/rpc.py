"""Ingestion RPC: ``IngestFrontend.submit() -> Ticket`` over the wire.

The producer half of "Multi-process deployment" (docs/guide.md).
Replication already crosses processes (``net/client.py`` /
``net/server.py``); this module does the same for *ingestion* so a
producer can live in its own OS process and still get the exact
frontend contract: submit a batch, hold a ticket, learn its fate —
APPLIED (with ``tick``/``lsn``), DEDUPED, REJECTED or SHED.

Wire protocol (pickled tuples over ``net/framing.py``)::

    ("hello", producer, in_doubt_ids) -> ("ok", {graph, epoch, tick,
                                                 admitted})
    ("submit",) + SubmitReq           -> ("ack",) + SubmitAck
    ("resolve",) + TicketResolve      -> ("ok", {batch_id: SubmitAck})
    ("ping",)                         -> ("ok", {graph, tick, lsn,
                                                 state})
    ("view", sink_name)               -> ("ok", tick, {key: weight})
    anything else                     -> ("err", text)

Exactly-once across reconnects is the point of the handshake. A
producer that dies mid-submit cannot know whether its last batch was
admitted, so on (re)connect it sends every in-doubt ``batch_id`` with
``hello``; the server answers with the subset its frontend's dedup
mirror remembers. Either way the producer simply *resubmits* the same
ids: an admitted id resolves DEDUPED against the mirror (one fold
total), an unadmitted one folds exactly once. The handshake makes the
outcome observable — ``RemoteProducer.last_hello["admitted"]`` — and
lets tests pin the invariant; it is never required for safety, which
rests on the mirror alone.

Ticket identity does NOT survive the server's ticket-table bound
(``REFLOW_RPC_TICKETS``): an evicted in-flight ticket resolves as
``"unknown"`` and the producer resubmits — again safe by dedup. A
promoted replacement leader starts with an empty table but a recovered
mirror, so the same path covers failover.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, NamedTuple, Optional, Tuple

from reflow_tpu.net.backoff import ReconnectPolicy
from reflow_tpu.net.framing import TransportError, WireTimeout
from reflow_tpu.net.transport import Conn, Transport
from reflow_tpu.obs import trace as _trace
from reflow_tpu.serve.tickets import (
    APPLIED, DEDUPED, REJECTED, SHED, FrontendClosed, TicketResult)
from reflow_tpu.utils.config import env_float, env_int
from reflow_tpu.utils.runtime import named_lock

__all__ = ["SubmitReq", "SubmitAck", "TicketResolve", "RpcIngestServer",
           "RemoteProducer", "RemoteTicket"]

#: accept/recv poll slice (matches net/server.py): how often blocked
#: server threads re-check the stop flag
_POLL_S = 0.2

#: ack states that end a ticket's life on the client
_TERMINAL = (APPLIED, DEDUPED, REJECTED, SHED)


class SubmitReq(NamedTuple):
    """One producer submission as it crosses the wire.

    ``cause`` is the optional causality token minted at the producer
    (``obs.trace.mint_cause``); its presence IS the sampling decision —
    the server adopts it instead of re-rolling, so every process
    records the same 1-in-N writes. Trailing + defaulted and trimmed
    when None (:func:`_trim`) so untraced requests stay byte-identical
    to the pre-trace wire protocol."""

    batch_id: str
    source: str                    # source/loop node name on the graph
    payload: Any                   # host DeltaBatch (picklable)
    timeout_s: Optional[float] = None
    cause: Optional[str] = None


class SubmitAck(NamedTuple):
    """Server's answer to a submit (or one entry of a resolve reply).

    ``state`` is a ticket status (terminal), ``"pending"`` (admitted,
    fate undecided — resolve later), ``"retry"`` (frontend closed or
    pump crashed mid-admission; resubmit after backoff) or
    ``"unknown"`` (server holds no ticket for this id; resubmit).
    ``result`` carries the :class:`TicketResult` fields when terminal.
    """

    batch_id: str
    state: str
    result: Optional[tuple] = None
    reason: Optional[str] = None
    cause: Optional[str] = None    # echo of the request token (traced)


class TicketResolve(NamedTuple):
    """Poll the fate of outstanding tickets, server-side long-poll up
    to ``wait_s`` (capped by ``REFLOW_RPC_RESOLVE_WAIT_S``)."""

    batch_ids: tuple
    wait_s: float = 0.0


def _trim(fields: tuple) -> tuple:
    """Drop exactly one trailing None before a frame hits the wire —
    the ``Shipment`` compat pattern (net/client.py): an unstamped
    request/ack pickles byte-identically to the pre-``cause`` protocol,
    while the receiving NamedTuple's default fills the gap."""
    if fields and fields[-1] is None:
        fields = fields[:-1]
    return fields


def _ticket_cause(ticket) -> Optional[str]:
    """The causality token riding a server-side ticket's trace context
    (None for unsampled/untraced tickets)."""
    return getattr(getattr(ticket, "trace", None), "cause", None)


def _result_fields(res: TicketResult) -> tuple:
    return (res.status, res.batch_id, res.tick, res.coalesced_with,
            res.reason, res.lsn)


def _result_from(fields) -> TicketResult:
    return TicketResult(*fields)


def _frontend_of(handle):
    """Accept an ``IngestFrontend`` or anything carrying one (a
    ``GraphHandle`` from the serve tier exposes ``.frontend``)."""
    return getattr(handle, "frontend", handle)


class RpcIngestServer:
    """Host one frontend's ingestion endpoint over ``transport``.

    Same shape as :class:`~reflow_tpu.net.server.ReplicaServer`: an
    accept-loop thread plus one handler thread per connection, so one
    producer's blocked admission (``policy="block"`` backpressure)
    never stalls another's. ``start()`` binds (port 0 under TCP — the
    OS assigns) and ``address`` reports the dialable address.
    """

    def __init__(self, handle, transport: Transport, *,
                 max_tickets: Optional[int] = None) -> None:
        self.handle = handle
        self.transport = transport
        self.max_tickets = (max_tickets if max_tickets is not None
                            else env_int("REFLOW_RPC_TICKETS"))
        self._submit_cap = env_float("REFLOW_RPC_SUBMIT_TIMEOUT_S")
        self._resolve_cap = env_float("REFLOW_RPC_RESOLVE_WAIT_S")
        self._listener = None
        self._stop = threading.Event()
        self._accept_thread = None
        self._lock = named_lock("serve.rpc.server")
        self._conns: list = []
        self._handlers: list = []
        self._tickets: "OrderedDict[str, Any]" = OrderedDict()
        self.connections_total = 0
        self.requests_total = 0
        self.submits_total = 0
        self.evicted_tickets = 0

    # the frontend is re-read per request: a tier ``rebind()`` revives
    # the same frontend object in place, and a ``GraphHandle`` always
    # names the current one — no server restart across failover rebinds
    @property
    def frontend(self):
        return _frontend_of(self.handle)

    @property
    def address(self):
        if self._listener is None:
            raise TransportError("server not started")
        return self._listener.address

    def start(self) -> "RpcIngestServer":
        if self._accept_thread is not None:
            return self
        self._listener = self.transport.listen()
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept(timeout_s=_POLL_S)
            except WireTimeout:
                continue
            except TransportError:
                return  # listener closed under us
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self.connections_total += 1
                t = threading.Thread(
                    target=self._serve_conn, args=(conn,),
                    name=f"rpc-serve/{self.connections_total}",
                    daemon=True)
                self._conns.append(conn)
                self._handlers.append(t)
            t.start()

    def _serve_conn(self, conn: Conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv_msg(timeout_s=_POLL_S)
                except WireTimeout:
                    continue
                except TransportError:
                    return
                try:
                    reply = self._dispatch(msg)
                except TransportError:
                    raise
                except Exception as e:  # noqa: BLE001 - a poisoned
                    # request must not kill the endpoint for the others
                    reply = ("err", f"{type(e).__name__}: {e}")
                try:
                    conn.send_msg(reply)
                except TransportError:
                    return
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- ops -----------------------------------------------------------

    def _dispatch(self, msg):
        if not isinstance(msg, tuple) or not msg:
            return ("err", f"malformed request {type(msg).__name__}")
        self.requests_total += 1
        op, args = msg[0], msg[1:]
        if op == "hello":
            return self._op_hello(*args)
        if op == "submit":
            return ("ack",) + _trim(tuple(self._op_submit(
                SubmitReq(*args))))
        if op == "resolve":
            return ("ok", self._op_resolve(TicketResolve(*args)))
        if op == "ping":
            return ("ok", self._status())
        if op == "flush":
            self.frontend.flush(timeout=args[0] if args else None)
            return ("ok",)
        if op == "view":
            fe = self.frontend
            sched = fe.sched
            return ("ok", sched._tick, dict(sched.view(args[0])))
        return ("err", f"unknown op {op!r}")

    def _status(self) -> dict:
        fe = self.frontend
        sched = fe.sched
        wal = getattr(sched, "wal", None)
        return {
            "graph": getattr(sched.graph, "name", "flow"),
            "tick": sched._tick,
            "lsn": wal.last_lsn() if wal is not None else None,
            "epoch": getattr(sched, "epoch", 0),
            "state": fe._state,
        }

    def _op_hello(self, producer, in_doubt_ids):
        """The dedup handshake: which of the producer's in-doubt ids
        does the frontend's mirror already remember? The reply also
        piggybacks this server's clock anchor (inside the dict — the
        reply stays a 2-tuple for old clients) so producer-side spans
        can be displayed on the leader's wall axis post-mortem."""
        from reflow_tpu.obs.wire import clock_anchor
        fe = self.frontend
        sched = fe.sched
        return ("ok", {
            "graph": getattr(sched.graph, "name", "flow"),
            "epoch": getattr(sched, "epoch", 0),
            "tick": sched._tick,
            "admitted": fe.admitted_ids(in_doubt_ids),
            "anchor": clock_anchor(),
        })

    def _source_node(self, name: str):
        fe = self.frontend
        for node in fe.sched.graph.nodes:
            if node.name == name and node.kind in ("source", "loop"):
                return node
        raise KeyError(f"no source/loop node named {name!r}")

    def _op_submit(self, req: SubmitReq) -> SubmitAck:
        self.submits_total += 1
        t0 = time.perf_counter()
        source = self._source_node(req.source)
        timeout = self._submit_cap
        if req.timeout_s is not None:
            timeout = min(timeout, req.timeout_s)
        try:
            # the wire decision rides the token: a present ``cause``
            # means the producer sampled this write, so the frontend
            # adopts it (and its sampling bit) instead of re-rolling —
            # every process then records the same writes
            ticket = self.frontend.submit(
                source, req.payload, batch_id=req.batch_id,
                timeout=timeout, cause=req.cause,
                sampled=(req.cause is not None))
        except FrontendClosed as e:
            # closed OR pump crashed: either way the producer holds the
            # payload and the mirror holds the truth — tell it to retry
            return SubmitAck(req.batch_id, "retry",
                             reason=f"{type(e).__name__}: {e}",
                             cause=req.cause)
        if _trace.ENABLED and req.cause is not None:
            _trace.evt("rpc_admit", t0, time.perf_counter() - t0,
                       track="rpc-server",
                       args={"batch_id": req.batch_id,
                             "cause": req.cause})
        return self._ack_of(ticket)

    def _ack_of(self, ticket) -> SubmitAck:
        cause = _ticket_cause(ticket)
        if ticket.done():
            try:
                res = ticket.result(timeout=0)
            except FrontendClosed as e:
                return SubmitAck(ticket.batch_id, "retry",
                                 reason=f"{type(e).__name__}: {e}",
                                 cause=cause)
            with self._lock:
                self._tickets.pop(ticket.batch_id, None)
            return SubmitAck(ticket.batch_id, res.status,
                             result=_result_fields(res), cause=cause)
        with self._lock:
            self._tickets[ticket.batch_id] = ticket
            self._tickets.move_to_end(ticket.batch_id)
            while len(self._tickets) > self.max_tickets:
                self._evict_one()
        return SubmitAck(ticket.batch_id, "pending", cause=cause)

    def _evict_one(self) -> None:
        # caller holds the lock; prefer dropping a resolved ticket (its
        # fate was deliverable) over an in-flight one (which will
        # resolve "unknown" -> resubmit -> DEDUPED, still exactly-once)
        for bid, t in self._tickets.items():
            if t.done():
                del self._tickets[bid]
                return
        self._tickets.popitem(last=False)
        self.evicted_tickets += 1

    def _op_resolve(self, req: TicketResolve) -> Dict[str, tuple]:
        wait_s = min(max(req.wait_s, 0.0), self._resolve_cap)
        deadline = time.perf_counter() + wait_s
        while True:
            out, pending = {}, []
            with self._lock:
                tickets = {b: self._tickets.get(b)
                           for b in req.batch_ids}
            for bid, t in tickets.items():
                if t is None:
                    out[bid] = _trim(tuple(SubmitAck(
                        bid, "unknown",
                        reason="no ticket on this server; resubmit")))
                elif t.done():
                    out[bid] = _trim(tuple(self._ack_of(t)))
                else:
                    pending.append(t)
                    out[bid] = _trim(tuple(SubmitAck(
                        bid, "pending", cause=_ticket_cause(t))))
            remaining = deadline - time.perf_counter()
            if not pending or remaining <= 0 or self._stop.is_set():
                return out
            # long-poll one slice on the first undecided ticket; loop
            # re-reads them all (another may have resolved meanwhile)
            pending[0]._event.wait(min(remaining, _POLL_S))

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns = list(self._conns)
            handlers = list(self._handlers)
        for c in conns:
            c.close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        for h in handlers:
            h.join(timeout=5.0)


class RemoteTicket:
    """Client-side future for one remote submission.

    Unlike an in-process :class:`~reflow_tpu.serve.tickets.Ticket`,
    this one RETAINS its payload until the fate is terminal: a link
    reset in the ack window means the producer cannot know whether the
    batch was admitted, and the only safe move is to resubmit the same
    ``batch_id`` after reconnect (the server's dedup mirror collapses
    the duplicate).
    """

    __slots__ = ("batch_id", "source", "payload", "timeout_s",
                 "submits", "link_gen", "cause", "_producer", "_result")

    def __init__(self, producer: "RemoteProducer", batch_id: str,
                 source: str, payload, timeout_s: Optional[float],
                 cause: Optional[str] = None):
        self.batch_id = batch_id
        self.source = source
        self.payload = payload
        self.timeout_s = timeout_s
        self.submits = 0       # wire submits (resubmits = submits - 1)
        self.link_gen = -1     # dial generation the last submit rode
        #: causality token for a sampled submission — minted ONCE, so
        #: every resubmit of this batch rides the same token and the
        #: post-failover chain still joins on string equality
        self.cause = cause
        self._producer = producer
        self._result: Optional[TicketResult] = None

    def done(self) -> bool:
        return self._result is not None

    def result(self, timeout: Optional[float] = None) -> TicketResult:
        """Drive the producer's link until this ticket is terminal.
        Raises ``TimeoutError`` if the fate stays undecided — the
        ticket stays live and a later call resumes where this left
        off."""
        res = self._producer._await(self, timeout)
        if res is None:
            raise TimeoutError(
                f"remote ticket {self.batch_id!r} unresolved after "
                f"{timeout}s (link {self._producer.conn_state})")
        return res


class RemoteProducer:
    """Mirror of the ``IngestFrontend.submit() -> Ticket`` surface over
    a framed transport connection.

    Owns the unreliable-link lifecycle the way
    :class:`~reflow_tpu.net.client.RemoteFollower` does for shipping:
    :class:`ReconnectPolicy` gates every re-dial, a down link never
    raises out of :meth:`submit` (the ticket simply stays pending), and
    every fresh connection re-runs the ``hello`` dedup handshake with
    all in-doubt ids before any resubmission.

    ``retarget(address)`` swings the producer at a different endpoint
    (the promoted leader after a failover); in-doubt tickets are then
    resubmitted there, where the recovered dedup mirror keeps them
    exactly-once.
    """

    def __init__(self, transport: Transport, address, *,
                 name: str = "producer",
                 policy: Optional[ReconnectPolicy] = None,
                 io_timeout_s: Optional[float] = None) -> None:
        self.transport = transport
        self.address = address
        self.name = name
        self.policy = policy if policy is not None \
            else ReconnectPolicy(name)
        self.io_timeout_s = (io_timeout_s if io_timeout_s is not None
                             else env_float("REFLOW_RPC_IO_TIMEOUT_S"))
        self._lock = named_lock("serve.rpc.producer")
        self._conn: Optional[Conn] = None
        self._gen = 0                  # successful-dial generation
        self._seq = 0
        self._pending: "OrderedDict[str, RemoteTicket]" = OrderedDict()
        #: server's answer to the last hello (graph/epoch/tick/admitted)
        self.last_hello: Optional[dict] = None
        #: server clock anchor from the last hello (+ rtt_s /
        #: wall_offset_s), when the server sends one; display-only —
        #: never used for ordering
        self.anchor: Optional[dict] = None
        self.submits_total = 0
        self.resubmits_total = 0
        self.reconnects_total = 0
        self.link_failures = 0
        self.deduped_total = 0

    @property
    def conn_state(self) -> str:
        return self.policy.state

    def transport_snapshot(self) -> dict:
        snap = self.policy.snapshot()
        snap["address"] = str(self.address)
        snap["in_doubt"] = len(self._pending)
        return snap

    def in_doubt_ids(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._pending)

    # -- the frontend surface ------------------------------------------

    def submit(self, source, batch, *, batch_id: Optional[str] = None,
               timeout: Optional[float] = None) -> RemoteTicket:
        """Submit one host batch to the remote frontend. Returns a
        :class:`RemoteTicket` immediately; a down link just leaves it
        pending (``result()`` keeps pushing). ``source`` is a graph
        ``Node`` or its name."""
        src = getattr(source, "name", source)
        with self._lock:
            if batch_id is None:
                batch_id = f"{self.name}-{self._seq}"
                self._seq += 1
            cause = None
            if _trace.ENABLED and _trace.sample():
                # sampling is decided HERE, before any ticket exists on
                # the server; the token carries the decision downstream
                epoch = (self.last_hello or {}).get("epoch", 0)
                cause = _trace.mint_cause(self.name, epoch)
            ticket = RemoteTicket(self, batch_id, src, batch, timeout,
                                  cause=cause)
            self._pending[batch_id] = ticket
            self._ensure_link()
            self._push(ticket)
        return ticket

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every outstanding ticket is terminal."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        while True:
            with self._lock:
                t = next(iter(self._pending.values()), None)
            if t is None:
                return
            left = (None if deadline is None
                    else deadline - time.perf_counter())
            if left is not None and left <= 0:
                raise TimeoutError(
                    f"{len(self.in_doubt_ids())} tickets still in "
                    f"doubt after {timeout}s")
            t.result(left)

    def retarget(self, address) -> None:
        """Point at a new endpoint (post-failover). The live link is
        torn down; the next pump re-dials, re-runs hello with every
        in-doubt id and resubmits them there."""
        with self._lock:
            self.address = address
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            self.policy.failed()  # schedules a (short, first) backoff

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    # -- link machinery ------------------------------------------------

    def _fail(self, err: Exception) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self.link_failures += 1
        self.policy.failed()

    def _ensure_link(self) -> bool:
        """Dial + hello handshake if the link is down and a backoff
        window is open. Caller holds the lock. True if live."""
        if self._conn is not None:
            return True
        if not self.policy.due():
            return False
        t0 = time.perf_counter()
        try:
            conn = self.transport.connect(self.address)
            conn.send_msg(("hello", self.name, tuple(self._pending)),
                          self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError as e:
            self._fail(e)
            if _trace.ENABLED:
                _trace.evt("net_reconnect", t0,
                           time.perf_counter() - t0,
                           track=f"rpc/{self.name}",
                           args={"ok": False, "error": str(e)[:120],
                                 "state": self.policy.state})
            return False
        if not (isinstance(resp, tuple) and len(resp) == 2
                and resp[0] == "ok"):
            conn.close()
            self._fail(TransportError(f"bad hello response {resp!r}"))
            return False
        recovered = self.policy.ok()
        if recovered:
            self.reconnects_total += 1
        self._conn = conn
        self._gen += 1
        self.last_hello = dict(resp[1])
        anchor = self.last_hello.get("anchor")
        if isinstance(anchor, dict):
            # pre-anchor servers omit the key; newer ones piggyback a
            # clock anchor so this producer's spans can be shown on the
            # leader's wall axis (error bounded by rtt/2)
            rtt = time.perf_counter() - t0
            anchor = dict(anchor)
            anchor["rtt_s"] = rtt
            anchor["wall_offset_s"] = anchor.get("wall", 0.0) - \
                (time.time() - rtt / 2.0)
            self.anchor = anchor
        if _trace.ENABLED:
            _trace.evt("net_reconnect", t0, time.perf_counter() - t0,
                       track=f"rpc/{self.name}",
                       args={"ok": True, "recovered": recovered,
                             "in_doubt": len(self._pending)})
        return True

    def _roundtrip(self, msg: tuple,
                   cause: Optional[str] = None) -> Any:
        conn = self._conn
        if conn is None:
            return None
        t0 = time.perf_counter()
        try:
            conn.send_msg(msg, self.io_timeout_s)
            resp = conn.recv_msg(self.io_timeout_s)
        except TransportError as e:
            self._fail(e)
            if _trace.ENABLED:
                args = {"op": msg[0], "ok": False,
                        "error": str(e)[:120]}
                if cause is not None:
                    args["cause"] = cause
                _trace.evt("net_send", t0, time.perf_counter() - t0,
                           track=f"rpc/{self.name}", args=args)
            return None
        self.policy.ok()
        if _trace.ENABLED:
            args = {"op": msg[0], "ok": True}
            if cause is not None:
                args["cause"] = cause
            _trace.evt("net_send", t0, time.perf_counter() - t0,
                       track=f"rpc/{self.name}", args=args)
        return resp

    def _push(self, ticket: RemoteTicket) -> None:
        """One wire submit for ``ticket`` (caller holds the lock; link
        may drop mid-call — the ticket then stays in doubt)."""
        if self._conn is None or ticket.done():
            return
        if ticket.submits > 0:
            self.resubmits_total += 1
        ticket.submits += 1
        ticket.link_gen = self._gen
        req = SubmitReq(ticket.batch_id, ticket.source, ticket.payload,
                        ticket.timeout_s, ticket.cause)
        self.submits_total += 1
        t0 = time.perf_counter()
        resp = self._roundtrip(("submit",) + _trim(tuple(req)),
                               cause=ticket.cause)
        if _trace.ENABLED and ticket.cause is not None:
            # the producer's end of the chain: submit sent -> ack (or
            # link loss) — freshness decomposition anchors ack->deliver
            # at this span's start
            _trace.evt("producer_submit", t0,
                       time.perf_counter() - t0,
                       track=f"rpc/{self.name}",
                       args={"batch_id": ticket.batch_id,
                             "cause": ticket.cause,
                             "submits": ticket.submits,
                             "ok": resp is not None})
        if isinstance(resp, tuple) and resp and resp[0] == "ack":
            self._apply_ack(ticket, SubmitAck(*resp[1:]))
        elif isinstance(resp, tuple) and resp and resp[0] == "err":
            # a protocol rejection (unknown source, malformed batch) is
            # deterministic — retrying the same request cannot succeed,
            # so resolve the ticket rather than park it in doubt
            ticket._result = TicketResult(REJECTED, ticket.batch_id,
                                          reason=str(resp[1]))
            ticket.payload = None
            self._pending.pop(ticket.batch_id, None)

    def _apply_ack(self, ticket: RemoteTicket, ack: SubmitAck) -> None:
        # caller holds the lock
        if ack.state in _TERMINAL:
            ticket._result = _result_from(ack.result)
            ticket.payload = None  # drop the retained bytes
            self._pending.pop(ticket.batch_id, None)
            if ack.state == DEDUPED:
                self.deduped_total += 1
        elif ack.state == "unknown":
            # the server holds no ticket (evicted, or a promoted
            # replacement): resubmit on the next pump — the dedup
            # mirror keeps the duplicate from folding twice
            ticket.link_gen = -1
        elif ack.state == "retry":
            # frontend closed / pump crashed mid-admission: back off a
            # touch, then resubmit against the (revived or promoted)
            # frontend on a later pump
            ticket.link_gen = -1
        # "pending": nothing to do — resolve polls will decide it

    def _pump(self, wait_s: float) -> None:
        """One client pump: ensure the link, (re)submit anything the
        current connection hasn't carried, then long-poll resolve."""
        with self._lock:
            if not self._ensure_link():
                return
            for t in list(self._pending.values()):
                if t.link_gen != self._gen:
                    self._push(t)
                    if self._conn is None:
                        return
            ids = tuple(self._pending)
            if not ids:
                return
            resp = self._roundtrip(
                ("resolve",) + tuple(TicketResolve(ids, wait_s)))
            if not (isinstance(resp, tuple) and len(resp) == 2
                    and resp[0] == "ok"):
                return
            for bid, fields in resp[1].items():
                t = self._pending.get(bid)
                if t is not None:
                    self._apply_ack(t, SubmitAck(*fields))

    def _await(self, ticket: RemoteTicket,
               timeout: Optional[float]) -> Optional[TicketResult]:
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        while True:
            if ticket.done():
                return ticket._result
            left = (None if deadline is None
                    else deadline - time.perf_counter())
            if left is not None and left <= 0:
                return None
            if self._conn is None:
                # link down: sleep out (a slice of) the backoff window
                # instead of spinning on due()
                nap = max(self.policy.seconds_until_due(), 0.01)
                if left is not None:
                    nap = min(nap, left)
                time.sleep(min(nap, _POLL_S))
            wait = _POLL_S if left is None else min(left, _POLL_S)
            self._pump(wait)
