"""Admission budget: the in-flight byte bound, injectable and shareable.

PR 2's ``IngestFrontend`` carried its byte budget inside
``SourceQueues`` (a bare ``max_bytes``); the serving tier needs ONE
budget spanning many graphs, with per-graph **floors** (guaranteed
bytes) and **ceilings** (caps). This module is that budget, factored so
both deployments inject the same object:

- standalone frontend: ``AdmissionBudget(max_bytes).register("solo")``
  (what the frontend builds for itself when none is injected);
- ``ServeTier``: one ``AdmissionBudget``, one ``register(name,
  floor=..., ceiling=...)`` per graph.

Like ``SourceQueues`` this is a pure data structure: every method is
called with the owning lock held — the frontend's own lock standalone,
the tier's shared lock when graphs share a budget. (Sharing an
``AdmissionBudget`` across frontends therefore REQUIRES sharing their
lock; the tier guarantees that by construction.)

Floors are *reservations*, not partitions: graph ``g``'s admission is
granted from ``total - sum(other graphs' unused floors)``, so a hot
tenant can burst into shared headroom but can never push a sibling
below its guaranteed floor — the unused part of every floor is held
back from everyone else. Ceilings cap one graph's usage outright.
The guarantee is stable under churn: as a graph uses its floor, its
reservation shrinks exactly in step with the bytes it takes from the
shared pool, and a release returns bytes and reservation together.

Producer wakeups: each frontend attaches its not-full condition to its
share; any release (a committed macro-tick, a shed) notifies EVERY
attached condition, because freed global bytes may unblock a producer
on a different graph.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["AdmissionBudget", "BudgetShare"]


class BudgetShare:
    """One graph's slice of an :class:`AdmissionBudget`.

    The frontend-facing surface: ``room_for`` / ``fits_alone`` answer
    admission, ``acquire`` / ``release`` move bytes, ``attach`` /
    ``notify_room`` wire producer wakeups. ``used`` / ``peak`` are the
    graph's live and high-water byte occupancy.
    """

    __slots__ = ("budget", "name", "floor", "ceiling", "used", "peak",
                 "_conds")

    def __init__(self, budget: "AdmissionBudget", name: str, floor: int,
                 ceiling: int):
        self.budget = budget
        self.name = name
        self.floor = floor
        self.ceiling = ceiling
        self.used = 0
        self.peak = 0
        self._conds: List[threading.Condition] = []

    # -- admission ---------------------------------------------------------

    def room_for(self, nbytes: int) -> bool:
        return self.budget._room_for(self, nbytes)

    def fits_alone(self, nbytes: int) -> bool:
        """Could this batch EVER be admitted (every queue empty)? False
        means the batch alone exceeds what this graph can hold — the
        frontend rejects instead of shedding for it."""
        return nbytes <= self.max_alone

    @property
    def max_alone(self) -> int:
        """The largest in-flight total this graph is guaranteed to be
        able to reach: its ceiling, clipped by the headroom left once
        every sibling's full floor is reserved."""
        return self.budget._max_alone(self)

    # -- accounting --------------------------------------------------------

    def acquire(self, nbytes: int) -> None:
        self.used += nbytes
        self.peak = max(self.peak, self.used)
        self.budget.used += nbytes
        self.budget.peak = max(self.budget.peak, self.budget.used)

    def release(self, nbytes: int) -> None:
        self.used -= nbytes
        self.budget.used -= nbytes

    # -- producer wakeups --------------------------------------------------

    def attach(self, cond: threading.Condition) -> None:
        """Register a not-full condition to wake on any release. All
        attached conditions must be built on the budget's owning lock."""
        self._conds.append(cond)

    def notify_room(self) -> None:
        """Wake blocked producers budget-wide (caller holds the owning
        lock): freed bytes are global, so a release by this graph may
        unblock a producer waiting on a sibling's frontend."""
        self.budget.notify_room()


class AdmissionBudget:
    """Global in-flight byte budget with per-graph floors/ceilings.

    ``total_bytes`` bounds the sum of every registered share's usage.
    ``register`` validates that floors stay reservable (their sum can't
    exceed the total) and that each ceiling is at least its floor.
    """

    def __init__(self, total_bytes: int):
        if total_bytes <= 0:
            raise ValueError(f"total_bytes must be positive, "
                             f"got {total_bytes}")
        self.total_bytes = total_bytes
        self.used = 0
        self.peak = 0
        self._shares: Dict[str, BudgetShare] = {}
        self._metric_keys: list = []  # (registry, prefix) published

    # -- registration ------------------------------------------------------

    def register(self, name: str, *, floor: int = 0,
                 ceiling: Optional[int] = None) -> BudgetShare:
        if name in self._shares:
            raise ValueError(f"budget share {name!r} already registered")
        ceiling = self.total_bytes if ceiling is None else ceiling
        if not 0 <= floor <= ceiling:
            raise ValueError(
                f"need 0 <= floor <= ceiling, got floor={floor} "
                f"ceiling={ceiling} for {name!r}")
        if ceiling > self.total_bytes:
            raise ValueError(
                f"ceiling {ceiling} for {name!r} exceeds the "
                f"{self.total_bytes}B budget")
        reserved = sum(s.floor for s in self._shares.values())
        if reserved + floor > self.total_bytes:
            raise ValueError(
                f"floor {floor} for {name!r} is not reservable: "
                f"{reserved}B of the {self.total_bytes}B budget is "
                f"already promised to other graphs")
        share = BudgetShare(self, name, floor, ceiling)
        self._shares[name] = share
        return share

    def resize(self, name: str, *, floor: Optional[int] = None,
               ceiling: Optional[int] = None) -> BudgetShare:
        """Live-retune one share's floor/ceiling under the owning lock —
        the control plane's rebalancing actuator.

        Validation matches :meth:`register`: the new floor must stay
        reservable alongside every sibling's floor, and the ceiling must
        stay within the total. Shrinking a floor returns its reservation
        to the shared pool immediately (siblings' ``max_alone`` grows);
        growing one re-checks reservability. A ceiling below the share's
        CURRENT usage is legal: nothing is evicted, but no new admission
        happens until usage drains back under it. Loosened constraints
        may unblock parked producers, so every resize notifies room
        budget-wide.
        """
        share = self._shares.get(name)
        if share is None:
            raise KeyError(f"no budget share {name!r}")
        new_floor = share.floor if floor is None else floor
        new_ceiling = share.ceiling if ceiling is None else ceiling
        if not 0 <= new_floor <= new_ceiling:
            raise ValueError(
                f"need 0 <= floor <= ceiling, got floor={new_floor} "
                f"ceiling={new_ceiling} for {name!r}")
        if new_ceiling > self.total_bytes:
            raise ValueError(
                f"ceiling {new_ceiling} for {name!r} exceeds the "
                f"{self.total_bytes}B budget")
        reserved = sum(s.floor for s in self._shares.values()
                       if s is not share)
        if reserved + new_floor > self.total_bytes:
            raise ValueError(
                f"floor {new_floor} for {name!r} is not reservable: "
                f"{reserved}B of the {self.total_bytes}B budget is "
                f"already promised to other graphs")
        share.floor = new_floor
        share.ceiling = new_ceiling
        self.notify_room()
        return share

    def unregister(self, name: str) -> None:
        """Drop a share; any bytes it still holds return to the pool
        (its entries' tickets were already failed or applied)."""
        share = self._shares.pop(name, None)
        if share is not None and share.used:
            self.used -= share.used
            share.used = 0

    def shares(self) -> Dict[str, BudgetShare]:
        return dict(self._shares)

    # -- admission math ----------------------------------------------------

    def _reserved_for_others(self, share: BudgetShare) -> int:
        return sum(max(0, s.floor - s.used)
                   for s in self._shares.values() if s is not share)

    def _room_for(self, share: BudgetShare, nbytes: int) -> bool:
        if share.used + nbytes > share.ceiling:
            return False
        return (self.used + nbytes
                <= self.total_bytes - self._reserved_for_others(share))

    def _max_alone(self, share: BudgetShare) -> int:
        headroom = self.total_bytes - sum(
            s.floor for s in self._shares.values() if s is not share)
        return min(share.ceiling, headroom)

    # -- producer wakeups --------------------------------------------------

    def notify_room(self) -> None:
        for share in self._shares.values():
            for cond in share._conds:
                cond.notify_all()

    # -- observability -----------------------------------------------------

    def publish_metrics(self, registry=None, *, name: str = "budget"
                        ) -> str:
        """Register live occupancy gauges (total/used/peak bytes,
        occupancy fraction, per-share usage) into an obs registry.
        Gauges read the counters the owning lock already guards —
        snapshot reads are racy-but-consistent-enough telemetry, never
        admission decisions. Returns the gauge-name prefix."""
        from reflow_tpu.obs import REGISTRY
        reg = registry if registry is not None else REGISTRY
        reg.gauge(f"{name}.total_bytes", lambda: self.total_bytes)
        reg.gauge(f"{name}.used_bytes", lambda: self.used)
        reg.gauge(f"{name}.peak_bytes", lambda: self.peak)
        reg.gauge(f"{name}.occupancy",
                  lambda: self.used / self.total_bytes)
        self._metric_keys.append((reg, name))
        reg.gauge(f"{name}.per_share_used",
                  lambda: {s.name: s.used
                           for s in self._shares.values()})
        return name

    def unpublish_metrics(self) -> None:
        """Drop every gauge :meth:`publish_metrics` registered — the
        tier calls this at close so a re-created budget never leaves
        stale lambdas capturing a dead instance in the registry."""
        for reg, prefix in self._metric_keys:
            reg.unregister_prefix(f"{prefix}.")
        self._metric_keys = []
