"""Coalescing: fold queued micro-batches into ``tick_many`` macro-ticks.

The window has three triggers (any one fires the pump):

- **max-rows**: enough host rows are queued to fill a merged feed batch;
- **max-ticks**: the backlog would already unfold into that many feeds;
- **max-latency**: the oldest admitted micro-batch has waited long
  enough — the tail-latency bound under light traffic.

Feed construction honors the scheduler's one-per-source-per-tick rule:
host micro-batches for the same source merge via ``DeltaBatch.concat``
(up to ``max_rows`` rows per merged batch); a device-resident batch
takes a feed slot alone (host concat would force a device readback).
Feeds form in parallel across sources — feed ``t`` carries every
source's ``t``-th merged chunk — so steady-state multi-source traffic
rides one macro-tick, not one tick per source.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import Node

from .queues import Entry

__all__ = ["CoalesceWindow", "Feed", "build_feeds"]


@dataclasses.dataclass(frozen=True)
class CoalesceWindow:
    """Coalescing-window configuration (see module docstring)."""

    max_rows: int = 4096        # host rows per merged feed batch
    max_ticks: int = 8          # feeds per tick_many macro-tick
    max_latency_s: float = 0.005  # oldest-entry admission-to-tick bound

    def __post_init__(self):
        if self.max_rows < 1 or self.max_ticks < 1:
            raise ValueError(f"degenerate coalescing window: {self}")


@dataclasses.dataclass
class Feed:
    """One tick's worth of coalesced input."""

    batches: Dict[Node, DeltaBatch]
    ids: Dict[Node, List[str]]
    entries: Dict[Node, List[Entry]]


def _chunk_source(entries: Sequence[Entry], max_rows: int
                  ) -> List[List[Entry]]:
    """Split one source's FIFO backlog into feed chunks: device entries
    alone, host runs merged up to ``max_rows`` rows."""
    chunks: List[List[Entry]] = []
    run: List[Entry] = []
    run_rows = 0
    for e in entries:
        if e.device:
            if run:
                chunks.append(run)
                run, run_rows = [], 0
            chunks.append([e])
            continue
        if run and run_rows + e.rows > max_rows:
            chunks.append(run)
            run, run_rows = [], 0
        run.append(e)
        run_rows += e.rows
    if run:
        chunks.append(run)
    return chunks


def build_feeds(entries_by_source: Dict[int, List[Entry]],
                max_rows: int) -> List[Feed]:
    """Unfold a drained backlog into ordered ``tick_many`` feeds."""
    per_source = {sid: _chunk_source(es, max_rows)
                  for sid, es in entries_by_source.items() if es}
    n_feeds = max((len(c) for c in per_source.values()), default=0)
    feeds: List[Feed] = []
    for t in range(n_feeds):
        batches: Dict[Node, DeltaBatch] = {}
        ids: Dict[Node, List[str]] = {}
        entries: Dict[Node, List[Entry]] = {}
        for chunks in per_source.values():
            if t >= len(chunks):
                continue
            chunk = chunks[t]
            node = chunk[0].source
            if chunk[0].device:
                batches[node] = chunk[0].batch
            elif len(chunk) == 1:
                batches[node] = chunk[0].batch
            else:
                batches[node] = DeltaBatch.concat(
                    [e.batch for e in chunk])
            ids[node] = [e.batch_id for e in chunk]
            entries[node] = list(chunk)
        feeds.append(Feed(batches, ids, entries))
    return feeds
