"""Promote-on-failure: epoch-fenced leader failover.

The write path's single point of failure was the leader: PR 10 gave
reads N replicas, but a dead leader meant no more commit windows, ever.
This module closes that gap with a :class:`FailoverCoordinator` — a
control-plane actuator that detects leader death, elects a follower,
promotes it, and re-points the whole serving path, while **epoch
fencing** guarantees a not-actually-dead old leader (the classic
zombie) can never corrupt the new timeline.

The sequence, and why each step is where it is:

1. **Final drain.** Every acknowledged write is synced (acks gate on
   ``wal.wait_durable``), and synced bytes are plain file bytes — a
   dead *committer* doesn't make the disk unreadable. So before
   electing, the coordinator pumps the old shipper until no byte
   moves: acked ⊆ synced ⊆ shipped. Zero acknowledged-write loss is
   a property of this ordering, not of luck.
2. **Fence.** The old WAL is fenced at ``epoch+1``: any append the
   zombie still attempts raises :class:`~reflow_tpu.wal.log.FencedWrite`
   (counted, traced), and every shipment it emits carries the old
   epoch — replicas NACK it with a ``fenced`` reason before mirroring
   a single byte, and the zombie's shipper stops offering to fenced
   followers. Rejected, never merged.
3. **Elect.** Deterministic policy, pluggable interface
   (:class:`ElectionPolicy`): the default
   :class:`HighestHorizonElection` picks the highest applied horizon,
   ties broken by name — after the final drain that follower holds
   every acknowledged window.
4. **Promote.** The winner truncates its held-back tail, opens its
   mirror as its own WAL in the new epoch (a fresh segment) and
   replays the mirrored prefix through ``recover()`` — see
   ``ReplicaScheduler.promote``.
5. **Re-ship.** A new :class:`~reflow_tpu.wal.ship.SegmentShipper`
   runs off the new leader; survivors ``reanchor()`` (truncate to
   their apply point, adopt the epoch) and re-attach — the
   truncation-style re-anchor that makes their mirrored prefixes
   byte-compatible with the new leader's log.
6. **Re-point serving.** ``ReadTier.promote`` swings the leader
   fallback; the tier handle's ``rebind()`` revives the (crashed)
   ``IngestFrontend`` over the promoted scheduler. In-flight tickets
   on the dead leader already failed with ``PumpCrashed``; producers
   resubmit through the rebuilt dedup mirror, so a batch the old
   leader committed-and-shipped dedups and a batch it never committed
   folds exactly once on the new leader.

Detection is sampled, not event-driven, in the style the rest of the
control plane tests depend on: ``step(now)`` with an injectable
``clock`` and ``sampler`` runs on a fake clock with zero sleeps. A
sample reports ``committer_dead`` / ``pump_failed`` booleans and an
opaque monotone ``beat`` value (the default sampler uses the WAL's
last LSN); the coordinator derives ``leader.heartbeat_age_s`` from
beat changes and declares death after ``confirm_intervals``
*consecutive* dead samples — one healthy sample resets the streak, so
a flapping gauge can't trigger a promotion.

Drive it standalone (``step()`` / ``promote_now()``) or hand it to
``ControlPlane(failover=...)``, which steps it on the supervision
interval and records its actions alongside the other actuators.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from reflow_tpu.graph import GraphError
from reflow_tpu.obs import trace as _trace
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.wal.ship import SegmentShipper

__all__ = ["ElectionPolicy", "HighestHorizonElection",
           "FailoverCoordinator"]


class ElectionPolicy:
    """Pluggable leader election over replica candidates. The in-tree
    policy is deterministic (every observer picks the same winner from
    the same candidate set); a distributed-consensus implementation
    plugs in here when replicas leave the process."""

    def elect(self, candidates: List[object]):
        raise NotImplementedError


class HighestHorizonElection(ElectionPolicy):
    """Highest applied horizon wins; ties break by name (ascending).
    After the coordinator's final drain, the highest horizon holds
    every acknowledged commit window — promoting anyone else could
    lose acked writes."""

    def elect(self, candidates: List[object]):
        if not candidates:
            raise RuntimeError("leader election with no candidates: "
                               "every replica is dead or promoted")
        return min(candidates,
                   key=lambda r: (-r.published_horizon(),
                                  getattr(r, "name", "")))


class FailoverCoordinator:
    """Detect leader death, elect, promote, re-point. See the module
    docstring for the sequence.

    ``replicas`` is the candidate pool (a live list is fine — it is
    re-read at election time). ``shipper`` is the OLD leader's
    ``SegmentShipper`` (its ``wal`` is what gets fenced; None for
    pure election tests). ``handle`` is the tier ``GraphHandle`` (or a
    bare ``IngestFrontend``) whose ingestion gets re-bound;
    ``read_tier`` the ``ReadTier`` whose leader fallback follows.
    ``promote_fn(winner, epoch)`` overrides the actual promotion —
    fake-clock tests stub it and assert on the decision logic alone.
    ``durable_kw`` forwards to ``ReplicaScheduler.promote`` (``fsync=``,
    ``committer=``, ...).
    """

    def __init__(self, replicas, *, shipper: Optional[SegmentShipper] = None,
                 handle=None, read_tier=None,
                 election: Optional[ElectionPolicy] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 confirm_intervals: int = 2,
                 clock: Callable[[], float] = time.monotonic,
                 sampler: Optional[Callable[[float], Dict]] = None,
                 promote_fn: Optional[Callable] = None,
                 durable_kw: Optional[Dict] = None,
                 drain_timeout_s: float = 3.0,
                 name: str = "failover"):
        if confirm_intervals < 1:
            raise ValueError("confirm_intervals must be >= 1")
        self.replicas = replicas
        self.shipper = shipper
        self.handle = handle
        self.read_tier = read_tier
        self.election = election if election is not None \
            else HighestHorizonElection()
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.confirm_intervals = confirm_intervals
        self.drain_timeout_s = drain_timeout_s
        self.name = name
        self._clock = clock
        self._sampler = sampler
        self._promote_fn = promote_fn
        self._durable_kw = dict(durable_kw or {})
        wal = shipper.wal if shipper is not None else None
        self._epoch = wal.epoch if wal is not None else 0
        self.heartbeat_age_s = 0.0
        self._last_beat = None
        self._beat_at: Optional[float] = None
        self._dead_streak = 0
        self._pending_rebind = False
        #: set by a successful promotion
        self.winner = None
        self.leader_sched = None
        self.new_shipper: Optional[SegmentShipper] = None
        self.promotions = 0
        self.drained_bytes = 0
        self.partitions_detected = 0
        self._metric_names: List[tuple] = []

    # -- detection ---------------------------------------------------------

    @property
    def epoch(self) -> int:
        """The epoch this coordinator believes is current."""
        return self._epoch

    @property
    def promoted(self) -> bool:
        return self.leader_sched is not None

    def _default_sample(self) -> Dict:
        wal = self.shipper.wal if self.shipper is not None else None
        fe = self.handle
        if fe is not None:
            fe = getattr(fe, "frontend", fe)
        committer_dead = (wal is not None
                          and wal.committer_error is not None)
        # every wire-attached follower unreachable while the committer
        # still runs: the leader is cut off from its replicas — a
        # partition, not a death (step() labels it "leader_partitioned")
        conn_states = []
        if self.shipper is not None:
            with self.shipper._lock:
                states = list(self.shipper._followers.values())
            conn_states = [getattr(st.follower, "conn_state", None)
                           for st in states]
            conn_states = [s for s in conn_states if s is not None]
        return {
            "committer_dead": committer_dead,
            "pump_failed": (fe is not None
                            and getattr(fe, "_state", None) == "failed"),
            "beat": wal.last_lsn() if wal is not None else None,
            "partitioned": (bool(conn_states) and not committer_dead
                            and all(s == "unreachable"
                                    for s in conn_states)),
        }

    def step(self, now: Optional[float] = None) -> List[Dict]:
        """One detect-and-maybe-act pass; returns this tick's actions
        (``ControlPlane`` merges them into its action log). After a
        promotion this only retries a still-pending ingestion rebind
        — the coordinator is single-fire; a failure of the NEW leader
        is a fresh coordinator's job (over ``new_shipper`` and the
        surviving replicas)."""
        now = self._clock() if now is None else now
        actions: List[Dict] = []
        if self.promoted:
            if self._pending_rebind and self._try_rebind():
                self._pending_rebind = False
                actions.append({"now": now, "kind": "failover_rebind",
                                "epoch": self._epoch})
            return actions
        sample = (self._sampler(now) if self._sampler is not None
                  else self._default_sample())
        beat = sample.get("beat")
        if self._beat_at is None or beat != self._last_beat:
            self._last_beat, self._beat_at = beat, now
        self.heartbeat_age_s = max(0.0, now - self._beat_at)
        dead = bool(sample.get("committer_dead")
                    or sample.get("pump_failed"))
        reason = ("committer_dead" if sample.get("committer_dead")
                  else "pump_failed")
        if not dead and sample.get("partitioned"):
            # the sampler can see the leader process alive but its
            # links dark (e.g. every shipping client unreachable):
            # "leader partitioned", not "leader dead". Same debounced
            # promotion — the epoch fence, not the drain, is what
            # protects the timeline from the isolated ex-leader.
            dead, reason = True, "leader_partitioned"
        if (not dead and self.heartbeat_timeout_s is not None
                and self.heartbeat_age_s > self.heartbeat_timeout_s):
            # beats stopped arriving: with positive evidence that the
            # committer still runs, that is a partition; without it we
            # can only call the stall itself
            dead = True
            reason = ("leader_partitioned" if sample.get("committer_alive")
                      else "heartbeat_timeout")
        if not dead:
            self._dead_streak = 0  # one healthy sample resets the streak
            return actions
        self._dead_streak += 1
        if self._dead_streak < self.confirm_intervals:
            return actions
        if reason == "leader_partitioned":
            self.partitions_detected += 1
        actions.extend(self.promote_now(now, reason=reason))
        return actions

    # -- the actuator ------------------------------------------------------

    def promote_now(self, now: Optional[float] = None, *,
                    reason: str = "manual") -> List[Dict]:
        """Run the failover end to end (also the operator's forced-
        promotion entry — see docs/guide.md "Leader failover").
        Idempotent: a second call returns no actions."""
        if self.promoted:
            return []
        now = self._clock() if now is None else now
        t0 = time.perf_counter()
        # 1. final drain: ship every synced byte the dead leader will
        # ever produce, so the election sees every acknowledged window
        drained = 0
        old_had_thread = False
        old_wal = None
        if self.shipper is not None:
            old_wal = self.shipper.wal
            old_had_thread = self.shipper._thread is not None
            # PATIENT drain: a remote follower mid-reconnect-backoff
            # reports zero progress for whole passes without being
            # done, so "no bytes moved" alone must not end the drain —
            # only "everyone reached the watermark" (fully_shipped) or
            # the deadline may. The deadline is real time on purpose:
            # it bounds waiting on real links, and fake-clock tests
            # stub the shipper out entirely.
            deadline = time.monotonic() + max(0.0, self.drain_timeout_s)
            try:
                while True:
                    got = self.shipper.pump_once()
                    drained += got
                    if got:
                        continue
                    if self.shipper.fully_shipped() \
                            or time.monotonic() >= deadline:
                        break
                    time.sleep(0.005)
            except Exception:  # noqa: BLE001 - a dead leader's disk may
                pass           # be gone too; promote from what shipped
            self.shipper.stop()
        self.drained_bytes = drained
        # 2. fence: from here every zombie append raises FencedWrite
        new_epoch = self._epoch + 1
        if old_wal is not None:
            new_epoch = max(new_epoch, old_wal.epoch + 1)
            try:
                old_wal.fence(new_epoch)
            except Exception:  # noqa: BLE001 - fencing a torn-down log
                pass           # is advisory; replicas reject by epoch
        # 3. elect (deterministic; see HighestHorizonElection)
        candidates = [r for r in self.replicas
                      if not getattr(r, "promoted", False)]
        winner = self.election.elect(candidates)
        if _trace.ENABLED:
            _trace.evt("failover_elect", t0, time.perf_counter() - t0,
                       track="failover",
                       args={"winner": getattr(winner, "name", "?"),
                             "epoch": new_epoch, "reason": reason,
                             "drained_bytes": drained,
                             "horizons": {
                                 getattr(r, "name", str(i)):
                                     r.published_horizon()
                                 for i, r in enumerate(candidates)}})
        # 4. promote (emits the failover_replay span)
        if self._promote_fn is not None:
            sched = self._promote_fn(winner, new_epoch)
        else:
            sched = winner.promote(epoch=new_epoch, **self._durable_kw)
        self.winner = winner
        self.leader_sched = sched
        self._epoch = new_epoch
        self.promotions += 1
        # 5. new shipper; survivors re-anchor and re-subscribe
        wal = getattr(sched, "wal", None)
        if wal is not None and self.shipper is not None:
            self.new_shipper = SegmentShipper(
                wal, ckpt_dir=getattr(winner, "ckpt_dir", None),
                leader_tick=lambda: sched._tick,
                poll_s=self.shipper.poll_s,
                max_chunk_bytes=self.shipper.max_chunk_bytes)
            for r in self.replicas:
                if r is winner or getattr(r, "promoted", False):
                    continue
                r.reanchor(new_epoch)
                self.new_shipper.attach(r)
            if old_had_thread:
                self.new_shipper.start()
        # 6. re-point reads and ingestion
        if self.read_tier is not None:
            self.read_tier.promote(winner, epoch=new_epoch)
        rebound = self._try_rebind()
        self._pending_rebind = self.handle is not None and not rebound
        return [{"now": now, "kind": "failover_promote",
                 "winner": getattr(winner, "name", "?"),
                 "epoch": new_epoch, "reason": reason,
                 "drained_bytes": drained, "rebound": rebound}]

    def _try_rebind(self) -> bool:
        """Revive the ingestion frontend over the new leader. Fails
        (and is retried each step) until the pump has actually crashed
        — a committer-dead leader whose pump hasn't hit the WAL yet is
        still ``"running"``, and ``revive()`` refuses to re-arm a
        frontend that never settled."""
        if self.handle is None:
            return True
        if self.leader_sched is None:
            return False
        try:
            fn = getattr(self.handle, "rebind", None)
            if fn is not None:
                fn(self.leader_sched)
            else:
                self.handle.revive(sched=self.leader_sched)
            return True
        except GraphError:
            return False

    # -- observability -----------------------------------------------------

    def publish_metrics(self, registry=None) -> None:
        reg = registry if registry is not None else REGISTRY

        def _rejected_appends() -> int:
            wal = self.shipper.wal if self.shipper is not None else None
            return wal.fence_rejected_appends if wal is not None else 0

        reg.gauge("failover.epoch", lambda: self._epoch)
        reg.gauge("failover.promotions_total", lambda: self.promotions)
        reg.gauge("failover.partitions_detected",
                  lambda: self.partitions_detected)
        reg.gauge("leader.heartbeat_age_s", lambda: self.heartbeat_age_s)
        reg.gauge("fence.rejected_appends", _rejected_appends)
        reg.gauge("fence.rejected_shipments",
                  lambda: sum(getattr(r, "fence_rejected_shipments", 0)
                              for r in self.replicas))
        self._metric_names += [(reg, "failover."),
                               (reg, "leader.heartbeat_age_s"),
                               (reg, "fence.")]

    def close(self) -> None:
        if self.new_shipper is not None:
            self.new_shipper.stop()
        for reg, base in self._metric_names:
            reg.unregister_prefix(base)
        self._metric_names.clear()
