"""Tickets: the producer-facing completion objects of the frontend.

``IngestFrontend.submit`` returns a :class:`Ticket` immediately; the
pump thread resolves it once the micro-batch's fate is decided. A
ticket always resolves with a :class:`TicketResult` — admission-control
outcomes (dedup, backpressure rejection, shed) are *reported*, never
silently dropped — except when the frontend itself dies, in which case
``result()`` raises (:class:`PumpCrashed` / :class:`FrontendClosed`).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

__all__ = ["APPLIED", "DEDUPED", "REJECTED", "SHED", "FrontendClosed",
           "PumpCrashed", "Ticket", "TicketResult"]

#: the batch folded into the graph at ``TicketResult.tick``
APPLIED = "applied"
#: the batch's id was already accepted (exactly-once dedup)
DEDUPED = "deduped"
#: backpressure refused admission (``reject`` policy, oversized batch,
#: or a ``block`` admission that timed out)
REJECTED = "rejected"
#: the ``shed-oldest`` policy evicted this already-admitted batch to
#: make room for a newer one — the upstream must re-send it
SHED = "shed"


class FrontendClosed(RuntimeError):
    """The frontend is closed (or closing): the submission was not
    admitted, and blocked producers have been released."""


class PumpCrashed(FrontendClosed):
    """The pump thread died mid-flight; the scheduler's durable state
    (if any) is whatever the WAL holds — recover and resubmit."""


@dataclasses.dataclass
class TicketResult:
    """Final fate of one submitted micro-batch."""

    status: str                  # APPLIED / DEDUPED / REJECTED / SHED
    batch_id: str
    #: scheduler tick the batch committed in (APPLIED only)
    tick: Optional[int] = None
    #: how many OTHER micro-batches were coalesced into the same feed
    #: entry (APPLIED only; >0 means the merge path engaged)
    coalesced_with: int = 0
    reason: Optional[str] = None
    #: WAL LSN the batch's window committed under (APPLIED on a durable
    #: scheduler only — resolution gated on ``wal.wait_durable(lsn)``)
    lsn: Optional[int] = None

    @property
    def applied(self) -> bool:
        return self.status == APPLIED


class Ticket:
    """Thread-safe future for one submission. Producers ``result()`` or
    poll ``done()``; only the frontend resolves it."""

    __slots__ = ("batch_id", "trace", "_event", "_result", "_error")

    def __init__(self, batch_id: str):
        self.batch_id = batch_id
        #: obs.trace.TraceCtx when tracing is enabled at submit time;
        #: the pump reads it to emit the ticket's stage timeline
        self.trace = None
        self._event = threading.Event()
        self._result: Optional[TicketResult] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> TicketResult:
        """Block until resolved. Raises the frontend's failure (e.g.
        :class:`PumpCrashed`) instead of returning when the batch's fate
        was never decided; raises ``TimeoutError`` on timeout."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"ticket {self.batch_id!r} unresolved after {timeout}s")
        if self._error is not None:
            raise self._error
        if self._result is None:
            raise RuntimeError(
                f"ticket {self.batch_id!r} resolved with neither result "
                f"nor error")
        return self._result

    # -- frontend side -----------------------------------------------------

    def _resolve(self, result: TicketResult) -> None:
        self._result = result
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()
