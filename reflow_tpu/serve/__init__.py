"""Streaming serving: concurrent producers → macro-ticks, one graph or
many.

``IngestFrontend`` owns one scheduler on a dedicated pump thread and
exposes a thread-safe ``submit() -> Ticket`` to any number of
producers, with backpressure, micro-batch coalescing, exactly-once
admission, and graceful drain/close. ``ServeTier`` hosts many named
graphs behind one shared ``AdmissionBudget`` (per-graph floors and
ceilings) and one pump pool with deficit-weighted round-robin QoS.
See ``docs/guide.md`` ("Serving ingestion" and "Serving tier") for the
tour.
"""

from .budget import AdmissionBudget, BudgetShare
from .coalesce import CoalesceWindow, Feed, build_feeds
from .control import (Autoscaler, BrownoutLadder, CircuitBreaker,
                      ControlConfig, ControlPlane, SLOSpec,
                      load_slo_specs)
from .failover import (ElectionPolicy, FailoverCoordinator,
                       HighestHorizonElection)
from .frontend import IngestFrontend
from .queues import batch_nbytes
from .read import LeaderReadAdapter, ReadResult, ReadTier, StaleRead
from .replica import ReplicaScheduler
from .rpc import (RemoteProducer, RemoteTicket, RpcIngestServer,
                  SubmitAck, SubmitReq, TicketResolve)
from .tickets import (APPLIED, DEDUPED, REJECTED, SHED, FrontendClosed,
                      PumpCrashed, Ticket, TicketResult)
from .tier import GraphConfig, GraphHandle, ServeTier, dwrr_pick

__all__ = [
    "APPLIED", "DEDUPED", "REJECTED", "SHED",
    "AdmissionBudget", "Autoscaler", "BrownoutLadder", "BudgetShare",
    "CircuitBreaker", "CoalesceWindow", "ControlConfig", "ControlPlane",
    "ElectionPolicy", "FailoverCoordinator", "Feed", "FrontendClosed",
    "GraphConfig", "GraphHandle", "HighestHorizonElection",
    "IngestFrontend", "LeaderReadAdapter", "PumpCrashed", "ReadResult",
    "ReadTier", "RemoteProducer", "RemoteTicket", "ReplicaScheduler",
    "RpcIngestServer", "SLOSpec", "ServeTier", "StaleRead", "SubmitAck",
    "SubmitReq", "Ticket", "TicketResolve", "TicketResult",
    "batch_nbytes", "build_feeds", "dwrr_pick", "load_slo_specs",
]
