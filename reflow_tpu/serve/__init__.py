"""Streaming ingestion frontend: concurrent producers → macro-ticks.

``IngestFrontend`` owns a scheduler on a dedicated pump thread and
exposes a thread-safe ``submit() -> Ticket`` to any number of
producers, with backpressure, micro-batch coalescing, exactly-once
admission, and graceful drain/close. See ``docs/guide.md`` ("Serving
ingestion") for the tour.
"""

from .coalesce import CoalesceWindow, Feed, build_feeds
from .frontend import IngestFrontend
from .queues import batch_nbytes
from .tickets import (APPLIED, DEDUPED, REJECTED, SHED, FrontendClosed,
                      PumpCrashed, Ticket, TicketResult)

__all__ = [
    "APPLIED", "DEDUPED", "REJECTED", "SHED",
    "CoalesceWindow", "Feed", "FrontendClosed", "IngestFrontend",
    "PumpCrashed", "Ticket", "TicketResult", "batch_nbytes",
    "build_feeds",
]
