"""Read replicas: continuous WAL tail replay at a published tick horizon.

A :class:`ReplicaScheduler` is the follower end of the WAL shipping
protocol (``wal/ship.py``). It mirrors the leader's CRC-framed segments
into a local directory, replays them through the exact idempotent
machinery crash recovery already trusts (``wal.recovery.replay_records``
— a replayed push dedups by batch id, a replayed tick below the counter
is skipped), and publishes a **tick horizon**: reads are answered from
a snapshot of the sink views as of a whole number of commit windows.
Readers never see half a window.

Three invariants carry the design:

- **Holdback**: shipped records are staged and applied only through the
  *last tick marker* received. Pushes past it — a commit window still in
  flight — touch nothing, not even the pending buffers, until their
  marker arrives. A torn or tampered shipment is therefore rejected
  whole (NACK with the replica's authoritative cursor) and a partial
  commit window is never applied, no matter where the transport died.
- **Restart-resume**: the replica checkpoints its own scheduler state
  (stamping the applied WAL position into ``meta.pkl``, exactly the
  contract ``recover()`` reads) and persists its ship cursor next to the
  checkpoint. A restart restores checkpoint + mirrored tail and
  re-subscribes from where it left off — never from segment 0.
- **Immutable read snapshots**: each published horizon lazily
  materializes per-sink arrays (keys + weights) that are never mutated
  afterward, so ``top_k`` is a lock-free ``np.argpartition`` over frozen
  numpy buffers — reads scale with replica count instead of serializing
  on the leader's live, mutable views.

``promote()`` turns a follower into a leader: the staged (unapplied)
tail is truncated out of the mirror, a ``DurableScheduler`` opens the
mirror directory as its own WAL in a **new epoch**, and ``recover()``
replays the mirrored prefix — so the new leader's state is exactly the
replica's published horizon, rebuilt through the same machinery crash
recovery trusts. Shipments from an older epoch are NACKed with a
``fenced`` reason and never mirrored; ``reanchor()`` is the surviving
followers' half of a failover (drop holdback, truncate to the apply
point, adopt the new epoch, re-subscribe). The election and serving
re-bind live in ``serve/failover.py``.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time
import zlib
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

import numpy as np

from reflow_tpu.obs import flight as _flight
from reflow_tpu.obs import trace as _trace
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.scheduler import DirtyScheduler
from reflow_tpu.utils import tiles as _t
from reflow_tpu.utils.config import env_int
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.wal.log import (_MAGIC, LogPosition, WalError, _repair_tail,
                                _seg_path, list_segments)
from reflow_tpu.wal.recovery import replay_records
from reflow_tpu.wal.ship import (ShipAck, Shipment, ShipNack, iter_frames,
                                 record_causes)

__all__ = ["ReplicaScheduler", "CURSOR_FILE", "TILE_UNIT_SCHEMA"]

CURSOR_FILE = "cursor.json"
CURSOR_SCHEMA = "reflow.replica_cursor/1"
#: one checkpoint file shipped as an independently CRC-framed unit
#: (wal/ship.py ``_bootstrap_tiles`` <-> ``receive_ckpt_tile``)
TILE_UNIT_SCHEMA = "reflow.tile_ship/1"
#: staging directory for an in-flight tile-unit bootstrap transfer
_STAGE_DIR = "bootstrap-ckpt"


class _Snapshot(NamedTuple):
    """Frozen per-sink read state at one published horizon. ``keys`` and
    ``weights`` are never mutated after construction: ``top_k`` runs
    ``np.argpartition`` on them without holding any lock."""

    horizon: int
    keys: List[tuple]
    weights: np.ndarray
    #: per-row scalar values, when the sink's values are numeric (the
    #: unique-keyed aggregate case, e.g. wordcount's (word, count) rows
    #: at weight 1) — None for non-numeric payloads
    values: Optional[np.ndarray]
    index: Dict[tuple, float]


class _Tile(NamedTuple):
    """One immutable key-range shard of a tiled snapshot. ``gen`` is the
    content generation: it bumps only when the tile is rebuilt, so two
    horizons sharing a gen share the *same* array objects (zero-copy
    reuse for untouched key ranges — the BENCH_r02 preload fix)."""

    lo: int
    hi: int
    gen: int
    keys: List[tuple]
    weights: np.ndarray
    values: Optional[np.ndarray]
    index: Dict[tuple, float]


class _TileSnap(NamedTuple):
    """Frozen tiled read state at one published horizon: a bucket-range
    plan plus one :class:`_Tile` per range. ``top_k`` argpartitions each
    tile and merges at most k candidates per tile; the full state is
    never concatenated into one array."""

    horizon: int
    plan: Tuple[Tuple[int, int], ...]
    tiles: Tuple[_Tile, ...]


def _row_bytes(kv) -> int:
    """Histogram estimate for one view row ``(key, value)``."""
    if isinstance(kv, tuple) and len(kv) == 2:
        return _t.approx_row_bytes(kv[0], kv[1])
    return _t.approx_row_bytes(kv, None)


class ReplicaScheduler:
    """A follower that replays shipped WAL windows into its own
    ``DirtyScheduler`` and serves snapshot reads at a published horizon.

    ``replica_dir`` holds everything the replica needs to resume:
    ``wal/`` (the mirrored leader segments), ``ckpt/`` (its own
    checkpoints) and ``cursor.json`` (the ship cursor, leader
    coordinates). Build it with the same graph the leader runs;
    ``executor=None`` gives the CPU oracle, which is what a read tier
    wants — views are host Counters either way."""

    def __init__(self, graph, replica_dir: str, *, executor=None,
                 name: Optional[str] = None,
                 tile_bytes: Optional[int] = None) -> None:
        self.graph = graph
        self.replica_dir = replica_dir
        self.mirror_dir = os.path.join(replica_dir, "wal")
        self.ckpt_dir = os.path.join(replica_dir, "ckpt")
        os.makedirs(self.mirror_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.name = name or (os.path.basename(os.path.normpath(replica_dir))
                             or "replica")
        self.sched = DirtyScheduler(graph, executor)
        self._lock = named_lock(f"serve.replica.{self.name}", reentrant=True)
        #: parsed-but-unapplied records (the holdback buffer): entries
        #: are (pos, end_pos, record); only a suffix past the last
        #: applied tick marker ever lives here
        self._staged: List[Tuple[LogPosition, LogPosition, dict]] = []
        self._cursor: Optional[LogPosition] = None   # next byte expected
        self._applied: Optional[LogPosition] = None  # end of last applied
        self._horizon = 0
        self._leader_tick = 0
        self._snapshots: Dict[str, _Snapshot] = {}
        #: highest epoch witnessed (shipment header or mirrored record);
        #: shipments below it are fenced out before a byte is mirrored
        self._epoch = 0
        self._promoted_sched = None
        self.shipments = 0
        self.records_applied = 0
        self.windows_applied = 0
        self.crc_rejects = 0
        self.order_rejects = 0
        self.fence_rejected_shipments = 0
        self.bootstraps = 0
        self.restored_from: Optional[str] = None
        self._metric_names: List[Tuple[object, str]] = []
        #: optional SubscriptionHub fed by _apply_staged (attach_hub)
        self._hub = None
        #: snapshot tiling budget; 0 (the default) keeps the monolithic
        #: per-sink snapshot arrays byte-for-byte unchanged
        self.tile_bytes = env_int("REFLOW_TILE_BYTES") \
            if tile_bytes is None else int(tile_bytes)
        #: per-sink dirty bucket sets since that sink's last snapshot
        #: build; a ``None`` value means "everything dirty" (rebase,
        #: bootstrap, unreliable history) and forces a full rebuild
        self._dirty: Dict[str, Optional[Set[int]]] = {}
        self.snapshot_tile_builds = 0
        self.snapshot_tiles_reused = 0
        #: unit indices staged for the in-flight tile bootstrap transfer
        self._tile_units_seen: Set[int] = set()
        self.tile_units_received = 0
        self._restore()

    # -- transport surface (the watermark handshake) -----------------------

    def subscribe(self) -> Optional[Tuple[int, int]]:
        """The replica's persisted resume cursor in leader coordinates,
        or None for a fresh replica (the shipper then bootstraps)."""
        with self._lock:
            return tuple(self._cursor) if self._cursor is not None else None

    def attach_hub(self, hub) -> None:
        """Wire a :class:`~reflow_tpu.subs.hub.SubscriptionHub` into the
        apply path: each applied commit window is handed off as
        ``hub.on_window(from_h, to_h, tick_results)`` (O(1), the hub's
        own thread does the fan-out) and non-monotonic state moves
        (bootstrap/promote/reanchor) call ``hub.rebase()``. Pass None
        to detach."""
        with self._lock:
            self._hub = hub
        if hub is not None:
            hub.rebase()   # start from a fresh snapshot of current state

    def bootstrap(self, ckpt_dir: str) -> Tuple[int, int]:
        """Checkpoint-anchored catch-up: load the *leader's* checkpoint
        and resume shipping from its recorded WAL position — always a
        segment start, so leader and mirror coordinates agree on every
        byte after it. Immediately re-checkpoints locally so a restart
        never needs the leader's files again."""
        from reflow_tpu.utils.checkpoint import load_checkpoint

        with self._lock:
            meta = load_checkpoint(self.sched, ckpt_dir)
            pos = meta.get("wal_pos")
            if pos is None:
                raise WalError(f"{ckpt_dir}: leader checkpoint has no "
                               f"wal_pos — cannot anchor a replica on it")
            self._cursor = LogPosition(*pos)
            self._applied = self._cursor
            self._horizon = self.sched._tick
            self._staged.clear()
            self._snapshots = {}
            self._dirty = dict.fromkeys(self.sched.sink_views, None)
            self.bootstraps += 1
        self.checkpoint()
        if self._hub is not None:
            self._hub.rebase()   # state moved non-monotonically
        return tuple(self._cursor)

    def receive(self, sh: Shipment):
        """Verify, mirror, stage and (window-complete) apply one
        shipment. Returns :class:`ShipAck` with the advanced cursor and
        the new horizon, or :class:`ShipNack` carrying the replica's
        authoritative cursor for the shipper to resume from."""
        t0 = time.perf_counter()
        with self._lock:
            self.shipments += 1
            ep = getattr(sh, "epoch", 0)
            if ep < self._epoch:
                # a zombie ex-leader kept shipping: refuse before a
                # single byte is mirrored or staged
                self.fence_rejected_shipments += 1
                if _trace.ENABLED:
                    _trace.evt("fence_reject", t0,
                               time.perf_counter() - t0,
                               track=f"replica/{self.name}",
                               args={"kind": "shipment", "epoch": ep,
                                     "fenced_by": self._epoch,
                                     "segment": sh.segment})
                # a fence is exactly the moment this process may not
                # outlive — get the evidence onto disk now
                _flight.note("fence_reject", epoch=ep,
                             fenced_by=self._epoch, segment=sh.segment)
                return ShipNack(
                    tuple(self._cursor) if self._cursor else None,
                    f"fenced: shipment epoch {ep} < replica epoch "
                    f"{self._epoch}")
            if ep > self._epoch:
                self._epoch = ep
            cur = self._cursor
            if cur is None:
                # an unanchored fresh replica may only start at a
                # segment's first frame
                if sh.offset != len(_MAGIC):
                    self.order_rejects += 1
                    return ShipNack(None, "fresh replica needs a segment "
                                          "start")
                cur = LogPosition(sh.segment, sh.offset)
            if (sh.segment, sh.offset) != tuple(cur):
                self.order_rejects += 1
                return ShipNack(tuple(cur),
                                f"out of order: expected {tuple(cur)}, "
                                f"got {(sh.segment, sh.offset)}")
            entries, valid, reason = iter_frames(sh.payload, sh.segment,
                                                 sh.offset)
            if valid != len(sh.payload) \
                    or sh.offset + valid != sh.end_offset:
                # reject the shipment whole: nothing mirrored, nothing
                # staged, cursor unmoved — the shipper re-reads from it
                self.crc_rejects += 1
                return ShipNack(tuple(cur),
                                reason or "end_offset mismatch")
            self._mirror_append(sh)
            self._staged.extend(entries)
            applied = self._apply_staged()
            if sh.seals:
                nxt = (sh.next_segment if sh.next_segment is not None
                       else sh.segment + 1)
                self._cursor = LogPosition(nxt, len(_MAGIC))
            else:
                self._cursor = LogPosition(sh.segment, sh.end_offset)
            self._leader_tick = max(self._leader_tick, sh.leader_tick)
            self._persist_cursor()
            ack = ShipAck(tuple(self._cursor), self._horizon)
        if _trace.ENABLED:
            causes: List[str] = []
            for _p, _e, r in entries:
                for c in record_causes(r):
                    if c not in causes:
                        causes.append(c)
            _trace.evt("replica_replay", t0, time.perf_counter() - t0,
                       track=f"replica/{self.name}",
                       args={"segment": sh.segment, "bytes": len(sh.payload),
                             "records": len(entries), "applied": applied,
                             "horizon": ack.horizon,
                             "cause": getattr(sh, "cause", None),
                             "causes": causes,
                             "lag_ticks": self.lag_ticks()})
        return ack

    def _mirror_append(self, sh: Shipment) -> None:
        path = _seg_path(self.mirror_dir, sh.segment)
        if not os.path.exists(path):
            if sh.offset != len(_MAGIC):
                raise WalError(f"mirror gap: shipment for "
                               f"wal-{sh.segment:08d}.log @ {sh.offset} "
                               f"but no local segment")
            with open(path, "wb") as f:
                f.write(_MAGIC)
        size = os.path.getsize(path)
        if size > sh.offset:
            # an acked-but-forgotten overlap (shipper resumed behind us
            # after a NACK storm): drop our unacked surplus and re-land
            with open(path, "rb+") as f:
                f.truncate(sh.offset)
        elif size < sh.offset:
            raise WalError(f"mirror gap: wal-{sh.segment:08d}.log is "
                           f"{size} bytes, shipment starts at {sh.offset}")
        with open(path, "ab") as f:
            f.write(sh.payload)
            f.flush()

    def _apply_staged(self) -> int:
        """Apply staged records through the LAST tick marker; everything
        past it stays held back. Returns records applied."""
        last = None
        for i in range(len(self._staged) - 1, -1, -1):
            if self._staged[i][2].get("kind") == "tick":
                last = i
                break
        if last is None:
            return 0
        window = self._staged[:last + 1]
        del self._staged[:last + 1]
        hist0 = len(self.sched.history)
        from_h = self._horizon
        _rep, _ded, ticks, _skip = replay_records(
            self.sched, [(p, r) for p, _e, r in window])
        self.records_applied += len(window)
        self.windows_applied += ticks
        self._applied = window[-1][1]
        self._horizon = self.sched._tick
        results = tuple(self.sched.history[hist0:])
        reliable = len(results) == self._horizon - from_h
        if self.tile_bytes > 0:
            if reliable:
                # accumulate dirty buckets from the window's columnar
                # deltas: the next snapshot build rebuilds only tiles
                # owning a touched bucket and reuses the rest by identity
                for res in results:
                    for sname, d in res.sink_deltas.items():
                        cur = self._dirty.get(sname, set())
                        if cur is None:
                            continue  # already all-dirty
                        for kk, vv, _w in d.rows():
                            cur.add(_t.bucket_of((kk, vv)))
                        self._dirty[sname] = cur
            else:
                # restored state or trimmed history — per-key deltas
                # can't be trusted; next build starts from scratch
                self._dirty = dict.fromkeys(self.sched.sink_views, None)
            # keep stale tiled snapshots: they seed zero-copy reuse
            self._snapshots = {n: s for n, s in self._snapshots.items()
                               if isinstance(s, _TileSnap)}
        else:
            self._snapshots = {}
        hub = self._hub
        if hub is not None and self._horizon > from_h:
            if reliable:
                causes: List[str] = []
                if _trace.ENABLED:
                    for _p, _e, r in window:
                        for c in record_causes(r):
                            if c not in causes:
                                causes.append(c)
                # O(1) hand-off: the hub's fan-out thread does the work
                if causes:
                    hub.on_window(from_h, self._horizon, results,
                                  causes=tuple(causes))
                else:
                    hub.on_window(from_h, self._horizon, results)
            else:
                # replay didn't tick one-for-one (restored state or a
                # trimmed history) — deltas can't be trusted; re-snapshot
                hub.rebase()
        return len(window)

    # -- persistence -------------------------------------------------------

    def _persist_cursor(self) -> None:
        state = {
            "schema": CURSOR_SCHEMA,
            "cursor": list(self._cursor) if self._cursor else None,
            "applied": list(self._applied) if self._applied else None,
            "horizon": self._horizon,
            "leader_tick": self._leader_tick,
        }
        path = os.path.join(self.replica_dir, CURSOR_FILE)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        except OSError:
            pass  # advisory: restart re-derives the cursor from disk

    def checkpoint(self) -> str:
        """Checkpoint the replica's own scheduler state, stamping the
        applied WAL position into the meta so a restart resumes replay
        exactly where reads last saw — the same ``wal_pos`` contract
        ``recover()`` uses, written by hand because a replica's plain
        scheduler has no WAL of its own to rotate."""
        from reflow_tpu.utils.checkpoint import save_checkpoint

        with self._lock:
            save_checkpoint(self.sched, self.ckpt_dir)
            meta_path = os.path.join(self.ckpt_dir, "meta.pkl")
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
            pos = self._applied if self._applied is not None \
                else self._cursor
            if pos is not None:
                meta["wal_pos"] = tuple(pos)
            tmp = meta_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(meta, f)
                f.flush()
                # reflow-lint: waive lock-blocking-call -- checkpoint-meta fsync on the replica's own apply thread; readers never park on this lock mid-read (horizon snapshot is taken before)
                os.fsync(f.fileno())
            os.replace(tmp, meta_path)
            self._persist_cursor()
        return self.ckpt_dir

    def _restore(self) -> None:
        """Restart-resume: local checkpoint (if any) + mirrored tail.
        The cursor comes out at the end of the mirror's valid prefix —
        never segment 0 unless the replica truly is fresh."""
        from reflow_tpu.utils.checkpoint import (checkpoint_exists,
                                                 load_checkpoint)

        start: Optional[Tuple[int, int]] = None
        if checkpoint_exists(self.ckpt_dir):
            meta = load_checkpoint(self.sched, self.ckpt_dir)
            start = meta.get("wal_pos")
            self._horizon = self.sched._tick
            self.restored_from = "checkpoint"
        segs = list_segments(self.mirror_dir)
        if segs:
            # a kill mid-append leaves a torn mirror tail; drop it (the
            # shipper re-sends from our recomputed cursor)
            _repair_tail(segs[-1][1], segs[-1][0])
            segs = list_segments(self.mirror_dir)
        cursor = LogPosition(*start) if start is not None else None
        self._applied = cursor
        had_ckpt = self.restored_from == "checkpoint"
        had_tail = False
        for seq, path in segs:
            if start is not None and seq < start[0]:
                continue
            with open(path, "rb") as f:
                data = f.read()
            if data[:len(_MAGIC)] != _MAGIC:
                continue
            entries, _valid, _reason = iter_frames(
                data[len(_MAGIC):], seq, len(_MAGIC))
            for p, e, r in entries:
                # mirrored records carry their writer's epoch: a restart
                # resumes already knowing the highest epoch it witnessed,
                # so a zombie's shipments stay fenced across restarts
                self._epoch = max(self._epoch, r.get("epoch", 0) or 0)
                if start is not None and p.segment == start[0] \
                        and p.offset < start[1]:
                    continue
                self._staged.append((p, e, r))
                cursor = e if cursor is None or e > cursor else cursor
            had_tail = had_tail or bool(entries)
        if had_tail:
            self.restored_from = "checkpoint+tail" if had_ckpt else "tail"
        if self._staged:
            self._apply_staged()
        # NOTE: cursor.json is deliberately NOT consulted here — it can
        # run AHEAD of a torn mirror tail (persisted, then the appended
        # bytes died with the process), and resuming past bytes the
        # mirror lost would skip records forever. Checkpoint + mirror
        # walk is always sufficient: bootstrap checkpoints immediately,
        # so the persisted wal_pos anchors every resume.
        self._cursor = cursor
        self._horizon = self.sched._tick

    # -- read surface ------------------------------------------------------

    def published_horizon(self) -> int:
        """Tick counter as of the last fully-applied commit window."""
        return self._horizon

    def lag_ticks(self) -> int:
        """Published horizon's distance behind the leader tick last seen
        on a shipment (0 when fully caught up)."""
        return max(0, self._leader_tick - self._horizon)

    def _snapshot(self, sink):
        name = sink if isinstance(sink, str) else sink.name
        snap = self._snapshots.get(name)
        h = self._horizon
        if snap is not None and snap.horizon == h:
            return snap
        if self.tile_bytes > 0:
            return self._snapshot_tiled(name)
        with self._lock:
            snap = self._snapshots.get(name)
            if snap is None or snap.horizon != self._horizon:
                view = self.sched.sink_views[name]
                items = [(kv, w) for kv, w in view.items() if w != 0]
                try:
                    values = np.asarray([kv[1] for kv, _ in items],
                                        dtype=np.float64)
                except (TypeError, ValueError, IndexError):
                    values = None
                if values is not None and values.ndim != 1:
                    values = None
                snap = _Snapshot(
                    self._horizon,
                    [kv for kv, _ in items],
                    np.asarray([w for _, w in items], dtype=np.float64),
                    values,
                    dict(items))
                self._snapshots[name] = snap
        return snap

    # -- tiled snapshots (REFLOW_TILE_BYTES > 0) ---------------------------

    @staticmethod
    def _build_tile(items, lo: int, hi: int, gen: int) -> _Tile:
        try:
            values = np.asarray([kv[1] for kv, _ in items],
                                dtype=np.float64)
        except (TypeError, ValueError, IndexError):
            values = None
        if values is not None and values.ndim != 1:
            values = None
        return _Tile(lo, hi, gen,
                     [kv for kv, _ in items],
                     np.asarray([w for _, w in items], dtype=np.float64),
                     values, dict(items))

    def _build_all_tiles(self, view, h: int) -> _TileSnap:
        """Full build: histogram the live view into buckets, plan tiles
        under the budget, materialize each tile once."""
        buckets: List[list] = [[] for _ in range(_t.N_BUCKETS)]
        bbytes = [0.0] * _t.N_BUCKETS
        for kv, w in view.items():
            if w == 0:
                continue
            b = _t.bucket_of(kv)
            buckets[b].append((kv, w))
            bbytes[b] += _row_bytes(kv)
        plan = tuple(_t.plan_tiles(bbytes, self.tile_bytes))
        tiles = []
        for lo, hi in plan:
            items = [it for b in range(lo, hi) for it in buckets[b]]
            tiles.append(self._build_tile(items, lo, hi, 1))
            self.snapshot_tile_builds += 1
        return _TileSnap(h, plan, tuple(tiles))

    def _snapshot_tiled(self, name: str) -> _TileSnap:
        with self._lock:
            snap = self._snapshots.get(name)
            h = self._horizon
            if isinstance(snap, _TileSnap) and snap.horizon == h:
                return snap
            view = self.sched.sink_views[name]
            prev = snap if isinstance(snap, _TileSnap) else None
            dirty = self._dirty.get(name, set())
            if prev is None or dirty is None:
                snap = self._build_all_tiles(view, h)
            elif not dirty:
                # no delta touched this sink: every tile reused as-is
                self.snapshot_tiles_reused += len(prev.tiles)
                snap = prev._replace(horizon=h)
            else:
                snap = self._rebuild_dirty(view, h, prev, dirty)
            self._dirty[name] = set()
            self._snapshots[name] = snap
            return snap

    def _rebuild_dirty(self, view, h: int, prev: _TileSnap,
                       dirty: Set[int]) -> _TileSnap:
        """Rebuild only the tiles owning a dirty bucket; clean tiles are
        carried over by identity (same array objects, same gen)."""
        dirty_tiles = {i for i, (lo, hi) in enumerate(prev.plan)
                       if any(lo <= b < hi for b in dirty)}
        if not dirty_tiles:
            self.snapshot_tiles_reused += len(prev.tiles)
            return prev._replace(horizon=h)
        per: Dict[int, list] = {i: [] for i in dirty_tiles}
        est: Dict[int, float] = {i: 0.0 for i in dirty_tiles}
        for kv, w in view.items():
            if w == 0:
                continue
            i = _t.owning_tile(prev.plan, _t.bucket_of(kv))
            if i in per:
                per[i].append((kv, w))
                est[i] += _row_bytes(kv)
        for i in dirty_tiles:
            lo, hi = prev.plan[i]
            if est[i] > 2 * self.tile_bytes and hi - lo > 1:
                # a rebuilt tile blew past the enforced bound and can
                # still be split — replan the whole sink
                return self._build_all_tiles(view, h)
        tiles = list(prev.tiles)
        for i in dirty_tiles:
            lo, hi = prev.plan[i]
            tiles[i] = self._build_tile(per[i], lo, hi,
                                        prev.tiles[i].gen + 1)
            self.snapshot_tile_builds += 1
        self.snapshot_tiles_reused += len(prev.tiles) - len(dirty_tiles)
        return _TileSnap(h, prev.plan, tuple(tiles))

    def _top_k_tiled(self, snap: _TileSnap, k: int, by: str):
        if by not in ("weight", "value"):
            raise ValueError(f"by={by!r}: expected 'weight' or 'value'")
        cands: List[Tuple[float, tuple, float]] = []
        for t in snap.tiles:
            n = len(t.keys)
            if n == 0:
                continue
            if by == "value":
                if t.values is None:
                    raise ValueError(
                        f"sink has non-numeric values; "
                        f"top_k(by='value') needs scalars")
                rank = t.values
            else:
                rank = t.weights
            kk = min(int(k), n)
            idx = np.argpartition(rank, n - kk)[n - kk:]
            for i in idx:
                cands.append((float(rank[i]), t.keys[int(i)],
                              float(t.weights[i])))
        cands.sort(key=lambda c: c[0], reverse=True)
        return (max(snap.horizon, 0),
                [(key, w) for _r, key, w in cands[:int(k)]])

    def top_k(self, sink, k: int, *, by: str = "weight",
              ) -> Tuple[int, List[Tuple[tuple, float]]]:
        """Top ``k`` sink entries at the snapshot's horizon:
        ``(horizon, [((key, value), weight), ...])`` descending.
        ``by="weight"`` ranks by multiset weight; ``by="value"`` ranks
        by the row's scalar value — the natural order for unique-keyed
        aggregate sinks, where the count lives in the value and every
        live row has weight 1. The hot path is a lock-free argpartition
        over frozen arrays. With ``REFLOW_TILE_BYTES`` set, each tile is
        argpartitioned independently and at most k candidates per tile
        are merged — the full state is never concatenated."""
        snap = self._snapshot(sink)
        if isinstance(snap, _TileSnap):
            return self._top_k_tiled(snap, k, by)
        n = len(snap.keys)
        if n == 0:
            return max(snap.horizon, 0), []
        if by == "value":
            if snap.values is None:
                raise ValueError(f"sink {sink!r} has non-numeric values; "
                                 f"top_k(by='value') needs scalars")
            rank = snap.values
        elif by == "weight":
            rank = snap.weights
        else:
            raise ValueError(f"by={by!r}: expected 'weight' or 'value'")
        kk = min(int(k), n)
        idx = np.argpartition(rank, n - kk)[n - kk:]
        idx = idx[np.argsort(rank[idx])[::-1]]
        return snap.horizon, [(snap.keys[int(i)], float(snap.weights[i]))
                              for i in idx]

    def lookup(self, sink, key) -> Tuple[int, float]:
        """Weight of one ``(key, value)`` sink entry at the snapshot's
        horizon (0.0 when absent). Tiled snapshots touch only the
        owning tile's index."""
        snap = self._snapshot(sink)
        if isinstance(snap, _TileSnap):
            t = snap.tiles[_t.owning_tile(snap.plan, _t.bucket_of(key))]
            return max(snap.horizon, 0), float(t.index.get(key, 0.0))
        return max(snap.horizon, 0), float(snap.index.get(key, 0.0))

    def view_at(self, sink) -> Tuple[int, Dict[tuple, float]]:
        """Full sink view copy at the snapshot's horizon — parity
        checks and small views; ``top_k`` is the scaling read."""
        snap = self._snapshot(sink)
        if isinstance(snap, _TileSnap):
            out: Dict[tuple, float] = {}
            for t in snap.tiles:
                out.update(t.index)
            return max(snap.horizon, 0), out
        return max(snap.horizon, 0), dict(snap.index)

    # -- tile-unit bootstrap (wal/ship.py _bootstrap_tiles) ----------------

    def receive_ckpt_tile(self, unit: dict) -> dict:
        """Stage one CRC-framed checkpoint unit (one file of the
        leader's checkpoint directory, tile files included) into
        ``bootstrap-ckpt/``; on the last unit, anchor on the staged
        checkpoint exactly as :meth:`bootstrap` would. Returns
        ``{"ok": True}`` per unit (plus ``"cursor"`` on the last) or
        ``{"ok": False, "reason": ...}`` — a per-unit NACK, so the
        shipper re-sends one tile, not the chain."""
        stage = os.path.join(self.replica_dir, _STAGE_DIR)
        with self._lock:
            if unit.get("schema") != TILE_UNIT_SCHEMA:
                return {"ok": False,
                        "reason": f"schema {unit.get('schema')!r}"}
            idx = int(unit.get("idx", -1))
            if idx == 0:
                # a new transfer: drop any half-staged earlier attempt
                shutil.rmtree(stage, ignore_errors=True)
                self._tile_units_seen = set()
            payload = unit.get("payload") or b""
            if (zlib.crc32(payload) & 0xFFFFFFFF) != unit.get("crc"):
                self.crc_rejects += 1
                return {"ok": False, "reason": "crc mismatch",
                        "idx": idx}
            rel = unit.get("rel") or ""
            parts = rel.replace("\\", "/").split("/")
            if not rel or os.path.isabs(rel) or ".." in parts:
                return {"ok": False, "reason": f"bad relpath {rel!r}"}
            dest = os.path.join(stage, *parts)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(payload)
            self._tile_units_seen.add(idx)
            self.tile_units_received += 1
            if not unit.get("last"):
                return {"ok": True}
            total = int(unit.get("total", 0))
            if len(self._tile_units_seen) != total:
                return {"ok": False,
                        "reason": f"incomplete transfer: "
                                  f"{len(self._tile_units_seen)}/{total} "
                                  f"units staged"}
            cursor = self.bootstrap(stage)
            shutil.rmtree(stage, ignore_errors=True)
            self._tile_units_seen = set()
            return {"ok": True, "cursor": tuple(cursor)}

    # -- failover ----------------------------------------------------------

    @property
    def epoch(self) -> int:
        """Highest epoch this replica has witnessed."""
        return self._epoch

    @property
    def promoted(self) -> bool:
        return self._promoted_sched is not None

    def _truncate_mirror_to_applied(self) -> None:
        """Drop every mirrored byte past the apply point: segments
        beyond it are deleted, the apply-point segment is cut at its
        offset. With ``_applied`` None (nothing ever applied) the whole
        mirror goes — the shipper re-bootstraps."""
        pos = self._applied
        for seq, path in list_segments(self.mirror_dir):
            if pos is None or seq > pos.segment:
                os.remove(path)
            elif seq == pos.segment:
                with open(path, "rb+") as f:
                    f.truncate(pos.offset)

    def promote(self, *, epoch: Optional[int] = None, **durable_kw):
        """Promote this follower to leader. The staged (held-back) tail
        is truncated out of the mirror — a partial commit window never
        survives a failover — then a :class:`DurableScheduler` opens the
        mirror directory as its own WAL in the new epoch (a fresh
        segment; segments are never resumed) and ``recover()`` replays
        the mirrored prefix through the replica's checkpoint. Returns
        the new leader scheduler; idempotent (a second call returns the
        same scheduler). ``durable_kw`` forwards to
        ``DurableScheduler`` (``fsync=``, ``committer=``, ...)."""
        from reflow_tpu.wal.durable import DurableScheduler
        from reflow_tpu.wal.recovery import recover

        t0 = time.perf_counter()
        with self._lock:
            if self._promoted_sched is not None:
                return self._promoted_sched
            new_epoch = int(epoch) if epoch is not None \
                else self._epoch + 1
            if new_epoch <= self._epoch and epoch is not None:
                raise WalError(
                    f"promote epoch {new_epoch} must exceed the "
                    f"replica's witnessed epoch {self._epoch}")
            self._staged.clear()
            self._truncate_mirror_to_applied()
            self._cursor = self._applied
            # the promotion horizon: what this replica had applied when
            # it won the election — the new leader's state is exactly it
            horizon = self._horizon
            sched = DurableScheduler(
                self.graph, wal_dir=self.mirror_dir,
                epoch=new_epoch, **durable_kw)
            report = recover(sched, self.mirror_dir, self.ckpt_dir)
            self._epoch = new_epoch
            self._promoted_sched = sched
            self._persist_cursor()
        if _trace.ENABLED:
            _trace.evt("failover_replay", t0, time.perf_counter() - t0,
                       track=f"replica/{self.name}",
                       args={"epoch": new_epoch, "horizon": horizon,
                             "replayed_pushes": report.replayed_pushes,
                             "replayed_ticks": report.replayed_ticks,
                             "final_tick": report.final_tick})
        # promotion is a die-worthy moment for the flight ring: flush
        # the failover evidence before this process does anything else
        _flight.note("promote", epoch=new_epoch, horizon=horizon)
        if self._hub is not None:
            self._hub.rebase()   # subscribers re-snapshot off the leader
        return sched

    def reanchor(self, epoch: int) -> Optional[Tuple[int, int]]:
        """The surviving followers' half of a failover: drop the
        holdback buffer, truncate the mirror back to the apply point
        (bytes past it may diverge from the new leader's log), adopt the
        new epoch and return the re-anchored cursor — ready for a fresh
        ``shipper.attach``. Applied state is untouched: the apply point
        is always at or below the promotion horizon, so the new leader's
        log extends it byte-identically."""
        with self._lock:
            self._staged.clear()
            self._truncate_mirror_to_applied()
            self._cursor = self._applied
            if epoch > self._epoch:
                self._epoch = epoch
            self._persist_cursor()
            cursor = tuple(self._cursor) if self._cursor is not None \
                else None
        if self._hub is not None:
            self._hub.rebase()   # holdback dropped; re-prove via snapshot
        return cursor

    # -- lifecycle / observability -----------------------------------------

    def publish_metrics(self, registry=None,
                        name: Optional[str] = None) -> None:
        reg = registry if registry is not None else REGISTRY
        base = name or f"replica.{self.name}"
        reg.gauge(f"{base}.lag_ticks", self.lag_ticks)
        reg.gauge(f"{base}.horizon", lambda: self._horizon)
        reg.gauge(f"{base}.records_applied",
                  lambda: self.records_applied)
        reg.gauge(f"{base}.crc_rejects", lambda: self.crc_rejects)
        reg.gauge(f"{base}.staged_records", lambda: len(self._staged))
        reg.gauge(f"{base}.epoch", lambda: self._epoch)
        reg.gauge(f"{base}.fence_rejected_shipments",
                  lambda: self.fence_rejected_shipments)
        reg.gauge(f"{base}.snapshot_tiles",
                  lambda: sum(len(s.tiles)
                              for s in self._snapshots.values()
                              if isinstance(s, _TileSnap)))
        reg.gauge(f"{base}.snapshot_tiles_reused",
                  lambda: self.snapshot_tiles_reused)
        self._metric_names.append((reg, base))

    def close(self) -> None:
        for reg, base in self._metric_names:
            reg.unregister_prefix(base)
        self._metric_names.clear()
