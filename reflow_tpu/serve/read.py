"""ReadTier: fan read queries across replicas with horizon-aware routing.

The router holds N :class:`~reflow_tpu.serve.replica.ReplicaScheduler`s
and answers ``top_k`` / ``lookup`` / ``view_at`` from whichever replica
satisfies the caller's consistency floor:

- ``min_horizon=0`` (default): any replica will do — round-robin so
  aggregate read QPS scales with replica count.
- ``min_horizon=H`` (read-your-writes): a writer that observed its
  window land at leader tick H passes it here; only replicas whose
  published horizon has reached H are eligible, and the result is
  re-checked after the read (a replica may hand back a snapshot built a
  moment before its horizon advanced).
- **Leader fallback**: when no replica has caught up to ``min_horizon``,
  the read goes to the leader adapter — always current, never scalable.
  Leader reads serialize on one lock and copy the live view every time;
  the whole point of the tier is that steady-state traffic never lands
  there (the ``read.leader_fallbacks`` counter says whether yours does).

:class:`LeaderReadAdapter` wraps the leader's scheduler with that
lock-and-copy discipline. The leader's sink views are mutated in place
by the ingest pump's window folds (outside any lock this adapter could
share), so a copy taken mid-fold may observe a torn iteration — the
adapter retries on that, and the *consistency* story stays with the
replicas' published horizons, which is where reads belong.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

from reflow_tpu.net.framing import TransportError
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.utils.runtime import named_lock

__all__ = ["ReadTier", "LeaderReadAdapter", "StaleRead", "ReadResult"]


class StaleRead(RuntimeError):
    """No replica satisfies ``min_horizon`` and no leader to fall back
    on (or the leader itself is behind the requested horizon)."""


class ReadResult(NamedTuple):
    """One routed read: the payload, the horizon it was served at, and
    which backend answered (a replica name or ``"leader"``)."""

    value: object
    horizon: int
    source: str


class LeaderReadAdapter:
    """Leader-side fallback reads: copy the live, mutable sink view
    under one adapter-local lock. The pump folds windows into those
    Counters concurrently, so iteration can be torn mid-fold — retried
    here — and two leader reads never run in parallel. Both costs are
    the point of comparison for the replica path's frozen snapshots."""

    name = "leader"

    def __init__(self, sched, *, tick=None) -> None:
        self.sched = sched
        self._tick = tick if tick is not None else (lambda: sched._tick)
        self._lock = named_lock("serve.read.leader")

    def published_horizon(self) -> int:
        return self._tick()

    def _copy_view(self, sink) -> Dict[tuple, float]:
        name = sink if isinstance(sink, str) else sink.name
        view = self.sched.sink_views[name]
        for _ in range(64):
            try:
                return dict(view)
            except RuntimeError:
                continue  # fold resized the dict mid-copy; go again
        return dict(view)  # let the final attempt raise for real

    def top_k(self, sink, k: int, *, by: str = "weight"):
        with self._lock:
            h = self._tick()
            view = self._copy_view(sink)
        if by == "value":
            key = lambda r: -float(r[0][1])  # noqa: E731
        elif by == "weight":
            key = lambda r: -r[1]  # noqa: E731
        else:
            raise ValueError(f"by={by!r}: expected 'weight' or 'value'")
        rows = sorted(((kv, float(w)) for kv, w in view.items()
                       if w != 0), key=key)
        return h, rows[:int(k)]

    def lookup(self, sink, key):
        with self._lock:
            h = self._tick()
            view = self._copy_view(sink)
        return h, float(view.get(key, 0.0))

    def view_at(self, sink):
        with self._lock:
            h = self._tick()
            view = self._copy_view(sink)
        return h, {kv: float(w) for kv, w in view.items() if w != 0}


class ReadTier:
    """Route reads across replicas by published horizon, falling back
    to the leader only when nothing else is fresh enough."""

    def __init__(self, replicas=(), *, leader: Optional[object] = None,
                 name: str = "read") -> None:
        self.name = name
        self.leader = leader
        self._replicas: List[object] = list(replicas)
        self._rr = itertools.count()
        self._lock = named_lock(f"serve.read.{name}")
        self.replica_reads = 0
        self.leader_fallbacks = 0
        self.stale_reads = 0
        #: replicas pulled from rotation because their link went
        #: unreachable or a read blew up link-side; every _route pass
        #: probes them for restore
        self._ejected: List[object] = []
        #: id(replica) -> link object exposing ``conn_state`` (normally
        #: the shipper's RemoteFollower for the same endpoint)
        self._links: Dict[int, object] = {}
        self.ejects = 0
        self.restores = 0
        self._metric_names: List[Tuple[object, str]] = []

    # -- membership --------------------------------------------------------

    def add_replica(self, replica) -> None:
        with self._lock:
            self._replicas.append(replica)

    def remove_replica(self, replica) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas if r is not replica]
            self._ejected = [r for r in self._ejected if r is not replica]
            self._links.pop(id(replica), None)

    @property
    def replicas(self) -> List[object]:
        with self._lock:
            return list(self._replicas)

    @property
    def ejected_replicas(self) -> List[object]:
        with self._lock:
            return list(self._ejected)

    def bind_link(self, replica, link) -> None:
        """Tie ``replica``'s rotation eligibility to ``link`` (anything
        exposing ``conn_state``, normally the
        :class:`~reflow_tpu.net.client.RemoteFollower` shipping to the
        same endpoint): while the link reports ``unreachable`` the
        replica is ejected from rotation, and it is restored on the
        first probe after recovery."""
        with self._lock:
            self._links[id(replica)] = link

    def _link_unreachable(self, replica) -> bool:
        link = self._links.get(id(replica))
        return link is not None \
            and getattr(link, "conn_state", "local") == "unreachable"

    def _eject(self, replica) -> None:
        with self._lock:
            if any(r is replica for r in self._ejected):
                return
            self._replicas = [r for r in self._replicas
                              if r is not replica]
            self._ejected.append(replica)
            self.ejects += 1

    def _probe_ejected(self) -> None:
        """Restore any ejected replica whose link recovered. Cheap (an
        attribute read per ejected replica), so every routed read runs
        it — recovery latency is one read, not a timer."""
        with self._lock:
            if not self._ejected:
                return
            back = [r for r in self._ejected
                    if not self._link_unreachable(r)]
            if not back:
                return
            self._ejected = [r for r in self._ejected
                             if not any(r is b for b in back)]
            self._replicas.extend(back)
            self.restores += len(back)

    def promote(self, replica, *, epoch: Optional[int] = None,
                **durable_kw):
        """Failover re-point: promote ``replica`` to leader (idempotent
        — an already-promoted replica hands back its scheduler), drop it
        from the read rotation (its snapshots stop advancing as a
        follower's would) and swing the leader fallback to a
        :class:`LeaderReadAdapter` over the new leader. Returns the new
        leader scheduler so the caller (normally
        ``serve.failover.FailoverCoordinator``) can re-bind ingestion
        and shipping too."""
        sched = replica.promote(epoch=epoch, **durable_kw)
        self.remove_replica(replica)
        with self._lock:
            self.leader = LeaderReadAdapter(sched)
        return sched

    # -- routing -----------------------------------------------------------

    def _route(self, op: str, sink, args: tuple,
               min_horizon: int, kwargs: Optional[dict] = None,
               ) -> ReadResult:
        kwargs = kwargs or {}
        self._probe_ejected()
        replicas = self.replicas
        start = next(self._rr)
        n = len(replicas)
        for i in range(n):
            r = replicas[(start + i) % n]
            if self._link_unreachable(r):
                self._eject(r)
                continue
            try:
                if r.published_horizon() < min_horizon:
                    continue
                h, value = getattr(r, op)(sink, *args, **kwargs)
            except (TransportError, ConnectionError, TimeoutError,
                    OSError) as e:
                # link-flavored failure mid-read: out of rotation until
                # a probe sees the link healthy again
                self._eject(r)
                del e
                continue
            if h < min_horizon:
                # the snapshot raced an advancing horizon; this replica
                # is eligible, but this *result* is not — try the next
                continue
            self.replica_reads += 1
            return ReadResult(value, h, getattr(r, "name", "replica"))
        if self.leader is not None \
                and self.leader.published_horizon() >= min_horizon:
            h, value = getattr(self.leader, op)(sink, *args, **kwargs)
            self.leader_fallbacks += 1
            return ReadResult(value, h,
                              getattr(self.leader, "name", "leader"))
        self.stale_reads += 1
        raise StaleRead(
            f"no backend at min_horizon={min_horizon} "
            f"(replica horizons: "
            f"{[r.published_horizon() for r in replicas]}, "
            f"leader: {self.leader.published_horizon() if self.leader is not None else None})")

    def top_k(self, sink, k: int, *, min_horizon: int = 0,
              by: str = "weight") -> ReadResult:
        return self._route("top_k", sink, (k,), min_horizon, {"by": by})

    def lookup(self, sink, key, *, min_horizon: int = 0) -> ReadResult:
        return self._route("lookup", sink, (key,), min_horizon)

    def view_at(self, sink, *, min_horizon: int = 0) -> ReadResult:
        return self._route("view_at", sink, (), min_horizon)

    def max_lag_ticks(self) -> int:
        """Laggiest replica's distance behind the leader tick it last
        saw (the ``replica.lag_ticks`` fleet gauge)."""
        lags = [r.lag_ticks() for r in self.replicas
                if hasattr(r, "lag_ticks")]
        return max(lags) if lags else 0

    def min_horizon_available(self) -> int:
        """Highest horizon any replica currently serves (a writer can
        read-its-writes up to this without touching the leader)."""
        hs = [r.published_horizon() for r in self.replicas]
        return max(hs) if hs else 0

    # -- observability -----------------------------------------------------

    def publish_metrics(self, registry=None,
                        name: Optional[str] = None) -> None:
        reg = registry if registry is not None else REGISTRY
        base = name or self.name
        reg.gauge(f"{base}.replica_reads", lambda: self.replica_reads)
        reg.gauge(f"{base}.leader_fallbacks",
                  lambda: self.leader_fallbacks)
        reg.gauge(f"{base}.stale_reads", lambda: self.stale_reads)
        reg.gauge(f"{base}.replicas", lambda: len(self.replicas))
        reg.gauge(f"{base}.ejected_replicas",
                  lambda: len(self.ejected_replicas))
        reg.gauge(f"{base}.ejects", lambda: self.ejects)
        reg.gauge(f"{base}.restores", lambda: self.restores)
        reg.gauge("replica.lag_ticks", self.max_lag_ticks)
        self._metric_names.append((reg, base))
        self._metric_names.append((reg, "replica.lag_ticks"))

    def close(self) -> None:
        for reg, base in self._metric_names:
            reg.unregister_prefix(base)
        self._metric_names.clear()
