"""ServeTier: many named graphs behind one budget and one pump pool.

One host serves many incremental graphs (per-tenant pagerank, tfidf,
knn …). Standalone ``IngestFrontend``\\ s give each graph a private pump
thread and a private byte budget — N graphs means N unmanaged threads
and no global memory bound. The tier multiplexes instead:

- **one** :class:`~reflow_tpu.serve.budget.AdmissionBudget` spans every
  graph (global in-flight bytes), with per-graph ``floor_bytes``
  (guaranteed reservation — a hot tenant can never push a sibling below
  it) and ``ceiling_bytes`` (hard cap on one graph's usage);
- **one pump pool** of K threads pulls coalesced macro-tick work items
  from the per-graph ready set, picked by deficit-weighted round-robin
  on configured QoS ``weight``\\ s (:func:`dwrr_pick`): over time a
  ready graph receives service proportional to its weight, in units of
  rows served, regardless of how bursty its siblings are.

Single-owner invariant: a scheduler is only ever driven by one thread
at a time. Each graph carries an in-flight latch (the frontend's
``_executing`` flag); a latched graph is simply not ready, so its
macro-tick never interleaves with itself — the pool adds concurrency
ACROSS graphs, never within one.

Concurrency design — one shared lock: the tier's lock is *the* lock of
every registered frontend, every producer-wakeup condition, and the
budget. This is what makes cross-graph wakeups (graph A's commit frees
bytes graph B's producer is blocked on) deadlock-free by construction:
there is no second lock to order against. The pool holds the lock only
to pick/latch work; macro-tick execution runs unlocked.

Reuse, not fork: admission, dedup (``SourceCursor`` + mirror),
coalescing, ticket resolution, and crash semantics all live in the
PR-2 frontend — the tier injects its budget/lock/work-condition and
drives the frontend's external-pump surface (``_poll`` /
``_take_window`` / ``_run_window`` / ``_finish_window``). Durable
graphs keep their own WAL; the pool's window IS the group-commit
window (``DurableScheduler.tick_many`` → ``append_group``, one fsync
per macro-tick).

Failure isolation: a crash inside one graph's macro-tick
(``pool_window@<name>`` / ``pump_*@<name>`` seams) fails THAT graph —
its undecided tickets resolve :class:`PumpCrashed`, its bytes return
to the pool — and the worker thread survives to keep serving siblings.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from reflow_tpu.graph import GraphError
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.obs import trace as _trace

from .budget import AdmissionBudget
from .coalesce import CoalesceWindow
from .frontend import METRIC_WINDOW, IngestFrontend

__all__ = ["GraphConfig", "GraphHandle", "ServeTier", "dwrr_pick"]


@dataclasses.dataclass(frozen=True)
class GraphConfig:
    """Per-graph QoS and admission knobs for :meth:`ServeTier.register`.

    ``weight`` is the DWRR service share (relative rows/s under
    contention). ``floor_bytes`` / ``ceiling_bytes`` are this graph's
    guaranteed / maximum slice of the tier's byte budget. ``policy`` /
    ``queue_batches`` / ``window`` are the frontend's backpressure
    policy, per-source depth bound, and coalescing window.
    ``admission`` keys the byte charge: ``"host"`` = payload bytes,
    ``"device"`` = ingress-queue slot bytes, ``"auto"`` = device iff
    the graph's executor advertises the mega-tick window path.

    ``placement`` / ``device`` bind the graph's executor to one mesh
    device at register time, so K tenants run their mega-tick windows
    on K chips concurrently instead of serializing on the default
    device: ``placement="spread"`` round-robins over ``jax.devices()``,
    ``placement="pin"`` (or just ``device=``) pins to the given
    ``jax.Device`` / device index. ``"none"`` leaves the executor
    wherever it already runs — which is also how a sharded hot tenant
    (``ShardedTpuExecutor``, spanning the mesh) registers.
    """

    weight: float = 1.0
    floor_bytes: int = 0
    ceiling_bytes: Optional[int] = None
    policy: str = "block"
    queue_batches: int = 256
    window: Optional[CoalesceWindow] = None
    crash: Optional[object] = None  # CrashInjector override (tests)
    admission: str = "auto"
    #: None | jax.Device | int index into jax.devices() (implies "pin")
    device: Optional[object] = None
    placement: str = "none"  # "none" | "spread" | "pin"
    #: pipelined window depth for this graph's pump (None = the
    #: frontend's REFLOW_WINDOW_DEPTH default; 1 = serial windows)
    window_depth: Optional[int] = None


def dwrr_pick(ready: List["GraphHandle"], quantum_rows: int,
              busy_devices: frozenset = frozenset()) -> "GraphHandle":
    """Deficit-weighted round-robin over the ready graphs.

    Each graph carries a rolling deficit in row units. When every ready
    graph is out of deficit, all of them are replenished by
    ``weight * quantum_rows``; the pick is the largest positive
    deficit, and the caller charges the rows actually served after the
    window runs. Long-run service among continuously-ready graphs is
    therefore proportional to weight, independent of burst shape; a
    graph that is rarely ready is never replenished in absentia, so it
    cannot hoard deficit and then monopolize the pool.

    ``busy_devices`` makes the pick placement-aware: among the
    positive-deficit candidates, graphs whose bound device currently
    has NO window in flight are preferred (largest deficit among them),
    so co-located tenants stop contending for a chip while other chips
    idle. Deficit accounting is untouched — a deferred graph keeps its
    deficit and wins as soon as its device frees up, so long-run
    weighted fairness is preserved; only the service ORDER shifts. When
    every candidate's device is busy (or devices are untagged) the pick
    falls back to pure DWRR.
    """
    while all(h._deficit <= 0 for h in ready):
        for h in ready:
            h._deficit += h.config.weight * quantum_rows
    cands = [h for h in ready if h._deficit > 0]
    free = [h for h in cands
            if h.device_label is None
            or h.device_label not in busy_devices]
    return max(free or cands, key=lambda h: h._deficit)


class GraphHandle:
    """One registered graph: the producer-facing proxy plus the tier's
    per-graph scheduling state. Returned by :meth:`ServeTier.register`;
    ``submit`` / ``flush`` / ``drain`` forward to the underlying
    :class:`IngestFrontend` (``handle.frontend`` for everything else)."""

    def __init__(self, tier: "ServeTier", name: str,
                 frontend: IngestFrontend, config: GraphConfig):
        self.tier = tier
        self.name = name
        self.frontend = frontend
        self.config = config
        # -- pool scheduling state (under the tier lock) --
        self._deficit = 0.0
        #: when the graph's current ready stretch began (None while not
        #: ready / latched) — scheduling delay is sampled on pick
        self._ready_since: Optional[float] = None
        self.windows = 0
        self.rows_applied = 0
        #: windows that crashed THIS graph (the control plane's
        #: circuit-breaker input)
        self.crashes = 0
        self.sched_delay_s: Deque[float] = deque(maxlen=METRIC_WINDOW)

    @property
    def weight(self) -> float:
        return self.config.weight

    @property
    def device_label(self) -> Optional[str]:
        """Where this graph's windows execute: the executor's obs tag
        (``"cpu:3"`` for a pinned tenant, ``"mesh[8]"`` for a sharded
        one, None on the default device)."""
        sched = getattr(self.frontend, "sched", None)
        return getattr(getattr(sched, "executor", None),
                       "device_label", None)

    def submit(self, source, batch, **kw):
        return self.frontend.submit(source, batch, **kw)

    def flush(self, timeout: Optional[float] = None) -> None:
        self.frontend.flush(timeout)

    def drain(self, source=None, **kw) -> int:
        return self.frontend.drain(source, **kw)

    def rebind(self, sched) -> None:
        """Failover re-point: revive this graph's (crashed) frontend
        over a NEW scheduler — normally a promoted replica's
        ``DurableScheduler``. The graph stays registered, producers keep
        this handle, and resubmissions of batches the dead leader never
        committed are re-admitted through the rebuilt dedup mirror
        (see ``IngestFrontend.revive``). The old scheduler is left to
        its owner — a fenced zombie may still be flailing at it."""
        self.frontend.revive(sched=sched)

    def __repr__(self) -> str:
        return (f"GraphHandle({self.name!r}, weight={self.config.weight}, "
                f"state={self.frontend._state!r})")


class ServeTier:
    """Host many named graphs on one admission budget and one pump pool.

    ``max_bytes``: the tier-wide in-flight payload budget shared by all
    graphs. ``pump_threads``: pool size K (macro-ticks of *different*
    graphs run concurrently; one graph is always single-owner).
    ``quantum_rows``: the DWRR replenish quantum. ``crash``: a
    ``CrashInjector`` for the pool seams (tests only).
    """

    def __init__(self, *, max_bytes: int = 256 << 20,
                 pump_threads: int = 2, quantum_rows: int = 4096,
                 crash=None):
        if pump_threads <= 0:
            raise ValueError(
                f"pump_threads must be positive, got {pump_threads}")
        self.quantum_rows = quantum_rows
        self._crash = crash
        self._lock = named_lock("serve.tier")
        #: the pool's (and every frontend's) work condition: producers
        #: notify on admit, workers notify on window finish
        self._work = threading.Condition(self._lock)
        self.budget = AdmissionBudget(max_bytes)
        self._graphs: Dict[str, GraphHandle] = {}
        self._closed = False
        #: round-robin cursor for placement="spread" registrations
        self._place_counter = 0
        # -- counters (utils.metrics.summarize_tier) --
        self.windows = 0
        self.pool_crashes = 0
        #: completed checkpoint_barrier() cuts (the barrier seq)
        self.barriers = 0
        #: picks whose graph's device already had a window in flight —
        #: the placement-aware DWRR tie-break could not avoid the
        #: contention (every positive-deficit candidate was co-located
        #: with busy hardware). trace_inspect's per-device breakdown
        #: shows the resulting skew.
        self.device_collisions = 0
        self._busy_s = 0.0
        self._metric_keys: List = []
        self._t0 = time.perf_counter()
        self.pump_threads = pump_threads
        # -- pool supervision state (under the tier lock) --
        #: how many live workers the pool SHOULD have; the supervisor
        #: (ensure_workers) respawns toward it, scale_pool retunes it
        self._target_threads = pump_threads
        #: workers asked to exit at their next loop top (scale-down)
        self._retiring = 0
        self._next_worker_id = 0
        self.worker_deaths = 0
        self.worker_respawns = 0
        self.last_worker_error: Optional[BaseException] = None
        self._threads: List[threading.Thread] = []
        with self._lock:
            for _ in range(pump_threads):
                self._spawn_worker_locked()

    # -- registry ----------------------------------------------------------

    def register(self, name: str, sched,
                 config: Optional[GraphConfig] = None) -> GraphHandle:
        """Host ``sched`` (Dirty- or DurableScheduler) as graph
        ``name``. The scheduler must not be driven directly from now
        until :meth:`unregister` — the pool owns it."""
        cfg = config if config is not None else GraphConfig()
        if cfg.weight <= 0:
            raise ValueError(
                f"QoS weight must be positive, got {cfg.weight} "
                f"for {name!r}")
        placement = cfg.placement
        if placement not in ("none", "spread", "pin"):
            raise ValueError(
                f"placement must be 'none', 'spread' or 'pin', got "
                f"{placement!r} for {name!r}")
        if cfg.device is not None and placement == "none":
            placement = "pin"  # device= alone means: pin to it
        if placement == "pin" and cfg.device is None:
            raise ValueError(
                f"placement='pin' needs device= for {name!r}")
        with self._lock:
            if self._closed:
                raise GraphError("tier is closed; register refused")
            if name in self._graphs:
                raise ValueError(f"graph {name!r} already registered")
            if placement != "none":
                ex = getattr(sched, "executor", None)
                if not hasattr(ex, "place"):
                    raise GraphError(
                        f"graph {name!r}: placement={placement!r} needs an "
                        f"executor with place() (TpuExecutor); "
                        f"{type(ex).__name__} has none")
                if placement == "spread":
                    import jax

                    devs = jax.devices()
                    dev = devs[self._place_counter % len(devs)]
                    self._place_counter += 1
                else:
                    dev = cfg.device
                # a sharded executor raises here (it spans the mesh)
                ex.place(dev)
            share = self.budget.register(
                name, floor=cfg.floor_bytes, ceiling=cfg.ceiling_bytes)
            try:
                fe = IngestFrontend(
                    sched, policy=cfg.policy,
                    queue_batches=cfg.queue_batches, window=cfg.window,
                    crash=cfg.crash if cfg.crash is not None
                    else self._crash,
                    start=False, budget=share, lock=self._lock,
                    work=self._work, name=name,
                    admission=cfg.admission, depth=cfg.window_depth)
            except BaseException:
                self.budget.unregister(name)
                raise
            handle = GraphHandle(self, name, fe, cfg)
            self._graphs[name] = handle
            return handle

    def handle(self, name: str) -> GraphHandle:
        with self._lock:
            return self._graphs[name]

    def graphs(self) -> Dict[str, GraphHandle]:
        with self._lock:
            return dict(self._graphs)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, name: str, source=None, **kw) -> int:
        """Quiesce one graph in place (flush its backlog, run the
        scheduler's deferred-fixpoint drain) without unregistering it —
        siblings keep ticking throughout. Returns the drain tick
        count."""
        return self.handle(name).drain(source, **kw)

    def checkpoint_barrier(self, saver, *, names: Optional[List[str]]
                           = None) -> Dict[str, object]:
        """Tier-wide checkpoint barrier: one consistent cut across all
        (or ``names``) graphs. Every frontend is paused — each quiesces
        at a macro-tick boundary, so each graph's cut is a whole-window
        horizon — and only once ALL of them are idle does
        ``saver(name, handle)`` run per graph against the frozen
        schedulers (a ``CheckpointChain.save``, a ``save_checkpoint``,
        a state probe — the tier does not care). Admission keeps
        queueing throughout; producers block at the budget, they are
        not failed. Resumes everything even when a saver raises.

        Returns ``{"barrier": seq, "horizons": {name: tick},
        "results": {name: saver result}}`` — the horizons are the
        per-graph macro-tick cut the chain manifests record, which is
        what makes cross-tenant restore consistent: every graph's
        checkpoint in one barrier observes a single quiesced tier."""
        with self._lock:
            if self._closed:
                raise GraphError("tier is closed; barrier refused")
            if names is None:
                handles = dict(self._graphs)
            else:
                handles = {n: self._graphs[n] for n in names}
            self.barriers += 1
            seq = self.barriers
        paused: List[GraphHandle] = []
        results: Dict[str, object] = {}
        t0 = time.perf_counter()
        try:
            for h in handles.values():
                h.frontend.pause()
                paused.append(h)
            horizons = {n: h.frontend.sched._tick
                        for n, h in handles.items()}
            for n, h in handles.items():
                results[n] = saver(n, h)
        finally:
            for h in paused:
                h.frontend.resume()
        if _trace.ENABLED:
            _trace.evt("checkpoint_barrier", t0,
                       time.perf_counter() - t0,
                       args={"barrier": seq,
                             "graphs": sorted(handles)})
        return {"barrier": seq, "horizons": horizons,
                "results": results}

    def unregister(self, name: str, *, flush: bool = True,
                   timeout: Optional[float] = None) -> GraphHandle:
        """Quiesce and remove one graph: admission stops, blocked
        producers are released with ``FrontendClosed``, the pool ticks
        out its backlog (``flush=True``) or its tickets fail
        (``flush=False``), the scheduler's WAL (if durable) is sealed,
        and its budget share returns to the pool. Siblings never stall:
        the pool keeps serving them while this graph drains."""
        with self._lock:
            h = self._graphs.get(name)
            if h is None:
                raise KeyError(f"no graph {name!r} registered")
        h.frontend.close(flush=flush, timeout=timeout)
        with self._lock:
            self._graphs.pop(name, None)
            self.budget.unregister(name)
        return h

    def close(self, *, flush: bool = True,
              timeout: Optional[float] = None) -> None:
        """Drain and unregister every graph, then stop the pool.
        Idempotent."""
        with self._lock:
            names = list(self._graphs)
        for n in names:
            try:
                self.unregister(n, flush=flush, timeout=timeout)
            except KeyError:
                pass  # a concurrent unregister won the race
        with self._lock:
            self._closed = True
            self._work.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"tier close() timed out after {timeout}s waiting "
                    f"for {t.name}")
        for reg, key in self._metric_keys:
            reg.unregister_source(key)
            reg.unregister_prefix(f"{key}.")
        self._metric_keys = []
        self.budget.unpublish_metrics()

    def __enter__(self) -> "ServeTier":
        return self

    def __exit__(self, *exc) -> None:
        self.close(flush=exc == (None, None, None))

    # -- metrics -----------------------------------------------------------

    def publish_metrics(self, registry=None, *, name: str = "tier"
                        ) -> str:
        """Register the tier's live summary (``summarize_tier``
        schema, every graph nested) plus shared-budget occupancy gauges
        as obs metric sources; unregistered at :meth:`close`. Returns
        the source key."""
        from reflow_tpu.obs import REGISTRY
        from reflow_tpu.utils.metrics import summarize_tier
        reg = registry if registry is not None else REGISTRY
        reg.register_source(name,
                            lambda: summarize_tier(self).to_dict())
        reg.gauge(f"{name}.pump_utilization",
                  lambda: self.pump_utilization)
        reg.gauge(f"{name}.budget_used_bytes", lambda: self.budget.used)
        reg.gauge(f"{name}.budget_occupancy",
                  lambda: self.budget.used / self.budget.total_bytes)
        reg.gauge(f"{name}.live_workers", lambda: self.live_workers)
        reg.gauge(f"{name}.worker_deaths", lambda: self.worker_deaths)
        reg.gauge(f"{name}.device_collisions",
                  lambda: self.device_collisions)
        self._metric_keys.append((reg, name))
        return name

    @property
    def pump_utilization(self) -> float:
        """Busy-fraction of the pool since construction: macro-tick
        seconds / (threads x wall seconds)."""
        elapsed = time.perf_counter() - self._t0
        if elapsed <= 0:
            return 0.0
        return self._busy_s / (self.pump_threads * elapsed)

    # -- pool supervision / elasticity -------------------------------------

    def _spawn_worker_locked(self) -> None:
        t = threading.Thread(
            target=self._pool_loop,
            name=f"reflow-tier-pump-{self._next_worker_id}", daemon=True)
        self._next_worker_id += 1
        self._threads.append(t)
        t.start()

    def _reap_locked(self) -> None:
        self._threads = [t for t in self._threads if t.is_alive()]

    @property
    def live_workers(self) -> int:
        """Pool workers currently alive (dead ones are respawned by
        :meth:`ensure_workers`; retirees from a scale-down exit at their
        next loop top)."""
        return sum(1 for t in self._threads if t.is_alive())

    def ensure_workers(self) -> int:
        """Respawn dead pool workers back to the target size — the
        supervision seam the control plane ticks. A worker that dies
        (a bug escaping the per-window isolation, a deliberate
        ``pool_worker@*`` seam) would otherwise shrink effective
        parallelism for the life of the tier. Returns how many workers
        were spawned (0 = pool already at target)."""
        with self._lock:
            if self._closed:
                return 0
            self._reap_locked()
            spawned = 0
            while (len(self._threads) - self._retiring
                   < self._target_threads):
                self._spawn_worker_locked()
                spawned += 1
            self.worker_respawns += spawned
            return spawned

    def scale_pool(self, target: int) -> int:
        """Retune the pool to ``target`` workers — the autoscaling
        actuator. Growing spawns immediately; shrinking marks the excess
        to retire at their next loop top (never mid-window). Clamped to
        at least 1. Returns the new target."""
        with self._lock:
            if self._closed:
                return self._target_threads
            target = max(1, int(target))
            self._target_threads = target
            self.pump_threads = target  # utilization denominator
            self._reap_locked()
            planned = len(self._threads) - self._retiring
            if planned < target:
                for _ in range(target - planned):
                    self._spawn_worker_locked()
            elif planned > target:
                self._retiring += planned - target
                self._work.notify_all()  # idle workers retire in wait()
            return target

    @property
    def ready_depth(self) -> int:
        """How many graphs have a fireable window RIGHT NOW (the
        autoscaler's backlog signal; racy-but-fine telemetry)."""
        with self._lock:
            now = time.perf_counter()
            return sum(1 for h in self._graphs.values()
                       if h.frontend._poll(now)[0])

    # -- the pool ----------------------------------------------------------

    def _pool_loop(self) -> None:
        # worker death (anything escaping _pool_iteration, including
        # the per-window isolation handler itself failing) is recorded
        # so the supervisor can respawn back to target — a silent exit
        # here is the pool-capacity leak
        try:
            while self._pool_iteration():
                pass
        except BaseException as e:  # noqa: BLE001 - supervision boundary
            with self._lock:
                self.worker_deaths += 1
                self.last_worker_error = e
                self._work.notify_all()

    def _pool_iteration(self) -> bool:
        # one pick + macro-tick (or one settle-only pass over a graph
        # with retired work pending); False = exit this worker
        with self._lock:
            picked = None
            settle_h: Optional[GraphHandle] = None
            while picked is None:
                if self._closed:
                    return False
                if self._retiring > 0:
                    self._retiring -= 1
                    return False
                now = time.perf_counter()
                ready: List[GraphHandle] = []
                wait_t: Optional[float] = None
                for h in self._graphs.values():
                    fire, w = h.frontend._poll(now)
                    if fire:
                        if h._ready_since is None:
                            h._ready_since = now
                        ready.append(h)
                    else:
                        # not ready (or latched by a sibling
                        # worker): the ready stretch is over
                        h._ready_since = None
                        if w is not None:
                            wait_t = (w if wait_t is None
                                      else min(wait_t, w))
                if ready:
                    # placement-aware tie-break: devices with a window
                    # (or unretired pipeline) in flight are "busy" —
                    # prefer candidates whose chip is idle
                    busy = frozenset(
                        h.device_label for h in self._graphs.values()
                        if h.device_label is not None
                        and (h.frontend._executing
                             or h.frontend._inflight))
                    picked = dwrr_pick(ready, self.quantum_rows, busy)
                    if (picked.device_label is not None
                            and picked.device_label in busy):
                        self.device_collisions += 1
                    ready_since = picked._ready_since
                    picked.sched_delay_s.append(now - ready_since)
                    picked._ready_since = None
                    if _trace.ENABLED:
                        _trace.evt("pool_pick", ready_since,
                                   now - ready_since,
                                   args={"graph": picked.name,
                                         "device": picked.device_label})
                    drained = picked.frontend._take_window(
                        ready_since=ready_since)
                else:
                    # nothing fireable: retire any graph's dispatched-
                    # but-unsettled pipelined windows (their tickets
                    # wire to the durable watermark here, and pause/
                    # close waiters unblock)
                    settle_h = next(
                        (h for h in self._graphs.values()
                         if h.frontend._needs_settle()), None)
                    if settle_h is not None:
                        settle_h.frontend._begin_settle()
                        break
                    self._work.wait(timeout=wait_t)
        if settle_h is not None:
            t0 = time.perf_counter()
            crashed = False
            try:
                settle_h.frontend._settle_all()
            except BaseException as e:  # noqa: BLE001 - fault isolation
                crashed = True
                # count the crash BEFORE tickets fail: an observer who
                # caught a PumpCrashed result must already see it
                with self._lock:
                    self.pool_crashes += 1
                    settle_h.crashes += 1
                settle_h.frontend._on_pump_crash(e)
            with self._lock:
                self._busy_s += time.perf_counter() - t0
                if not crashed:
                    settle_h.frontend._finish_window()
                self._work.notify_all()
            return True
        # -- macro-tick, unlocked (single-owner: the latch set by
        # _take_window keeps every other worker off this graph) --
        t0 = time.perf_counter()
        crashed = False
        try:
            if self._crash is not None:
                self._crash.point(f"pool_window@{picked.name}")
            picked.frontend._run_window(drained)
        except BaseException as e:  # noqa: BLE001 - fault isolation
            crashed = True
            # count the crash BEFORE tickets fail: an observer who
            # caught a PumpCrashed result must already see it
            with self._lock:
                self.pool_crashes += 1
                picked.crashes += 1
            picked.frontend._on_pump_crash(e, window=drained)
            # _on_pump_crash released the latch, the graph's bytes,
            # and its blocked producers
        busy = time.perf_counter() - t0
        rows = sum(e.rows for entries in drained.values()
                   for e in entries)
        with self._lock:
            self._busy_s += busy
            self.windows += 1
            picked.windows += 1
            picked._deficit -= max(rows, 1)
            if not crashed:
                picked.rows_applied += rows
                picked.frontend._finish_window()
            # re-evaluate readiness pool-wide: the just-unlatched
            # graph may have accrued backlog, and idle workers only
            # wake on notify
            self._work.notify_all()
        # deliberate WORKER-death seam (vs pool_window@, which crashes
        # the graph): fires between windows, after the graph is settled,
        # so the only casualty is this thread — exactly the capacity
        # leak the supervisor exists to heal
        if self._crash is not None:
            self._crash.point(f"pool_worker@{picked.name}")
        return True
