"""Self-healing control plane: the actuator loop over the obs gauges.

PRs 2–5 instrumented the serving tier end to end — pump utilization,
per-graph sched-delay p99, shared-budget occupancy, ``wal.queue_depth``
/ ``wal.durable_lag_s`` — but nothing *acted* on the signals: a hot
tenant could pin the tier at its admission ceiling forever, a crashed
pump worker silently shrank pool parallelism for the life of the
process, and a dead WAL committer poisoned every later append. This
module closes the loop. A :class:`ControlPlane` samples those gauges on
a fixed interval and drives three actuator families against the tier:

**Graceful overload degradation** — each graph gets an :class:`SLOSpec`
(sched-delay p99, durable lag, budget occupancy; ``None`` thresholds
are skipped). On ``breach_intervals`` consecutive breached samples the
controller steps THAT graph down a brownout ladder of admission
policies (configured policy → ``"reject"`` → ``"shed-oldest"``), and
steps back up one rung per ``recover_intervals`` consecutive clean
samples (hysteresis — a flapping gauge can't oscillate the policy). A
hot-tenant surge therefore degrades the surging tenant while quiet
siblings keep their configured admission behavior; QoS-selective
shedding falls out of the per-graph specs (give high-QoS graphs no
spec, an empty ladder, or set ``ControlConfig.protect_weight``).

**Supervision and self-healing** — a graph whose window crashed
(``PumpCrashed``; frontend state ``"failed"``) is revived with
exponential backoff plus jitter, behind a per-graph crash-storm
circuit breaker: K crashes inside a sliding window opens the breaker
(the graph stays quarantined, submissions fail fast), a cooldown later
a half-open probe revives it once, and only a probe that stays healthy
for ``probe_intervals`` samples closes the breaker again (a probe
crash re-opens it with a doubled cooldown). A dead WAL committer under
a still-running durable graph is respawned via
``WriteAheadLog.restart_committer()`` at most
``max_committer_restarts`` times — after that the graph fails fast
instead of looping. Dead pool *workers* (the capacity leak) are
respawned every tick via ``ServeTier.ensure_workers()``.

**Elasticity and rebalancing** — an :class:`Autoscaler` grows the pump
pool on sustained ready-graph backlog exceeding the live worker count
and shrinks it on sustained idle, clamped to ``[min_workers,
max_workers]``; idle-graph budget reclaim shrinks a quiet graph's floor
to the bytes it actually holds (returning the reservation tier-wide,
under the shared budget lock) and restores the configured floor the
moment traffic returns.

Design for testability: every policy lives in a standalone state
machine (:class:`BrownoutLadder`, :class:`CircuitBreaker`,
:class:`Autoscaler`) driven by plain ``observe``/``poll`` calls, and
:class:`ControlPlane.step` takes an explicit ``now`` plus an injectable
``sampler``/``clock``/``rng`` — the state-machine tests run on a fake
clock with injected gauge sequences, no sleeps anywhere.

Lock discipline: actuation (policy flips, budget resizes) happens under
the tier lock; WAL calls (``durable_lag_s``, ``restart_committer``)
happen with the tier lock RELEASED, because the committer thread takes
the tier lock while holding the WAL lock (durable callbacks resolve
tickets), so the reverse order here would deadlock.

Observability of the observer: the loop publishes ``control.*`` action
counters (brownouts entered/exited, respawns, breaker opens/closes,
scale events, reclaims) and a ``pool.live_workers`` gauge through the
same :class:`MetricsRegistry`, and emits ``control.<action>`` trace
spans when tracing is enabled — ``tools/trace_inspect.py`` surfaces
them alongside the data-path spans.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from reflow_tpu.obs import trace as _trace
from reflow_tpu.utils.metrics import percentile

from .frontend import POLICIES

__all__ = ["SLOSpec", "BrownoutLadder", "CircuitBreaker", "Autoscaler",
           "ControlConfig", "ControlPlane", "load_slo_specs"]


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One graph's service-level objective and its brownout ladder.

    A threshold of ``None`` skips that signal. ``breach_intervals``
    consecutive breached control samples step the graph DOWN one rung
    of ``ladder``; ``recover_intervals`` consecutive clean samples step
    it back UP one rung (each rung of recovery needs a full clean
    streak — the hysteresis that keeps a borderline gauge from
    flapping the policy). The ladder rungs are admission policies
    applied in order after the graph's configured policy.
    """

    #: cross-graph scheduling delay bound (s): time a ready window
    #: waited for a pool thread, p99 over the metric window
    sched_delay_p99_s: Optional[float] = None
    #: age bound (s) on the oldest pending durability request
    durable_lag_s: Optional[float] = None
    #: bound on the graph's share usage / its byte cap (0..1)
    budget_occupancy: Optional[float] = None
    breach_intervals: int = 3
    recover_intervals: int = 5
    ladder: Tuple[str, ...] = ("reject", "shed-oldest")

    def __post_init__(self):
        for p in self.ladder:
            if p not in POLICIES:
                raise ValueError(
                    f"ladder policy {p!r} not in {POLICIES}")
        if self.breach_intervals <= 0 or self.recover_intervals <= 0:
            raise ValueError("breach/recover intervals must be >= 1")

    def breached(self, info: Dict) -> bool:
        """Does one control sample (a per-graph gauge dict) breach this
        SLO? Missing keys read as healthy."""
        if (self.sched_delay_p99_s is not None
                and info.get("sched_delay_p99_s", 0.0)
                > self.sched_delay_p99_s):
            return True
        if (self.durable_lag_s is not None
                and info.get("durable_lag_s", 0.0) > self.durable_lag_s):
            return True
        if (self.budget_occupancy is not None
                and info.get("occupancy", 0.0) > self.budget_occupancy):
            return True
        return False


def load_slo_specs(path: str) -> Dict[str, SLOSpec]:
    """Parse per-graph :class:`SLOSpec`s from a JSON config file so
    operators can retune brownout ladders without code::

        {"default_slo": {"sched_delay_p99_s": 0.5},
         "specs": {"hot-tenant": {"budget_occupancy": 0.9,
                                  "ladder": ["reject", "shed-oldest"],
                                  "breach_intervals": 2}}}

    ``default_slo`` (optional) supplies field defaults every spec
    inherits; each entry under ``specs`` overrides per graph. Unknown
    fields and invalid ladder policies fail loudly — a typo'd config
    must not silently disable an SLO."""
    import json

    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: SLO config must be a JSON object")
    unknown = set(raw) - {"specs", "default_slo"}
    if unknown:
        raise ValueError(f"{path}: unknown top-level keys {sorted(unknown)}")
    fields = {f.name for f in dataclasses.fields(SLOSpec)}

    def build(name: str, entry: Dict) -> SLOSpec:
        if not isinstance(entry, dict):
            raise ValueError(f"{path}: spec {name!r} must be an object")
        bad = set(entry) - fields
        if bad:
            raise ValueError(f"{path}: spec {name!r} has unknown "
                             f"fields {sorted(bad)} (valid: "
                             f"{sorted(fields)})")
        merged = dict(raw.get("default_slo") or {})
        merged.update(entry)
        if "ladder" in merged:
            merged["ladder"] = tuple(merged["ladder"])
        return SLOSpec(**merged)

    if raw.get("default_slo"):
        bad = set(raw["default_slo"]) - fields
        if bad:
            raise ValueError(f"{path}: default_slo has unknown fields "
                             f"{sorted(bad)}")
    return {name: build(name, entry)
            for name, entry in (raw.get("specs") or {}).items()}


class BrownoutLadder:
    """Per-graph brownout state machine: level 0 is the configured
    policy, level i>0 is ``ladder[i-1]``. Driven by one
    :meth:`observe` per control interval; returns the new policy
    string when (and only when) the level changed."""

    def __init__(self, base_policy: str,
                 ladder: Tuple[str, ...] = ("reject", "shed-oldest"),
                 *, breach_intervals: int = 3, recover_intervals: int = 5):
        # duplicate rungs (e.g. a base policy already in the ladder)
        # collapse — stepping "down" to the same policy is a no-op rung
        levels: List[str] = [base_policy]
        for p in ladder:
            if p not in levels:
                levels.append(p)
        self.levels: Tuple[str, ...] = tuple(levels)
        self.breach_intervals = breach_intervals
        self.recover_intervals = recover_intervals
        self.level = 0
        self._breach_streak = 0
        self._ok_streak = 0

    @property
    def policy(self) -> str:
        return self.levels[self.level]

    def observe(self, breached: bool) -> Optional[str]:
        """Feed one interval's breach verdict; returns the policy to
        actuate when the level moved, else None."""
        if breached:
            self._ok_streak = 0
            self._breach_streak += 1
            if (self._breach_streak >= self.breach_intervals
                    and self.level < len(self.levels) - 1):
                self.level += 1
                self._breach_streak = 0
                return self.levels[self.level]
            return None
        self._breach_streak = 0
        if self.level == 0:
            self._ok_streak = 0
            return None
        self._ok_streak += 1
        if self._ok_streak >= self.recover_intervals:
            self.level -= 1
            self._ok_streak = 0  # next rung up needs a fresh streak
            return self.levels[self.level]
        return None


class CircuitBreaker:
    """Crash-storm breaker + respawn backoff for one graph.

    States: ``"closed"`` (normal; each crash schedules a revive after
    an exponentially-backed-off, jittered delay) → ``"open"`` (K
    crashes inside ``window_s``: quarantined, submissions fail fast,
    no revives) → ``"half_open"`` (cooldown elapsed: ONE probe revive)
    → ``"closed"`` again once the probe stays healthy for
    ``probe_intervals`` polls; a crash while half-open re-opens with a
    doubled (capped) cooldown. Pure state machine: callers feed
    :meth:`record_crash` on observed crashes and :meth:`poll` once per
    control interval, acting on the returned verdicts.
    """

    def __init__(self, *, max_crashes: int = 3, window_s: float = 10.0,
                 backoff_s: float = 0.05, backoff_max_s: float = 2.0,
                 cooldown_s: float = 0.5, cooldown_max_s: float = 8.0,
                 probe_intervals: int = 2, jitter_frac: float = 0.2,
                 rng: Optional[Callable[[], float]] = None):
        if max_crashes <= 0:
            raise ValueError("max_crashes must be >= 1")
        self.max_crashes = max_crashes
        self.window_s = window_s
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.cooldown_base_s = cooldown_s
        self.cooldown_max_s = cooldown_max_s
        self.probe_intervals = probe_intervals
        self.jitter_frac = jitter_frac
        self._rng = rng if rng is not None else random.random
        self.state = "closed"
        self.crashes = 0
        self.opens = 0
        self._crash_times: Deque[float] = deque()
        self._respawn_at: Optional[float] = None
        self._consecutive_respawns = 0
        self._opened_at: Optional[float] = None
        self._cooldown = cooldown_s
        self._healthy_polls = 0

    def respawn_delay(self) -> float:
        """The backoff the NEXT closed-state respawn would use (before
        jitter): exponential in respawns since the last confirmed
        healthy stretch, capped."""
        return min(self.backoff_s * (2 ** self._consecutive_respawns),
                   self.backoff_max_s)

    def record_crash(self, now: float) -> str:
        """Feed one observed crash; returns the resulting state."""
        self.crashes += 1
        self._crash_times.append(now)
        while (self._crash_times
               and now - self._crash_times[0] > self.window_s):
            self._crash_times.popleft()
        self._healthy_polls = 0
        if self.state == "half_open":
            # the probe itself crashed: back off harder
            self.state = "open"
            self.opens += 1
            self._opened_at = now
            self._cooldown = min(self._cooldown * 2, self.cooldown_max_s)
            self._respawn_at = None
            return self.state
        if len(self._crash_times) >= self.max_crashes:
            self.state = "open"
            self.opens += 1
            self._opened_at = now
            self._respawn_at = None
            return self.state
        # closed, storm threshold not reached: schedule a backed-off,
        # jittered revive
        delay = self.respawn_delay()
        delay *= 1.0 + self.jitter_frac * self._rng()
        self._consecutive_respawns += 1
        self._respawn_at = now + delay
        return self.state

    def poll(self, now: float, *, healthy: bool) -> Optional[str]:
        """One control interval; ``healthy`` is whether the graph is
        currently running. Returns an action verdict:

        - ``"respawn"`` — closed-state backoff elapsed, revive now;
        - ``"probe"`` — cooldown elapsed, transitioned to half-open,
          revive ONCE as the probe;
        - ``"close"`` — the probe proved out, breaker closed (reset);
        - ``None`` — nothing to do this interval.
        """
        if self.state == "closed":
            if not healthy:
                if (self._respawn_at is not None
                        and now >= self._respawn_at):
                    self._respawn_at = None
                    return "respawn"
                return None
            self._healthy_polls += 1
            if self._healthy_polls >= self.probe_intervals:
                self._consecutive_respawns = 0  # backoff resets
            return None
        if self.state == "open":
            if now - self._opened_at >= self._cooldown:
                self.state = "half_open"
                self._healthy_polls = 0
                return "probe"
            return None
        # half_open: the probe revive happened; wait for it to prove out
        # (a crash arrives via record_crash and re-opens)
        if not healthy:
            return None
        self._healthy_polls += 1
        if self._healthy_polls >= self.probe_intervals:
            self.state = "closed"
            self._cooldown = self.cooldown_base_s
            self._consecutive_respawns = 0
            self._crash_times.clear()
            return "close"
        return None


class Autoscaler:
    """Pump-pool sizing policy: grow one worker after
    ``grow_intervals`` consecutive samples with more ready graphs than
    live workers; shrink one after ``shrink_intervals`` consecutive
    fully-idle samples; always clamp into ``[min_workers,
    max_workers]`` (an out-of-range live count returns a clamping
    target immediately). Returns the new target, or None to hold."""

    def __init__(self, *, min_workers: int = 1, max_workers: int = 8,
                 grow_intervals: int = 3, shrink_intervals: int = 10):
        if not 1 <= min_workers <= max_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"[{min_workers}, {max_workers}]")
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.grow_intervals = grow_intervals
        self.shrink_intervals = shrink_intervals
        self._backlog_streak = 0
        self._idle_streak = 0

    def observe(self, ready_depth: int, live: int) -> Optional[int]:
        if live < self.min_workers:
            self._backlog_streak = self._idle_streak = 0
            return self.min_workers
        if live > self.max_workers:
            self._backlog_streak = self._idle_streak = 0
            return self.max_workers
        if ready_depth > live:
            self._idle_streak = 0
            self._backlog_streak += 1
            if self._backlog_streak >= self.grow_intervals:
                self._backlog_streak = 0
                if live < self.max_workers:
                    return live + 1
            return None
        self._backlog_streak = 0
        if ready_depth == 0:
            self._idle_streak += 1
            if self._idle_streak >= self.shrink_intervals:
                self._idle_streak = 0
                if live > self.min_workers:
                    return live - 1
            return None
        self._idle_streak = 0
        return None


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Tuning knobs for :class:`ControlPlane` (see docs/guide.md
    "Control plane" for the operator's view)."""

    #: control sample/actuation period (the loop thread's tick)
    interval_s: float = 0.05
    #: SLO applied to graphs without an explicit spec (None = none)
    default_slo: Optional[SLOSpec] = None
    #: graphs with QoS weight >= this are never browned out, even under
    #: default_slo (QoS-protected tenants); None disables the carve-out
    protect_weight: Optional[float] = None
    # -- supervision --
    #: master switch for crash revives (breaker still tracks crashes)
    respawn: bool = True
    max_crashes: int = 3
    crash_window_s: float = 10.0
    respawn_backoff_s: float = 0.05
    respawn_backoff_max_s: float = 2.0
    breaker_cooldown_s: float = 0.5
    breaker_cooldown_max_s: float = 8.0
    probe_intervals: int = 2
    jitter_frac: float = 0.2
    #: dead-WAL-committer respawn budget per graph; exhausted = the
    #: graph fails fast instead of looping (respawn-or-fail-fast)
    max_committer_restarts: int = 3
    #: dead-WAL-compactor respawn budget (same respawn-or-fail-fast
    #: stance: a compactor that keeps dying has hit real corruption,
    #: and the log is merely unbounded without it, never wrong)
    max_compactor_restarts: int = 3
    # -- elasticity --
    #: pump-pool autoscale range; None disables autoscaling
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None
    grow_intervals: int = 3
    shrink_intervals: int = 10
    #: consecutive idle intervals before a quiet graph's budget floor
    #: is reclaimed tier-wide (0 disables)
    reclaim_idle_intervals: int = 0
    # -- subscription shedding (ControlPlane(subs=hub)) --
    #: queued fan-out windows above which subscription fan-out counts
    #: as breaching (the hub is falling behind the apply path); None
    #: disables the backlog signal
    sub_backlog_windows_max: Optional[int] = 8
    #: slowest-subscriber lag (ticks behind the fan-out horizon) above
    #: which fan-out counts as breaching; None disables the lag signal
    sub_lag_windows_max: Optional[int] = None
    #: consecutive breached/recovered intervals before the subs ladder
    #: steps (conflate -> pause) or relaxes
    sub_breach_intervals: int = 3
    sub_recover_intervals: int = 5


class _GraphControl:
    """Per-graph controller state (ladder + breaker + reclaim/committer
    bookkeeping), keyed by handle identity so an unregister/re-register
    under the same name starts fresh."""

    __slots__ = ("handle", "spec", "ladder", "breaker", "last_state",
                 "committer_restarts_used", "reclaimed", "idle_streak",
                 "windows_last")

    def __init__(self, handle, spec: Optional[SLOSpec],
                 cfg: ControlConfig, rng: Callable[[], float]):
        self.handle = handle
        self.spec = spec
        self.ladder = None
        if spec is not None and spec.ladder:
            self.ladder = BrownoutLadder(
                handle.config.policy, spec.ladder,
                breach_intervals=spec.breach_intervals,
                recover_intervals=spec.recover_intervals)
        self.breaker = CircuitBreaker(
            max_crashes=cfg.max_crashes, window_s=cfg.crash_window_s,
            backoff_s=cfg.respawn_backoff_s,
            backoff_max_s=cfg.respawn_backoff_max_s,
            cooldown_s=cfg.breaker_cooldown_s,
            cooldown_max_s=cfg.breaker_cooldown_max_s,
            probe_intervals=cfg.probe_intervals,
            jitter_frac=cfg.jitter_frac, rng=rng)
        self.last_state = "running"
        self.committer_restarts_used = 0
        self.reclaimed = False
        self.idle_streak = 0
        self.windows_last = 0


class ControlPlane:
    """The supervision thread: sample → decide → actuate, once per
    ``config.interval_s``. Construct over a live :class:`ServeTier`,
    optionally with per-graph ``specs``; ``start()`` spawns the daemon
    loop (or drive :meth:`step` by hand — tests and benches do).

    ``sampler``/``clock``/``rng`` are injectable for determinism: the
    sampler returns the gauge dict :meth:`_default_sample` would
    (``{"graphs": {name: {...}}, "ready_depth": int, "live_workers":
    int}``), the clock feeds every state machine, the rng drives
    respawn jitter.
    """

    def __init__(self, tier, *, specs: Optional[Dict[str, SLOSpec]] = None,
                 config: Optional[ControlConfig] = None,
                 config_path: Optional[str] = None, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[Callable[[], float]] = None,
                 sampler: Optional[Callable[[float], Dict]] = None,
                 failover=None, compactor=None, fleet=None, subs=None):
        from reflow_tpu.obs import REGISTRY
        self.tier = tier
        #: optional serve.failover.FailoverCoordinator, stepped on the
        #: control interval — leader-death detection and promotion ride
        #: the same supervision loop as the other actuators
        self.failover = failover
        #: optional wal.compact.WalCompactor, supervised on the control
        #: interval with the committer's respawn-or-fail-fast budget
        self.compactor = compactor
        #: optional obs.fleet.FleetAggregator: fleet gauges consulted
        #: on the control interval. Advisory only — a lag-spread breach
        #: is surfaced as an action + counter, never actuated, because
        #: telemetry is allowed to be stale or absent (the inversion
        #: the fleet plane is built on)
        self.fleet = fleet
        self._fleet_breached = False
        #: optional subs.hub.SubscriptionHub: subscription fan-out is
        #: the one read-side load the control plane actuates, because
        #: it shares the replica process with the apply path — the
        #: shedding ladder degrades push freshness (conflate, then
        #: pause) before write-path SLOs breach
        self.subs = subs
        self._compactor_restarts_used = 0
        self._compactor_failed = False
        self._compactor_booted = False
        # file first, explicit specs= override per graph — an operator
        # config sets the fleet default, code pins the exceptions
        self.specs = (dict(load_slo_specs(config_path))
                      if config_path is not None else {})
        if specs:
            self.specs.update(specs)
        self.config = config if config is not None else ControlConfig()
        self.registry = registry if registry is not None else REGISTRY
        self._clock = clock
        self._rng = rng if rng is not None else random.random
        self._sampler = sampler
        self._ctl: Dict[str, _GraphControl] = {}
        self._autoscaler: Optional[Autoscaler] = None
        if (self.config.min_workers is not None
                or self.config.max_workers is not None):
            lo = self.config.min_workers or 1
            hi = self.config.max_workers or max(lo, tier.pump_threads)
            self._autoscaler = Autoscaler(
                min_workers=lo, max_workers=hi,
                grow_intervals=self.config.grow_intervals,
                shrink_intervals=self.config.shrink_intervals)
        self.ticks = 0
        self.errors = 0
        self.last_error: Optional[BaseException] = None
        #: recent actuations (dicts: now/kind/graph), for tests/benches
        self.actions: Deque[Dict] = deque(maxlen=1024)
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sub_ladder = (BrownoutLadder(
            "normal", ("conflate", "pause"),
            breach_intervals=self.config.sub_breach_intervals,
            recover_intervals=self.config.sub_recover_intervals)
            if subs is not None else None)
        reg = self.registry
        self._c = {k: reg.counter(f"control.{k}") for k in (
            "ticks", "brownouts_entered", "brownouts_exited",
            "brownout_steps", "respawns", "breaker_opens",
            "breaker_probes", "breaker_closes", "worker_respawns",
            "committer_restarts", "scale_ups", "scale_downs",
            "reclaims", "floor_restores", "errors",
            "compactions", "compactor_restarts",
            "fleet_lag_breaches", "sub_shed_steps",
            "sub_shed_recovers")}
        reg.gauge("pool.live_workers", lambda: self.tier.live_workers)
        reg.gauge("control.interval_s", lambda: self.config.interval_s)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ControlPlane":
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._loop, name="reflow-control", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_ev.wait(self.config.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 - loop must survive
                self.errors += 1
                self.last_error = e
                self._c["errors"].inc()

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.registry.unregister_prefix("control.")
        self.registry.unregister_prefix("pool.")

    def __enter__(self) -> "ControlPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- introspection (tests/benches) -------------------------------------

    def level(self, name: str) -> int:
        """Current brownout rung for graph ``name`` (0 = configured
        policy; no ladder reads as 0)."""
        ctl = self._ctl.get(name)
        if ctl is None or ctl.ladder is None:
            return 0
        return ctl.ladder.level

    def breaker_state(self, name: str) -> str:
        ctl = self._ctl.get(name)
        return "closed" if ctl is None else ctl.breaker.state

    @property
    def sub_shed_level(self) -> int:
        """Current subscription shedding rung (0 normal, 1 conflate,
        2 pause); 0 when no hub is attached."""
        return 0 if self._sub_ladder is None else self._sub_ladder.level

    # -- the control loop --------------------------------------------------

    def step(self, now: Optional[float] = None) -> List[Dict]:
        """One sample → decide → actuate pass; returns this tick's
        actions. Thread-driven in production; called directly (with an
        explicit fake ``now``) by tests and benches."""
        now = self._clock() if now is None else now
        if self.tier._closed:
            return []
        sample = (self._sampler(now) if self._sampler is not None
                  else self._default_sample())
        self.ticks += 1
        self._c["ticks"].inc()
        actions: List[Dict] = []
        handles = self.tier.graphs()
        # controller GC: drop graphs that left; a same-name re-register
        # is a different handle and starts with fresh machines
        for name in list(self._ctl):
            if self._ctl[name].handle is not handles.get(name):
                del self._ctl[name]
        for name, info in sample.get("graphs", {}).items():
            h = handles.get(name)
            if h is None:
                continue
            ctl = self._ctl.get(name)
            if ctl is None:
                ctl = self._ctl[name] = _GraphControl(
                    h, self._spec_for(h), self.config, self._rng)
            self._step_brownout(now, name, ctl, info, actions)
            self._step_supervision(now, name, ctl, info, actions)
            self._step_reclaim(now, name, ctl, info, actions)
        self._step_pool(now, sample, actions)
        if self.failover is not None:
            actions.extend(self.failover.step(now))
        if self.compactor is not None:
            self._step_compactor(now, actions)
        if self.fleet is not None:
            self._step_fleet(now, actions)
        if self.subs is not None:
            self._step_subs(now, actions)
        for a in actions:
            self._record(a)
        return actions

    def _step_fleet(self, now: float, actions: List[Dict]) -> None:
        """Consult the fleet aggregator's cross-node gauges. A lag
        spread past the aggregator's threshold raises an *advisory*
        action, edge-triggered (one per breach episode, one more on
        recovery) — the operator decides; this loop never actuates on
        telemetry that is allowed to be stale."""
        try:
            snap = self.fleet.fleet_snapshot()
        except Exception:  # noqa: BLE001 - telemetry loss is tolerated
            return
        gauges = snap.get("gauges", {})
        spread = gauges.get("lag_spread")
        limit = getattr(self.fleet, "lag_spread_max", None)
        breached = (spread is not None and limit is not None
                    and spread > limit)
        if breached and not self._fleet_breached:
            self._c["fleet_lag_breaches"].inc()
            actions.append({"now": now, "kind": "fleet_lag_spread",
                            "advisory": True,
                            "lag_spread": spread, "limit": limit,
                            "stale_nodes": gauges.get("nodes_stale", 0),
                            "alerts": list(snap.get("alerts", []))})
        elif self._fleet_breached and not breached:
            actions.append({"now": now, "kind": "fleet_lag_recovered",
                            "advisory": True, "lag_spread": spread})
        self._fleet_breached = breached

    def _step_subs(self, now: float, actions: List[Dict]) -> None:
        """Drive the subscription shedding ladder off the hub's own
        load signals (work-queue backlog, slowest-subscriber lag).
        Unlike the fleet hook this one actuates: fan-out shares the
        replica process with the apply path, so degrading push
        freshness — conflate (level 1), then pause (level 2) — is how
        write-path SLOs stay whole under subscriber overload. The
        ladder's hysteresis (breach/recover streaks) keeps it from
        flapping on one bursty window."""
        cfg = self.config
        if (cfg.sub_backlog_windows_max is None
                and cfg.sub_lag_windows_max is None):
            return
        try:
            load = self.subs.load()
        except Exception:  # noqa: BLE001 - a closing hub must not kill the control loop; next interval re-reads
            return
        backlog = load.get("backlog_windows") or 0
        lag = load.get("slowest_lag")
        breached = (
            (cfg.sub_backlog_windows_max is not None
             and backlog > cfg.sub_backlog_windows_max)
            or (cfg.sub_lag_windows_max is not None and lag is not None
                and lag > cfg.sub_lag_windows_max))
        before = self._sub_ladder.level
        moved = self._sub_ladder.observe(breached)
        if moved is None:
            return
        level = self._sub_ladder.level
        self.subs.set_shed_level(level)
        kind = "sub_shed_step" if level > before else "sub_shed_recover"
        self._c["sub_shed_steps" if level > before
                else "sub_shed_recovers"].inc()
        actions.append({"now": now, "kind": kind, "level": level,
                        "mode": moved, "backlog_windows": backlog,
                        "slowest_lag": lag,
                        "active_subs": load.get("active")})

    def _step_compactor(self, now: float, actions: List[Dict]) -> None:
        """Supervise the background WAL compactor: surface completed
        passes as actions, respawn a dead thread within the budget,
        fail fast past it (unbounded log, loudly — not a wrong one)."""
        comp = self.compactor
        for ev in comp.drain_events():
            self._c["compactions"].inc()
            actions.append({"now": now, "kind": "wal_compact",
                            "out": ev["out"], "covers": ev["covers"],
                            "segments": ev["segments"],
                            "reclaimed_bytes": ev["reclaimed_bytes"],
                            "gen": ev["gen"]})
        if comp.alive or self._compactor_failed:
            return
        if not self._compactor_booted:
            # first sight of a cold compactor: the control plane owns
            # its lifecycle — boot it for free, budget only respawns
            comp.start()
            self._compactor_booted = True
            return
        cfg = self.config
        if self._compactor_restarts_used >= cfg.max_compactor_restarts:
            self._compactor_failed = True
            actions.append({"now": now, "kind": "compactor_failed",
                            "error": repr(comp.last_error),
                            "used": self._compactor_restarts_used})
            return
        if comp.restart():
            self._compactor_restarts_used += 1
            self._c["compactor_restarts"].inc()
            actions.append({"now": now, "kind": "compactor_restart",
                            "used": self._compactor_restarts_used,
                            "error": repr(comp.last_error)})

    def _spec_for(self, h) -> Optional[SLOSpec]:
        spec = self.specs.get(h.name, self.config.default_slo)
        if (spec is not None and self.config.protect_weight is not None
                and h.config.weight >= self.config.protect_weight):
            return None  # QoS-protected: never browned out
        return spec

    def _default_sample(self) -> Dict:
        tier = self.tier
        graphs: Dict[str, Dict] = {}
        wals: Dict[str, object] = {}
        with tier._lock:
            live = tier.live_workers
            target = tier._target_threads
            t = time.perf_counter()
            ready = 0
            for name, h in tier._graphs.items():
                fe = h.frontend
                fire, _w = fe._poll(t)
                if fire:
                    ready += 1
                share = fe._budget
                cap = max(1, share.ceiling)
                graphs[name] = {
                    "state": fe._state,
                    "policy": fe.policy,
                    "queued_batches": fe._queues.queued_batches,
                    "bytes_used": share.used,
                    "occupancy": share.used / cap,
                    "sched_delay_p99_s": percentile(
                        list(h.sched_delay_s), 99),
                    "windows": h.windows,
                    "durable_lag_s": 0.0,
                    "committer_dead": False,
                }
                wal = getattr(fe.sched, "wal", None)
                if wal is not None:
                    wals[name] = wal
        # WAL reads OUTSIDE the tier lock (see module docstring: the
        # committer holds the WAL lock when it takes the tier lock)
        for name, wal in wals.items():
            err = wal.committer_error
            graphs[name]["committer_dead"] = err is not None
            if err is None:
                graphs[name]["durable_lag_s"] = wal.durable_lag_s()
        return {"graphs": graphs, "ready_depth": ready,
                "live_workers": live, "target_workers": target}

    # -- actuator family 1: graceful overload degradation ------------------

    def _step_brownout(self, now: float, name: str, ctl: _GraphControl,
                       info: Dict, actions: List[Dict]) -> None:
        if ctl.ladder is None or info.get("state") != "running":
            return
        before = ctl.ladder.level
        new_policy = ctl.ladder.observe(ctl.spec.breached(info))
        if new_policy is None:
            return
        with self.tier._lock:
            fe = ctl.handle.frontend
            fe.policy = new_policy
            # blocked producers re-check the (new) policy on wakeup
            fe._not_full.notify_all()
        level = ctl.ladder.level
        if level > before:
            if before == 0:
                self._c["brownouts_entered"].inc()
            self._c["brownout_steps"].inc()
            actions.append({"now": now, "kind": "brownout_step",
                            "graph": name, "level": level,
                            "policy": new_policy})
        else:
            if level == 0:
                self._c["brownouts_exited"].inc()
            actions.append({"now": now, "kind": "brownout_recover",
                            "graph": name, "level": level,
                            "policy": new_policy})

    # -- actuator family 2: supervision / self-healing ---------------------

    def _step_supervision(self, now: float, name: str,
                          ctl: _GraphControl, info: Dict,
                          actions: List[Dict]) -> None:
        cfg = self.config
        state = info.get("state", "running")
        failed = state == "failed"
        # a committer that died under a still-RUNNING graph is healed
        # before the next window would poison the whole graph
        if (info.get("committer_dead") and not failed
                and self._restart_committer(now, name, ctl, actions)):
            pass
        if failed and ctl.last_state != "failed":
            verdict = ctl.breaker.record_crash(now)
            if verdict == "open":
                self._c["breaker_opens"].inc()
                actions.append({"now": now, "kind": "breaker_open",
                                "graph": name,
                                "crashes": ctl.breaker.crashes})
                # a breaker trip is a moment the process may not
                # outlive — flush it to the flight ring eagerly
                from reflow_tpu.obs import flight as _flight
                _flight.note("breaker_open", graph=name,
                             crashes=ctl.breaker.crashes)
        ctl.last_state = state
        if not cfg.respawn:
            return
        verdict = ctl.breaker.poll(now, healthy=not failed)
        if verdict in ("respawn", "probe"):
            if verdict == "probe":
                self._c["breaker_probes"].inc()
                actions.append({"now": now, "kind": "breaker_probe",
                                "graph": name})
            if self._revive(now, name, ctl, actions):
                ctl.last_state = "running"
            else:
                # revive impossible (committer budget exhausted, state
                # raced): counts as a failed attempt — the breaker backs
                # off or opens instead of hot-looping
                ctl.breaker.record_crash(now)
        elif verdict == "close":
            self._c["breaker_closes"].inc()
            actions.append({"now": now, "kind": "breaker_close",
                            "graph": name})

    def _restart_committer(self, now: float, name: str,
                           ctl: _GraphControl,
                           actions: List[Dict]) -> bool:
        cfg = self.config
        if ctl.committer_restarts_used >= cfg.max_committer_restarts:
            return False  # fail fast from here on
        wal = getattr(ctl.handle.frontend.sched, "wal", None)
        if wal is None or not wal.restart_committer():
            return False
        ctl.committer_restarts_used += 1
        self._c["committer_restarts"].inc()
        actions.append({"now": now, "kind": "committer_restart",
                        "graph": name,
                        "used": ctl.committer_restarts_used})
        return True

    def _revive(self, now: float, name: str, ctl: _GraphControl,
                actions: List[Dict]) -> bool:
        fe = ctl.handle.frontend
        wal = getattr(fe.sched, "wal", None)
        if wal is not None and wal.committer_error is not None:
            if not self._restart_committer(now, name, ctl, actions):
                return False
        try:
            fe.revive()
        except Exception:  # noqa: BLE001 - state raced; retry next tick
            return False
        self._c["respawns"].inc()
        actions.append({"now": now, "kind": "respawn", "graph": name})
        return True

    # -- actuator family 3: elasticity / rebalancing -----------------------

    def _step_reclaim(self, now: float, name: str, ctl: _GraphControl,
                      info: Dict, actions: List[Dict]) -> None:
        cfg = self.config
        floor_cfg = ctl.handle.config.floor_bytes
        if not cfg.reclaim_idle_intervals or floor_cfg <= 0:
            return
        windows = info.get("windows", 0)
        idle = (info.get("state") == "running"
                and info.get("queued_batches", 0) == 0
                and info.get("bytes_used", 0) == 0
                and windows == ctl.windows_last)
        ctl.windows_last = windows
        if idle:
            if ctl.reclaimed:
                return
            ctl.idle_streak += 1
            if ctl.idle_streak < cfg.reclaim_idle_intervals:
                return
            with self.tier._lock:
                try:
                    # shrink to the bytes actually held (0 when idle):
                    # the unused reservation returns tier-wide
                    self.tier.budget.resize(name, floor=0)
                except (KeyError, ValueError):
                    return
            ctl.reclaimed = True
            self._c["reclaims"].inc()
            actions.append({"now": now, "kind": "floor_reclaim",
                            "graph": name, "floor_bytes": floor_cfg})
            return
        ctl.idle_streak = 0
        if not ctl.reclaimed:
            return
        with self.tier._lock:
            try:
                self.tier.budget.resize(name, floor=floor_cfg)
            except (KeyError, ValueError):
                return  # not reservable right now; retry next tick
        ctl.reclaimed = False
        self._c["floor_restores"].inc()
        actions.append({"now": now, "kind": "floor_restore",
                        "graph": name, "floor_bytes": floor_cfg})

    def _step_pool(self, now: float, sample: Dict,
                   actions: List[Dict]) -> None:
        spawned = self.tier.ensure_workers()
        if spawned:
            self._c["worker_respawns"].inc(spawned)
            actions.append({"now": now, "kind": "worker_respawn",
                            "count": spawned})
        if self._autoscaler is None:
            return
        live = sample.get("live_workers", self.tier.live_workers)
        target = self._autoscaler.observe(
            sample.get("ready_depth", 0), live)
        if target is None or target == live:
            return
        self.tier.scale_pool(target)
        if target > live:
            self._c["scale_ups"].inc()
            kind = "scale_up"
        else:
            self._c["scale_downs"].inc()
            kind = "scale_down"
        actions.append({"now": now, "kind": kind, "workers": target})

    # -- recording ---------------------------------------------------------

    def _record(self, action: Dict) -> None:
        self.actions.append(action)
        if _trace.ENABLED:
            t = time.perf_counter()
            args = {k: v for k, v in action.items() if k != "now"}
            _trace.evt(f"control.{action['kind']}", t, 0.0,
                       track="control", args=args)
