"""Admission queues: per-source bounded FIFOs under an injected byte
budget.

Pure data structure — every method is called with the frontend's lock
held; no locking happens here. The two admission limits compose:

- ``max_batches`` bounds each SOURCE's queue depth (a slow source can't
  starve the rest);
- the :class:`~reflow_tpu.serve.budget.BudgetShare` bounds the TOTAL
  in-flight payload (queued + currently executing) — per frontend when
  the frontend built its own budget, across every graph of a
  ``ServeTier`` when the share belongs to a tier-wide
  ``AdmissionBudget``.

What happens when a limit is hit is the frontend's backpressure policy
(``block`` / ``reject`` / ``shed-oldest``); this module only answers
"is there room" and "which entries would shedding evict".
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional

from reflow_tpu.graph import Node

from .tickets import Ticket

__all__ = ["Entry", "SourceQueues", "batch_nbytes"]


def batch_nbytes(batch) -> int:
    """Payload bytes of a delta batch, duck-typed over the columns so
    host (numpy) and device (jax) batches both answer without a device
    sync (``.nbytes`` is metadata on both)."""
    return sum(int(getattr(col, "nbytes", 0) or 0)
               for col in (batch.keys, batch.values, batch.weights))


@dataclasses.dataclass
class Entry:
    """One admitted micro-batch waiting for (or riding) a macro-tick."""

    ticket: Ticket
    source: Node
    batch: object                # DeltaBatch or device-resident batch
    batch_id: str
    nbytes: int
    t_admitted: float
    #: device-resident batches ride a feed slot ALONE (the
    #: one-per-source-per-tick rule; host concat would force a readback)
    device: bool
    #: host row count (0 for device batches — len() would read back)
    rows: int
    #: host-side pre-image of a device batch, captured at submit()
    #: BEFORE upload so a durable scheduler can log it without a forced
    #: readback (None for host batches or when the producer has none)
    preimage: object = None


class SourceQueues:
    def __init__(self, max_batches: int, budget):
        self.max_batches = max_batches
        #: BudgetShare holding this graph's in-flight bytes (queued +
        #: executing); acquire on push, release on shed/commit
        self.budget = budget
        self._q: Dict[int, Deque[Entry]] = {}
        self.queued_batches = 0
        self.queued_rows = 0
        self.queued_bytes = 0
        #: bytes drained into an executing macro-tick but not yet
        #: committed — still counted against the budget
        self.executing_bytes = 0

    @property
    def max_bytes(self) -> int:
        """This frontend's effective byte cap (guaranteed-reachable
        in-flight total) — the reject-reason bound."""
        return self.budget.max_alone

    # -- admission ---------------------------------------------------------

    def room_for(self, source_id: int, nbytes: int) -> bool:
        depth = len(self._q.get(source_id, ()))
        return depth < self.max_batches and self.budget.room_for(nbytes)

    def fits_alone(self, nbytes: int) -> bool:
        """Could this batch EVER be admitted (empty queues)? False means
        the batch alone exceeds the byte budget — reject, don't shed."""
        return self.budget.fits_alone(nbytes)

    def push(self, entry: Entry) -> None:
        self._q.setdefault(entry.source.id, deque()).append(entry)
        self.queued_batches += 1
        self.queued_rows += entry.rows
        self.queued_bytes += entry.nbytes
        self.budget.acquire(entry.nbytes)

    def shed_for(self, source_id: int, nbytes: int) -> List[Entry]:
        """Evict oldest-first until ``room_for`` holds: first from the
        submitting source's own queue (depth limit), then globally
        oldest (byte budget; only THIS graph's entries are sheddable —
        a tier sibling's backlog is never another graph's to evict).
        Returns the evicted entries — the caller resolves their tickets
        as SHED."""
        out: List[Entry] = []
        q = self._q.get(source_id)
        while q and len(q) >= self.max_batches:
            out.append(self._pop_entry(q))
        while not self.budget.room_for(nbytes):
            oldest: Optional[Deque[Entry]] = None
            for dq in self._q.values():
                if dq and (oldest is None
                           or dq[0].t_admitted < oldest[0].t_admitted):
                    oldest = dq
            if oldest is None:
                break  # nothing left to shed (executing bytes or a
                # sibling graph's admissions hold the budget)
            out.append(self._pop_entry(oldest))
        return out

    def _pop_entry(self, dq: Deque[Entry]) -> Entry:
        e = dq.popleft()
        self.queued_batches -= 1
        self.queued_rows -= e.rows
        self.queued_bytes -= e.nbytes
        self.budget.release(e.nbytes)
        return e

    # -- pump side ---------------------------------------------------------

    def oldest_t(self) -> Optional[float]:
        ts = [dq[0].t_admitted for dq in self._q.values() if dq]
        return min(ts) if ts else None

    def pending_feed_rounds(self, max_rows: int) -> int:
        """How many macro-tick feeds the current backlog would unfold
        into (the max-ticks coalescing trigger): per source, each
        device batch needs its own feed slot and host rows pack
        ``max_rows`` per slot; feeds form in parallel across sources,
        so the count is the max over sources."""
        rounds = 0
        for dq in self._q.values():
            dev = sum(1 for e in dq if e.device)
            host_rows = sum(e.rows for e in dq if not e.device)
            r = dev + (host_rows + max_rows - 1) // max_rows if dq else 0
            rounds = max(rounds, r)
        return rounds

    def drain_all(self) -> Dict[int, List[Entry]]:
        """Take the whole backlog (per-source FIFO order preserved);
        their bytes move to ``executing_bytes`` — still held against
        the budget — until the caller calls :meth:`commit_executing`."""
        out = {sid: list(dq) for sid, dq in self._q.items() if dq}
        self.executing_bytes += self.queued_bytes
        self._q.clear()
        self.queued_batches = 0
        self.queued_rows = 0
        self.queued_bytes = 0
        return out

    def commit_executing(self) -> None:
        if self.executing_bytes:
            self.budget.release(self.executing_bytes)
        self.executing_bytes = 0

    def release_executing(self, nbytes: int) -> int:
        """Release part of ``executing_bytes`` back to the budget — the
        pipelined pump's stage-complete release: once a window's rows
        are slot-written into the device ingress queue, their HOST
        payload no longer occupies the frontend, so producers may be
        admitted against that room while the window is still in flight.
        Clamped to what is actually held; returns the bytes released."""
        n = min(int(nbytes), self.executing_bytes)
        if n > 0:
            self.executing_bytes -= n
            self.budget.release(n)
        return n
