"""IngestFrontend: backpressured multi-producer admission onto one
scheduler.

The paper's tick-synchronous model assumes *someone* feeds the
scheduler; this is that someone. N concurrent producers call
``submit(source, batch)`` from their own threads; a single **pump**
owns the scheduler (``DirtyScheduler`` or ``DurableScheduler`` — never
touch it directly while the frontend is running), coalesces the queued
micro-batches into ``tick_many`` macro-ticks, and resolves each
submission's :class:`~reflow_tpu.serve.tickets.Ticket`.

Admission control (per submit, in order):

1. **id mint / dedup** — a missing ``batch_id`` is minted through
   ``SourceCursor`` (restart-safe: cursors resume past the scheduler's
   recovered dedup window); a duplicate id resolves the ticket
   ``DEDUPED`` immediately, never silently dropped.
2. **backpressure** — per-source queue depth + the in-flight byte
   budget (a :class:`~reflow_tpu.serve.budget.BudgetShare`), with the
   configured policy: ``block`` (wait for room; a ``close()`` releases
   blocked producers with :class:`FrontendClosed`), ``reject`` (resolve
   ``REJECTED`` now), ``shed-oldest`` (evict the oldest admitted
   entries — their tickets resolve ``SHED`` — to admit the newer one).

Two pump deployments share all of the above (the refactor the serving
tier forced — admission and pumping are **injectable**):

- ``start=True`` (default): the frontend owns a private pump thread —
  the PR-2 standalone shape.
- ``start=False`` + ``lock=``/``work=``/``budget=``: an external pump
  pool (``serve.tier.ServeTier``) drives the frontend through
  ``_poll`` / ``_take_window`` / ``_run_window`` / ``_finish_window``,
  under a lock shared with sibling graphs. The ``_executing`` flag is
  the per-graph in-flight latch: a graph's macro-tick never interleaves
  with itself, whoever pumps it.

Steady-state traffic rides the fused streaming path: the pump calls
``tick_many`` (never a synchronous ``tick``), so on a device executor
no mid-stream forced syncs happen — the zero-``forced_syncs`` property
``REFLOW_BENCH_SERVE=1`` asserts.

Durability pipeline (durable schedulers): the pump never blocks on an
fsync. ``tick_many(wait_durable=False)`` returns once the window's WAL
records are written+flushed; ticket resolution is deferred into a
:class:`_ResBlock` registered with ``wal.when_durable(lsn, ...)`` and
fires when the committer thread's fsync passes the window's LSN — so
window N's disk latency overlaps window N+1's merge and dispatch, while
commit-before-resolve holds (a crash between execute and fsync leaves
the tickets unresolved; upstream re-sends, replay dedups). Device
batches submitted with ``preimage=`` log the host pre-image instead of
paying a device readback (``DurableScheduler.push_preimage``).

Crash seams (``utils.faults.CrashInjector``): ``producer_submit`` /
``producer_admitted`` on the submitting thread, ``pump_coalesce`` /
``pump_before_tick`` / ``pump_after_tick`` on the pump. A named
frontend (tier-managed) scopes its seams as ``<seam>@<name>`` so one
graph of a pool can be killed in isolation. A pump kill fails every
undecided ticket with :class:`PumpCrashed` and releases blocked
producers; a durable scheduler's WAL then carries exactly-once across
``recover()`` + upstream re-send.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from reflow_tpu.graph import GraphError, Node
from reflow_tpu.obs import trace as _trace
from reflow_tpu.scheduler import SourceCursor
from reflow_tpu.utils.config import env_int
from reflow_tpu.utils.runtime import named_lock

from .budget import AdmissionBudget
from .coalesce import CoalesceWindow, build_feeds
from .queues import Entry, SourceQueues, batch_nbytes
from .tickets import (APPLIED, DEDUPED, REJECTED, SHED, FrontendClosed,
                      PumpCrashed, Ticket, TicketResult)

__all__ = ["IngestFrontend"]

POLICIES = ("block", "reject", "shed-oldest")


@dataclasses.dataclass
class _ResBlock:
    """One executed chunk awaiting its durability point: the tickets of
    a ``tick_many`` call whose WAL records are written but possibly not
    yet fsynced. Resolution fires from ``wal.when_durable`` — on the
    committer thread when the fsync overlapped later work, inline on
    the pump when the LSN was already durable."""

    #: (entry, committed tick, coalesced_with) per micro-batch
    items: List[Tuple[Entry, int, int]]
    lsn: int
    nticks: int
    t_ready: float
    t_exec0: float
    t_exec1: float


@dataclasses.dataclass
class _InflightWindow:
    """One dispatched-but-unretired pipelined window: the scheduler's
    staged handle (whose retire re-adopts the donated queue generation)
    plus the :class:`_ResBlock` whose durability wiring happens at the
    retire step — both deliberately OFF the stage→dispatch critical
    path."""

    handle: object               # scheduler _StagedTicks
    block: _ResBlock


#: per-sample metric retention: percentile summaries only need a recent
#: window, and a long-running serving process must not grow them forever
METRIC_WINDOW = 4096


class IngestFrontend:
    """Thread-safe streaming ingestion frontend over one scheduler.

    ``policy``: backpressure policy (``block`` / ``reject`` /
    ``shed-oldest``). ``queue_batches``: per-source queue bound.
    ``max_bytes``: in-flight payload budget (ignored when ``budget`` is
    injected). ``window``: the coalescing window (rows / ticks /
    latency triggers). ``crash``: a ``CrashInjector`` wired to the
    documented seams (tests only).

    Tier injection (``serve.tier`` wires these; standalone callers
    leave them defaulted): ``budget`` — a ``BudgetShare`` of a shared
    ``AdmissionBudget``; ``lock`` — the lock every sibling frontend and
    the pump pool share; ``work`` — the pool's shared work condition
    (must be built on ``lock``); ``name`` — the graph name, used to
    scope crash seams; ``start=False`` — no private pump thread, the
    pool pumps.
    """

    def __init__(self, sched, *, policy: str = "block",
                 queue_batches: int = 256, max_bytes: int = 64 << 20,
                 window: Optional[CoalesceWindow] = None, crash=None,
                 start: bool = True, budget=None, lock=None, work=None,
                 name: Optional[str] = None, admission: str = "auto",
                 depth: Optional[int] = None):
        if policy not in POLICIES:
            raise ValueError(f"policy {policy!r} not in {POLICIES}")
        if admission not in ("auto", "host", "device"):
            raise ValueError(
                f"admission {admission!r} not in ('auto', 'host', "
                f"'device')")
        self.sched = sched
        self.policy = policy
        self.window = window if window is not None else CoalesceWindow()
        self.name = name
        #: the executor advertises the fused mega-tick window path for
        #: this graph: the pump's tick_many windows dispatch through the
        #: device ingress queue (docs/guide.md "Compiled mega-ticks")
        self.megatick = bool(getattr(sched, "window_support", False))
        #: what a host batch's admission charge measures: "host" = its
        #: payload bytes, "device" = the queue-slot bytes it will reserve
        #: on device (backpressure then tracks device memory pressure);
        #: "auto" picks "device" exactly when the window path engages
        self.admission = ("device" if admission == "auto" and self.megatick
                          else "host" if admission == "auto" else admission)
        #: pipelined window depth: how many dispatched-but-unretired
        #: windows may be in flight while the NEXT one stages (software
        #: pipelining over the async device dispatch). 1 = the fully
        #: serial stage→dispatch→retire loop, bit-for-bit today's
        #: behavior; >1 requires the staged scheduler surface, so it is
        #: forced to 1 off the fused mega-tick path.
        if depth is None:
            depth = env_int("REFLOW_WINDOW_DEPTH")
        staged = (self.megatick
                  and getattr(sched, "stage_window", None) is not None)
        self.depth = max(1, int(depth)) if staged else 1
        #: dispatched windows awaiting their retire step, oldest first.
        #: Owned by whoever holds the pump latch (or the pool's settle
        #: latch) — never mutated concurrently.
        self._inflight: Deque[_InflightWindow] = deque()
        self._crash = crash
        self._lock = (lock if lock is not None
                      else named_lock(f"serve.frontend.{name}" if name
                                      else "serve.frontend"))
        self._not_full = threading.Condition(self._lock)   # producers
        self._work = (work if work is not None
                      else threading.Condition(self._lock))  # pump
        self._idle = threading.Condition(self._lock)       # flush/pause
        if budget is None:
            budget = AdmissionBudget(max_bytes).register(name or "frontend")
        budget.attach(self._not_full)
        self._budget = budget
        self._queues = SourceQueues(queue_batches, budget)
        self._cursors: Dict[int, SourceCursor] = {}
        #: admission-side mirror of the scheduler's dedup window (the
        #: pump owns the scheduler, so producers can't read it): seeded
        #: from the (possibly recovered) window, bounded the same way
        self._admitted: Dict[str, None] = dict.fromkeys(
            sched._seen_batch_ids)
        self._state = "running"
        self._closing_flush = True
        self._paused = False
        self._executing = False
        self._flush_pending = False
        #: executed chunks whose tickets await the durable watermark
        self._pending_res = 0
        self.pump_error: Optional[BaseException] = None
        # -- counters/samples (utils.metrics.summarize_serve) --
        self.submitted = 0
        self.admitted = 0
        self.applied = 0
        self.deduped = 0
        self.rejected = 0
        self.shed = 0
        self.ticks = 0
        self.pump_iterations = 0
        #: pipelining counters: fused windows staged through the split
        #: lifecycle, how many staged while a previous window was still
        #: in flight, and the host-stage seconds in each bucket
        #: (``stage_overlap_frac`` is the overlapped fraction)
        self.windows_staged = 0
        self.windows_pipelined = 0
        self.stage_s_total = 0.0
        self.stage_overlap_s = 0.0
        #: times a failed frontend was re-armed (:meth:`revive`)
        self.revives = 0
        # bounded reservoirs (most recent METRIC_WINDOW samples) — the
        # totals above are exact; only percentile inputs are windowed
        self.queue_depth_samples: Deque[int] = deque(maxlen=METRIC_WINDOW)
        self.admission_s: Deque[float] = deque(maxlen=METRIC_WINDOW)
        self.ticks_per_pump: Deque[int] = deque(maxlen=METRIC_WINDOW)
        self.inflight_bytes_peak = 0
        # obs wiring: registered metric sources (publish_metrics) and
        # the current window's ready/take stamps (trace spans)
        self._metric_keys: List = []
        self._win_t_ready: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._pump_loop, name="reflow-ingest-pump",
                daemon=True)
            self._thread.start()

    # -- crash seams -------------------------------------------------------

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(
                name if self.name is None else f"{name}@{self.name}")

    # -- producer side -----------------------------------------------------

    def submit(self, source: Node, batch, *, batch_id: Optional[str] = None,
               timeout: Optional[float] = None, preimage=None,
               cause: Optional[str] = None,
               sampled: Optional[bool] = None) -> Ticket:
        """Admit one micro-batch for ``source``; returns a Ticket that
        resolves once the batch's fate is decided. Thread-safe; callable
        from any number of producers. ``timeout`` bounds a ``block``
        admission wait (expiry resolves the ticket REJECTED).

        ``preimage``: for a device-resident ``batch``, the host-side
        ``DeltaBatch`` it was uploaded from — a durable scheduler then
        logs these bytes instead of reading the device copy back (the
        zero-readback logging path). Ignored for host batches.

        ``cause`` / ``sampled``: cross-process trace adoption (the
        ingestion RPC). ``sampled=None`` keeps today's local 1-in-N
        decision; a bool ADOPTS the wire decision that rode in with the
        producer's causality token, so every process records the same
        writes. A locally-sampled submit with no token mints one, so
        in-process callers get full chains too."""
        if source.kind not in ("source", "loop"):
            raise GraphError(
                f"can only submit to sources/loops, not {source}")
        if preimage is not None and hasattr(preimage, "nonzero"):
            raise GraphError(
                "preimage must be the HOST DeltaBatch the device batch "
                "was uploaded from, not another device batch")
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        with self._lock:
            self._crash_point("producer_submit")
            self.submitted += 1
            if self._state != "running":
                raise FrontendClosed(
                    f"frontend is {self._state}; submissions not accepted")
            if batch_id is None:
                batch_id = self._cursor(source).next_id()
            ticket = Ticket(batch_id)
            if _trace.ENABLED:
                if sampled is None:
                    ticket.trace = _trace.mint(batch_id, t0)
                    if cause is not None:
                        ticket.trace.cause = cause
                else:
                    ticket.trace = _trace.TraceCtx(batch_id, t0,
                                                   sampled, cause)
                if ticket.trace.sampled and ticket.trace.cause is None:
                    from reflow_tpu.obs.wire import node_id
                    ticket.trace.cause = _trace.mint_cause(
                        node_id(), getattr(self.sched, "epoch", 0))
            if batch_id in self._admitted:
                self.deduped += 1
                ticket._resolve(TicketResult(
                    DEDUPED, batch_id,
                    reason="batch_id already admitted"))
                self._trace_submit(ticket, "deduped")
                return ticket
            device = hasattr(batch, "nonzero")
            rows = 0 if device else len(batch)
            if not device and rows == 0:
                # an empty host batch is a semantic no-op; report it
                # applied rather than occupying a queue slot
                self._note_admitted(batch_id)
                ticket._resolve(TicketResult(APPLIED, batch_id,
                                             reason="empty batch"))
                self._trace_submit(ticket, "empty")
                return ticket
            nbytes = self._charge_bytes(source, batch, device)
            if not self._admit(source, nbytes, ticket, batch_id, deadline):
                return ticket  # ticket already resolved REJECTED/…
            if batch_id in self._admitted:
                # a blocked admission drops the lock in wait(): another
                # producer may have admitted this very id meanwhile —
                # pushing now would fold the batch twice
                self.deduped += 1
                ticket._resolve(TicketResult(
                    DEDUPED, batch_id,
                    reason="batch_id admitted concurrently while this "
                           "submit was blocked on backpressure"))
                self._trace_submit(ticket, "deduped")
                return ticket
            entry = Entry(ticket, source, batch, batch_id, nbytes,
                          time.perf_counter(), device, rows,
                          preimage=preimage if device else None)
            self._note_admitted(batch_id)
            self._queues.push(entry)
            self.admitted += 1
            self.admission_s.append(time.perf_counter() - t0)
            self.queue_depth_samples.append(self._queues.queued_batches)
            self.inflight_bytes_peak = max(
                self.inflight_bytes_peak,
                self._queues.queued_bytes + self._queues.executing_bytes)
            self._trace_submit(ticket, "admitted")
            self._work.notify()
            self._crash_point("producer_admitted")
        return ticket

    @staticmethod
    def _trace_submit(ticket: Ticket, outcome: str) -> None:
        # producer-track span covering this submit() call: admission
        # wait plus its terminal outcome (the sampled ticket's own
        # six-stage timeline is emitted at resolve time by the pump)
        ctx = ticket.trace
        if ctx is not None and ctx.sampled:
            _trace.evt("submit", ctx.t0,
                       time.perf_counter() - ctx.t0,
                       args={"batch_id": ticket.batch_id,
                             "outcome": outcome})

    def _charge_bytes(self, source: Node, batch, device: bool) -> int:
        """What this batch's admission charges against the byte budget.
        Under device-keyed admission (``admission="device"``, the
        mega-tick default) a host batch is charged the device bytes its
        ingress-queue slot will reserve — the capacity-bucketed padded
        footprint — so backpressure reflects actual device memory
        pressure, not host payload size. Device-resident batches always
        charge their (device) payload bytes; both reads are metadata,
        never a device sync."""
        if not device and self.admission == "device":
            from reflow_tpu.executors.ingress_queue import slot_nbytes

            return slot_nbytes(source.spec, len(batch))
        return batch_nbytes(batch)

    def _admit(self, source: Node, nbytes: int, ticket: Ticket,
               batch_id: str, deadline: Optional[float]) -> bool:
        # caller holds the lock; resolves the ticket and returns False
        # when admission is refused
        while not self._queues.room_for(source.id, nbytes):
            if self.policy == "reject":
                self.rejected += 1
                ticket._resolve(TicketResult(
                    REJECTED, batch_id, reason="backpressure: queue full"))
                self._trace_submit(ticket, "rejected")
                return False
            if self.policy == "shed-oldest":
                if not self._queues.fits_alone(nbytes):
                    self.rejected += 1
                    ticket._resolve(TicketResult(
                        REJECTED, batch_id,
                        reason=f"batch of {nbytes}B exceeds the "
                               f"{self._queues.max_bytes}B budget"))
                    self._trace_submit(ticket, "rejected")
                    return False
                shed_any = False
                for e in self._queues.shed_for(source.id, nbytes):
                    self.shed += 1
                    shed_any = True
                    # the evicted batch never reached the scheduler: drop
                    # it from the dedup mirror so the re-send the SHED
                    # ticket demands is admitted, not DEDUPED away
                    self._admitted.pop(e.batch_id, None)
                    e.ticket._resolve(TicketResult(
                        SHED, e.batch_id,
                        reason="shed-oldest backpressure; re-send"))
                    self._trace_submit(e.ticket, "shed")
                if shed_any:
                    # freed bytes are budget-wide: a sibling graph's
                    # blocked producer may fit now
                    self._budget.notify_room()
                if self._queues.room_for(source.id, nbytes):
                    return True
                # executing bytes hold the budget: fall through to wait
            # block (and shed-oldest squeezed by in-flight execution)
            remaining = (None if deadline is None
                         else deadline - time.perf_counter())
            if remaining is not None and remaining <= 0:
                self.rejected += 1
                ticket._resolve(TicketResult(
                    REJECTED, batch_id,
                    reason="backpressure: admission timed out"))
                self._trace_submit(ticket, "rejected")
                return False
            if not self._not_full.wait(timeout=remaining):
                self.rejected += 1
                ticket._resolve(TicketResult(
                    REJECTED, batch_id,
                    reason="backpressure: admission timed out"))
                self._trace_submit(ticket, "rejected")
                return False
            if self._state != "running":
                raise FrontendClosed(
                    "frontend closed while blocked on admission")
        return True

    def _cursor(self, source: Node) -> SourceCursor:
        cur = self._cursors.get(source.id)
        if cur is None:
            cur = self._cursors[source.id] = SourceCursor.resume(
                self.sched, source)
        return cur

    def _note_admitted(self, batch_id: str) -> None:
        self._admitted[batch_id] = None
        while len(self._admitted) > self.sched.dedup_window:
            self._admitted.pop(next(iter(self._admitted)))

    def admitted_ids(self, batch_ids) -> list:
        """Which of ``batch_ids`` the dedup mirror currently remembers.
        The ingestion RPC's reconnect handshake: a producer that died
        in an ack window sends its in-doubt ids here, then resubmits —
        a remembered id resolves DEDUPED, keeping resubmission
        exactly-once without the producer ever guessing."""
        with self._lock:
            return [b for b in batch_ids if b in self._admitted]

    # -- lifecycle ---------------------------------------------------------

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every batch admitted so far has been ticked."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        with self._lock:
            if self._state == "failed":
                raise PumpCrashed(f"pump died: {self.pump_error!r}")
            if self._paused:
                raise GraphError("flush() while paused would never "
                                 "complete; resume() first")
            self._flush_pending = True
            self._work.notify_all()
            try:
                while (self._queues.queued_batches or self._executing
                       or self._pending_res):
                    if self._state == "failed":
                        raise PumpCrashed(
                            f"pump died: {self.pump_error!r}")
                    if self._state == "closed":
                        return
                    remaining = (None if deadline is None
                                 else deadline - time.perf_counter())
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError("flush timed out")
                    self._idle.wait(timeout=remaining)
            finally:
                self._flush_pending = False

    def drain(self, source: Optional[Node] = None, *, max_ticks: int = 256,
              probe_rows: int = 1) -> int:
        """Flush, then run the scheduler's ``drain`` (deferred-fixpoint
        residue) with the pump paused. ``source`` defaults to the
        graph's sole source; pass one explicitly on multi-source graphs.
        Returns the scheduler drain's tick count."""
        if source is None:
            srcs = [n for n in self.sched.graph.nodes
                    if n.kind == "source"]
            if len(srcs) != 1:
                raise GraphError(
                    f"drain needs an explicit source on a graph with "
                    f"{len(srcs)} sources")
            source = srcs[0]
        self.flush()
        self.pause()
        try:
            return self.sched.drain(source, max_ticks=max_ticks,
                                    probe_rows=probe_rows)
        finally:
            self.resume()

    def pause(self) -> None:
        """Stop pumping (admission continues to queue); returns once the
        in-flight macro-tick (if any) completes. The scheduler may then
        be inspected/driven directly until :meth:`resume`."""
        with self._lock:
            self._paused = True
            # also wait out dispatched-but-unretired pipelined windows:
            # their retire mutates the ingress queue the caller is about
            # to drive directly
            while self._executing or self._inflight:
                self._idle.wait()

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._work.notify_all()

    def close(self, *, flush: bool = True,
              timeout: Optional[float] = None) -> None:
        """Quiesce and shut down: stop admission, release blocked
        producers with :class:`FrontendClosed`, tick out the remaining
        backlog (``flush=True``) or fail its tickets (``flush=False``),
        stop the pump, and seal a durable scheduler's WAL. Idempotent.

        On an externally-pumped frontend the draining is done by the
        pool (which must still be serving — ``ServeTier`` closes graphs
        before stopping its threads); this call waits for it."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        with self._lock:
            seal_only = self._state in ("closed", "failed")
        if seal_only:
            # outside the lock: sealing a durable scheduler closes its
            # WAL, whose final fsync may fire when_durable callbacks
            # that re-take this (non-reentrant) lock
            self._seal()
            return
        with self._lock:
            if self._state not in ("closed", "failed"):
                if self._state == "running":
                    self._closing_flush = flush
                # else: a retry after a close() timeout — keep the
                # original call's flush intent rather than silently
                # downgrading it
                self._state = "closing"
                self._paused = False
                self._not_full.notify_all()
                self._work.notify_all()
        if self._thread is not None:
            if self._thread.is_alive():
                self._thread.join(timeout=timeout)
                if self._thread.is_alive():
                    # the pump is still mid-macro-tick: sealing the WAL
                    # now would close a file it is appending to. Stay
                    # "closing" (admission already refused) and let the
                    # caller retry.
                    raise TimeoutError(
                        f"close() timed out after {timeout}s with the "
                        f"pump still draining; frontend left in state "
                        f"'closing' — call close() again to finish")
        else:
            self._close_external(deadline, timeout)
        with self._lock:
            if self._state != "failed":
                self._state = "closed"
            self._idle.notify_all()
        self._seal()

    def _close_external(self, deadline: Optional[float],
                        timeout: Optional[float]) -> None:
        # externally-pumped shutdown: with flush intent the pool drains
        # the backlog (closing graphs fire unconditionally in _poll);
        # without it we only wait out an in-flight window, then strand-
        # fail whatever is still queued
        with self._lock:
            while self._state == "closing" and (
                    self._executing or self._inflight
                    or (self._closing_flush
                        and self._queues.queued_batches)):
                remaining = (None if deadline is None
                             else deadline - time.perf_counter())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"close() timed out after {timeout}s with the "
                        f"pump pool still draining; frontend left in "
                        f"state 'closing' — call close() again to "
                        f"finish")
                self._idle.wait(timeout=remaining)
            if self._state == "closing" and not self._closing_flush:
                self._exit_pump_locked()

    @property
    def stage_overlap_frac(self) -> float:
        """Fraction of host staging time that overlapped an in-flight
        device dispatch (0.0 at depth 1 or before any fused window)."""
        return (self.stage_overlap_s / self.stage_s_total
                if self.stage_s_total > 0 else 0.0)

    def publish_metrics(self, registry=None) -> str:
        """Register this frontend's live counters (the
        ``summarize_serve().to_dict()`` schema) as an obs metric source
        — live snapshots and offline summaries stay one schema.
        Unregistered automatically at :meth:`close`. Returns the source
        key (``serve.<name>``)."""
        from reflow_tpu.obs import REGISTRY
        from reflow_tpu.utils.metrics import summarize_serve
        reg = registry if registry is not None else REGISTRY
        key = f"serve.{self.name or 'frontend'}"
        reg.register_source(key,
                            lambda: summarize_serve(self).to_dict())
        self._metric_keys.append((reg, key))
        return key

    def _seal(self) -> None:
        for reg, key in self._metric_keys:
            reg.unregister_source(key)
        self._metric_keys = []
        closefn = getattr(self.sched, "close", None)
        if closefn is not None:
            closefn()

    def __enter__(self) -> "IngestFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close(flush=exc == (None, None, None))

    # -- the pump ----------------------------------------------------------

    def _fire_or_timeout(self, now: float):
        # under lock: (fire, wait_timeout)
        if self._state == "closing":
            return True, None
        if self._paused or self._queues.queued_batches == 0:
            return False, None
        if self._flush_pending:
            return True, None
        w = self.window
        if self._queues.queued_rows >= w.max_rows:
            return True, None
        if self._queues.pending_feed_rounds(w.max_rows) >= w.max_ticks:
            return True, None
        oldest = self._queues.oldest_t()
        age = now - oldest if oldest is not None else 0.0
        if age >= w.max_latency_s:
            return True, None
        return False, w.max_latency_s - age

    # external-pump surface (the tier's pool; every method below up to
    # _run_window is called with the shared lock held) ---------------------

    def _poll(self, now: float):
        """Pool eligibility: (fire, wait_s). Never fires while the
        in-flight latch is held (single-owner invariant), after a
        failure, or once closed; a closing graph fires only while a
        flush-close still has backlog to tick out."""
        if self._executing or self._state in ("closed", "failed"):
            return False, None
        if self._state == "closing":
            return (self._closing_flush
                    and self._queues.queued_batches > 0), None
        return self._fire_or_timeout(now)

    def _take_window(self, ready_since: Optional[float] = None
                     ) -> Dict[int, List[Entry]]:
        """Claim the backlog as one macro-tick work item and set the
        in-flight latch; the caller must follow with ``_run_window``
        (lock released) and ``_finish_window`` (lock re-held).
        ``ready_since`` (tier pool): when the window first became
        eligible — the gap to now is cross-graph scheduling delay on
        the trace timeline."""
        self._win_t_ready = (ready_since if ready_since is not None
                             else time.perf_counter())
        drained = self._queues.drain_all()
        self._flush_pending = False
        self._executing = True
        return drained

    def _finish_window(self) -> None:
        """Release the latch and the window's remaining budget bytes
        (staged chunks already released theirs at stage-complete); wake
        blocked producers (budget-wide) and flush/pause waiters."""
        self._executing = False
        self._queues.commit_executing()
        self._budget.notify_room()
        self._idle.notify_all()

    def _needs_settle(self) -> bool:
        """Pool eligibility for a settle-only iteration (caller holds
        the lock): dispatched windows are waiting for their retire and
        nobody owns the latch. Ignores ``_paused`` deliberately — pause
        WAITS on the in-flight windows, so settling must proceed."""
        return (bool(self._inflight) and not self._executing
                and self._state != "failed")

    def _begin_settle(self) -> None:
        """Latch the graph for a settle-only iteration (caller holds
        the lock; follow with ``_settle_all`` unlocked, then
        ``_finish_window``)."""
        self._executing = True

    def _pump_loop(self) -> None:
        try:
            while True:
                drained = None
                with self._lock:
                    while True:
                        if self._state == "closing" and (
                                not self._closing_flush
                                or self._queues.queued_batches == 0):
                            if not self._inflight:
                                self._exit_pump_locked()
                                return
                            self._begin_settle()  # retire first
                            break
                        fire, wait_t = self._fire_or_timeout(
                            time.perf_counter())
                        if fire:
                            drained = self._take_window()
                            break
                        if self._inflight:
                            # idle with windows in flight: the device has
                            # nothing to overlap with, so retire now
                            # (latched, so pause/close wait it out)
                            self._begin_settle()
                            break
                        self._work.wait(timeout=wait_t)
                if drained is None:
                    self._settle_all()
                    with self._lock:
                        self._finish_window()
                    continue
                self._run_window(drained)
                with self._lock:
                    self._finish_window()
        except BaseException as e:  # noqa: BLE001 - incl. CrashPoint kills
            self._on_pump_crash(e)

    def _exit_pump_locked(self) -> None:
        # caller holds the lock; fail whatever close(flush=False) strands
        stranded = self._queues.drain_all()
        self._queues.commit_executing()
        for entries in stranded.values():
            for e in entries:
                e.ticket._fail(FrontendClosed(
                    f"frontend closed before batch {e.batch_id!r} "
                    f"was ticked"))
        self._budget.notify_room()
        self._idle.notify_all()
        self._not_full.notify_all()

    def _device_label(self) -> Optional[str]:
        """Executing-device obs tag for this graph's spans (placement
        skew shows up in trace_inspect's per-device breakdown)."""
        return getattr(getattr(self.sched, "executor", None),
                       "device_label", None)

    def _run_window(self, drained: Dict[int, List[Entry]]) -> None:
        self._window_entries = drained  # crash path fails their tickets
        tr = _trace.ENABLED
        t_w0 = time.perf_counter()
        t_ready = self._win_t_ready or t_w0
        feeds = build_feeds(drained, self.window.max_rows)
        if tr:
            _trace.evt("host_merge", t_w0, time.perf_counter() - t_w0,
                       args={"graph": self.name or "frontend",
                             "feeds": len(feeds)})
        self._crash_point("pump_coalesce")
        wal = getattr(self.sched, "wal", None)
        push_pre = getattr(self.sched, "push_preimage", None)
        if wal is not None and push_pre is not None:
            # ingest-time pre-images: hand the durable scheduler the
            # host payloads captured at submit() so device batches log
            # without a forced readback
            for f in feeds:
                for entries in f.entries.values():
                    for e in entries:
                        if e.device and e.preimage is not None:
                            push_pre(e.batch_id, e.preimage)
        push_cause = getattr(self.sched, "push_cause", None)
        if tr and wal is not None and push_cause is not None:
            # register sampled tickets' causality tokens so the WAL
            # stamps them onto this window's push records — the shipper
            # and replicas then re-emit the same tokens, stitching the
            # chain across processes
            for f in feeds:
                for entries in f.entries.values():
                    for e in entries:
                        ctx = e.ticket.trace
                        if ctx is not None and ctx.cause:
                            push_cause(e.batch_id, ctx.cause)
        k = self.window.max_ticks
        for i in range(0, len(feeds), k):
            chunk = feeds[i:i + k]
            # bound the pipeline: at most depth dispatched windows may
            # exist once this chunk dispatches, so retire the oldest
            # until a slot is free (depth 1 ⇒ settle everything here ⇒
            # the serial stage→dispatch→retire loop, today's behavior)
            while len(self._inflight) > self.depth - 1:
                self._settle_one()
            self._crash_point("pump_before_tick")
            handle = None
            if self.depth > 1:
                t_s0 = time.perf_counter()
                inflight0 = len(self._inflight)
                handle = self.sched.stage_window(
                    [f.batches for f in chunk],
                    feed_ids=[f.ids for f in chunk])
                if handle is not None:
                    t_s1 = time.perf_counter()
                    self.windows_staged += 1
                    self.stage_s_total += t_s1 - t_s0
                    if inflight0 > 0:
                        self.windows_pipelined += 1
                        self.stage_overlap_s += t_s1 - t_s0
                    if tr:
                        _trace.evt("window_stage", t_s0, t_s1 - t_s0,
                                   args={"graph": self.name or "frontend",
                                         "ticks": len(chunk),
                                         "inflight": inflight0,
                                         "device": self._device_label()})
                    # stage-complete budget release: the chunk's rows now
                    # live in the device ingress queue, so their admission
                    # bytes stop occupying the frontend — producers
                    # unblock a window earlier than the retire
                    chunk_bytes = sum(
                        e.nbytes for f in chunk
                        for entries in f.entries.values() for e in entries)
                    with self._lock:
                        self._queues.release_executing(chunk_bytes)
                        self._budget.notify_room()
            if handle is not None:
                tick0 = self.sched._tick
                t_exec0 = time.perf_counter()
                self.sched.dispatch_staged(handle)
                lsn = wal.last_lsn() if wal is not None else 0
                t_exec1 = time.perf_counter()
                if tr:
                    _trace.evt("pump_execute", t_exec0, t_exec1 - t_exec0,
                               args={"graph": self.name or "frontend",
                                     "ticks": len(chunk), "lsn": lsn,
                                     "megatick": True,
                                     "depth": len(self._inflight) + 1,
                                     "device": self._device_label()})
                self._crash_point("pump_after_tick")
                block = _ResBlock(self._chunk_items(chunk, tick0), lsn,
                                  len(chunk), t_ready, t_exec0, t_exec1)
                with self._lock:
                    self._pending_res += 1
                self._inflight.append(_InflightWindow(handle, block))
                continue
            # unfused (or depth-1) chunk: settle the pipeline first so
            # ticket wiring stays LSN-ordered, then run today's serial
            # tick_many path verbatim (it re-checks the window fit and
            # counts any fallback exactly once)
            self._settle_all()
            tick0 = self.sched._tick
            t_exec0 = time.perf_counter()
            if wal is not None:
                self.sched.tick_many([f.batches for f in chunk],
                                     feed_ids=[f.ids for f in chunk],
                                     wait_durable=False)
                lsn = wal.last_lsn()
            else:
                self.sched.tick_many([f.batches for f in chunk],
                                     feed_ids=[f.ids for f in chunk])
                lsn = 0
            t_exec1 = time.perf_counter()
            if tr:
                _trace.evt("pump_execute", t_exec0, t_exec1 - t_exec0,
                           args={"graph": self.name or "frontend",
                                 "ticks": len(chunk), "lsn": lsn,
                                 "megatick": self.megatick,
                                 "depth": 1,
                                 "device": self._device_label()})
            self._crash_point("pump_after_tick")
            block = _ResBlock(self._chunk_items(chunk, tick0), lsn,
                              len(chunk), t_ready, t_exec0, t_exec1)
            with self._lock:
                self._pending_res += 1
            self._wire_block(block)
        if tr:
            _trace.evt("window", t_w0, time.perf_counter() - t_w0,
                       args={"graph": self.name or "frontend",
                             "feeds": len(feeds),
                             "device": self._device_label()})
        self._win_t_ready = None
        with self._lock:
            self.pump_iterations += 1
            self.ticks_per_pump.append(len(feeds))
            more = (self._state == "running" and not self._paused
                    and not self._flush_pending
                    and self._queues.queued_batches > 0)
        self._window_entries = None
        # keep the pipeline primed only when another window is imminent:
        # its stage will overlap these dispatches. Otherwise retire now,
        # inside the latch, so flush/pause/close observe a settled graph.
        if self.depth <= 1 or not more:
            self._settle_all()

    @staticmethod
    def _chunk_items(chunk, tick0: int) -> List[Tuple[Entry, int, int]]:
        items = []
        for j, f in enumerate(chunk):
            for entries in f.entries.values():
                for e in entries:
                    items.append((e, tick0 + j + 1, len(entries) - 1))
        return items

    def _settle_one(self) -> None:
        """Retire the OLDEST dispatched window (lock NOT held): re-adopt
        its donated queue generation, then wire its tickets onto the
        durable watermark. Runs off the stage→dispatch critical path —
        under pipelining this executes while the next window is already
        on the device."""
        iw = self._inflight.popleft()
        tr = _trace.ENABLED
        t_r0 = time.perf_counter() if tr else 0.0
        self.sched.retire_staged(iw.handle)
        if tr:
            _trace.evt("window_retire", t_r0, time.perf_counter() - t_r0,
                       args={"graph": self.name or "frontend",
                             "ticks": iw.block.nticks})
        self._wire_block(iw.block)

    def _settle_all(self) -> None:
        while self._inflight:
            self._settle_one()

    def _wire_block(self, block: _ResBlock) -> None:
        """Park one executed chunk's tickets on the durable watermark
        (``_pending_res`` was already taken at dispatch). Pipelined
        resolution: commit-before-resolve holds, but the commit (the
        fsync) may still be in flight — ``when_durable`` fires on the
        committer once the window's LSN is covered, so the pump overlaps
        the disk latency instead of serializing behind it."""
        wal = getattr(self.sched, "wal", None)
        if wal is None:
            self._complete_block(block, None)
            return
        try:
            deferred = wal.when_durable(
                block.lsn,
                lambda err, b=block: self._complete_block(b, err))
        except BaseException:
            with self._lock:
                self._pending_res -= 1
            raise
        if not deferred:
            self._complete_block(block, None)

    def _complete_block(self, block: _ResBlock,
                        err: Optional[BaseException]) -> None:
        """Resolve one executed chunk's tickets at its durability point.
        Runs inline on the pump (LSN already durable / non-durable
        scheduler) or on the WAL committer thread via ``when_durable``
        (pipelined fsync). ``err`` is the committer's death cause — the
        chunk's records may never become durable, so its undecided
        tickets fail with :class:`PumpCrashed` instead (the upstream
        re-sends; replay after ``recover()`` dedups)."""
        if err is not None:
            crash = PumpCrashed(
                f"wal committer died before the window's records were "
                f"durable: {err!r}")
            crash.__cause__ = err
            with self._lock:
                self._state = "failed"
                self.pump_error = err
                self._pending_res -= 1
                self._not_full.notify_all()
                self._work.notify_all()
                self._idle.notify_all()
            for e, _tick, _co in block.items:
                if not e.ticket.done():
                    e.ticket._fail(crash)
            return
        tr = _trace.ENABLED
        t_dur = time.perf_counter()
        applied = 0
        for e, tick, co in block.items:
            if e.ticket.done():
                continue  # a pump-crash path decided it first
            ctx = e.ticket.trace
            e.ticket._resolve(TicketResult(
                APPLIED, e.batch_id, tick=tick, coalesced_with=co,
                lsn=block.lsn or None))
            applied += 1
            if tr and ctx is not None and ctx.sampled:
                _trace.ticket_stages(
                    ctx, t_adm=e.t_admitted, t_ready=block.t_ready,
                    t_exec0=block.t_exec0, t_exec1=block.t_exec1,
                    t_dur=t_dur, t_res=time.perf_counter())
                if ctx.cause:
                    # the write's durability boundary on the shared
                    # chain: execute end -> durable watermark passed
                    _trace.evt("wal_append", block.t_exec1,
                               t_dur - block.t_exec1, track="wal",
                               args={"batch_id": e.batch_id,
                                     "cause": ctx.cause,
                                     "lsn": block.lsn or None})
        with self._lock:
            self._pending_res -= 1
            self.ticks += block.nticks
            self.applied += applied
            self._idle.notify_all()

    def _on_pump_crash(self, error: BaseException,
                       window: Optional[Dict[int, List[Entry]]] = None,
                       ) -> None:
        """Fail the frontend after its pump died: every undecided ticket
        of the in-flight window and the stranded backlog resolves with
        :class:`PumpCrashed`, blocked producers are released, and the
        graph's budget bytes return to the pool. On a tier, only THIS
        graph fails — the pool thread survives and keeps serving
        siblings (``window`` carries the drained entries when the crash
        fired before ``_run_window`` stamped them)."""
        with self._lock:
            self._state = "failed"
            self.pump_error = error
            self._executing = False
            # dispatched-but-unretired pipelined windows die with the
            # pump: their device work may or may not have completed, so
            # treat them like the in-flight window — tickets fail (the
            # upstream re-sends; durable replay dedups what actually
            # applied) and their ids STAY in the dedup mirror. Their
            # queue generations are never retired; the executor's
            # use-after-donate guard already dropped the queue on a
            # dispatch crash, and a fresh one is allocated next window.
            inflight = list(self._inflight)
            self._inflight.clear()
            self._pending_res -= len(inflight)
            stranded = self._queues.drain_all()
            self._queues.commit_executing()
            # the stranded backlog never reached the scheduler: drop its
            # ids from the dedup mirror (same reasoning as the shed
            # path) so a re-send after revive() is admitted, not
            # DEDUPED. The in-flight window's ids stay mirrored — they
            # may have executed before the crash, and a re-send that
            # turns out unapplied still dedups safely at replay.
            for entries in stranded.values():
                for e in entries:
                    self._admitted.pop(e.batch_id, None)
            self._budget.notify_room()
            self._not_full.notify_all()
            self._work.notify_all()
            self._idle.notify_all()
        crash = PumpCrashed(f"ingest pump died: {error!r}")
        crash.__cause__ = error
        if window is None:
            window = getattr(self, "_window_entries", None) or {}
        for iw in inflight:
            for e, _tick, _co in iw.block.items:
                if not e.ticket.done():
                    e.ticket._fail(crash)
        for entries in list(window.values()) + list(stranded.values()):
            for e in entries:
                if not e.ticket.done():
                    e.ticket._fail(crash)

    def _bind_sched(self, sched) -> None:
        """Re-point a settled frontend at a new scheduler (the failover
        path; caller holds the lock, state is ``"failed"``). The dedup
        mirror is REBUILT from the new scheduler's recovered window:
        a batch the old leader committed *and shipped* dedups here,
        while a batch only the dead leader ever saw is dropped from the
        mirror — its ticket failed with ``PumpCrashed``, the producer's
        resubmit is admitted, and it folds exactly once on the new
        leader."""
        self.sched = sched
        self._cursors.clear()  # auto-id cursors re-derive from new sched
        self._admitted = dict.fromkeys(sched._seen_batch_ids)
        self.megatick = bool(getattr(sched, "window_support", False))
        if not self.megatick and self.admission == "device":
            self.admission = "host"
        staged = (self.megatick
                  and getattr(sched, "stage_window", None) is not None)
        if not staged:
            self.depth = 1

    def revive(self, sched=None) -> None:
        """Re-arm a failed frontend: ``"failed"`` → ``"running"`` — the
        control plane's respawn actuator (callers can also use it by
        hand). Only valid after :meth:`_on_pump_crash` settled the
        graph: queues drained, budget released, every undecided ticket
        failed — so the frontend is structurally identical to a freshly
        registered one and new submissions flow immediately. Upstreams
        re-send the batches whose tickets failed with
        :class:`PumpCrashed`; a durable graph's replay dedups any that
        actually executed.

        ``sched=`` re-points the frontend at a NEW scheduler before
        re-arming — the failover path: after a leader dies and a
        replica promotes, the tier revives the same frontend over the
        promoted ``DurableScheduler`` so producers keep their handle
        and resubmit through the (rebuilt) dedup mirror.

        Durability caveat: reviving is at-most-once for the CRASHED
        window on a volatile graph (its deltas are gone); a durable
        graph loses nothing acknowledged — unacknowledged batches are
        the upstream's to re-send, same as process-crash recovery. If
        the scheduler's WAL committer is dead this raises — call
        ``wal.restart_committer()`` first (or pass the promoted
        ``sched=``), or the next window would fail the graph right
        back."""
        with self._lock:
            if self._state != "failed":
                raise GraphError(
                    f"revive() re-arms a failed frontend; state is "
                    f"{self._state!r}")
            if sched is not None and sched is not self.sched:
                self._bind_sched(sched)
            wal = getattr(self.sched, "wal", None)
            if wal is not None and wal.committer_error is not None:
                raise GraphError(
                    "scheduler's WAL committer is dead; "
                    "restart_committer() before revive()")
            self._state = "running"
            self.pump_error = None
            self._executing = False
            self.revives += 1
            if self._thread is not None and not self._thread.is_alive():
                # the pump thread died WITH the crash (its own window
                # hit the dead committer) rather than surviving it (the
                # committer thread failing tickets via when_durable):
                # re-arm the loop itself, not just the state flag, or
                # nothing drains the queues and flush() never returns
                self._thread = threading.Thread(
                    target=self._pump_loop, name="reflow-ingest-pump",
                    daemon=True)
                self._thread.start()
            self._not_full.notify_all()
            self._work.notify_all()
            self._idle.notify_all()
