"""The delta model: batches of (key, value, weight) changes.

SURVEY.md §2 item 7: the reference's "delta buffers" are plain Python objects
flowing on graph edges. Here the host-side representation is columnar NumPy
(:class:`DeltaBatch`), chosen so the same batch converts losslessly to the
device representation (padded ``jax.Array`` columns — see
``executors/device_delta.py``) without a per-record Python loop.

Algebra
-------
A *collection* is a multiset of ``(key, value)`` rows with signed integer
multiplicities. A *delta* is itself such a multiset: positive weight inserts,
negative weight retracts. Applying a delta is multiset addition;
``consolidate`` merges duplicate rows and drops zero-weight rows. This is the
differential-dataflow change algebra (cf. DBSP), which is what makes
incremental Reduce/Join well-defined under retractions.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Hashable, Iterable, Mapping, Tuple

import numpy as np

__all__ = ["Spec", "DeltaBatch", "collection_counter", "counter_to_batch"]


@dataclasses.dataclass(frozen=True)
class Spec:
    """Static type/shape declaration for one edge's rows.

    Required for TPU lowering (XLA needs static shapes/dtypes); the CPU
    oracle ignores it. ``key_space`` bounds the integer key domain
    ``[0, key_space)`` for dense keyed state on device; host-side sources are
    responsible for mapping raw keys (e.g. strings) into this domain (host
    work is allowed at the graph boundary per the north star).
    """

    value_shape: Tuple[int, ...] = ()
    value_dtype: Any = np.float32
    key_space: int = 0  # 0 = unknown / host-only graph
    #: at most one row per key in the materialized collection (e.g. Reduce
    #: output). The device Join requires its left input to be unique-keyed.
    unique: bool = False

    def with_key_space(self, n: int) -> "Spec":
        return dataclasses.replace(self, key_space=n)

    def as_unique(self) -> "Spec":
        return dataclasses.replace(self, unique=True)


class DeltaBatch:
    """A columnar batch of (key, value, weight) changes.

    ``keys``:    int64[n] (or object[n] for host-only graphs with raw keys)
    ``values``:  [n, *value_shape] numeric, or object[n] for host-only graphs
    ``weights``: int64[n]; >0 insert, <0 retract
    """

    __slots__ = ("keys", "values", "weights")

    def __init__(self, keys, values, weights=None):
        keys = np.asarray(keys)
        values = np.asarray(values)
        if weights is None:
            weights = np.ones(len(keys), dtype=np.int64)
        else:
            weights = np.asarray(weights, dtype=np.int64)
        if not (len(keys) == len(values) == len(weights)):
            raise ValueError(
                f"column length mismatch: keys={len(keys)} values={len(values)} "
                f"weights={len(weights)}"
            )
        self.keys = keys
        self.values = values
        self.weights = weights

    # -- constructors ------------------------------------------------------

    @staticmethod
    def empty(spec: Spec | None = None) -> "DeltaBatch":
        if spec is None:
            return DeltaBatch(
                np.empty(0, dtype=np.int64),
                np.empty(0, dtype=object),
                np.empty(0, dtype=np.int64),
            )
        return DeltaBatch(
            np.empty(0, dtype=np.int64),
            np.empty((0,) + tuple(spec.value_shape), dtype=spec.value_dtype),
            np.empty(0, dtype=np.int64),
        )

    @staticmethod
    def from_pairs(pairs: Iterable[Tuple[Hashable, Any]], weight: int = 1) -> "DeltaBatch":
        """Build from an iterable of (key, value) with a uniform weight."""
        pairs = list(pairs)
        keys = np.array([k for k, _ in pairs], dtype=object)
        values = np.array([v for _, v in pairs], dtype=object)
        weights = np.full(len(pairs), weight, dtype=np.int64)
        return DeltaBatch(keys, values, weights)

    @staticmethod
    def concat(batches: Iterable["DeltaBatch"]) -> "DeltaBatch":
        batches = [b for b in batches if len(b)]
        if not batches:
            return DeltaBatch.empty()
        return DeltaBatch(
            np.concatenate([b.keys for b in batches]),
            np.concatenate([b.values for b in batches]),
            np.concatenate([b.weights for b in batches]),
        )

    # -- basics ------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.keys)

    def __iter__(self):
        return zip(self.keys, self.values, self.weights)

    def __repr__(self) -> str:
        return f"DeltaBatch(n={len(self)})"

    def rows(self):
        """Iterate (key, hashable_value, weight) rows (host-side only)."""
        for k, v, w in zip(self.keys, self.values, self.weights):
            yield k, _hashable(v), int(w)

    def consolidate(self) -> "DeltaBatch":
        """Merge duplicate (key, value) rows; drop zero weights."""
        acc: Counter = Counter()
        for k, v, w in self.rows():
            acc[(k, v)] += w
        return counter_to_batch(acc, like=self)

    def scale(self, factor: int) -> "DeltaBatch":
        return DeltaBatch(self.keys, self.values, self.weights * factor)

    def to_counter(self) -> Counter:
        acc: Counter = Counter()
        for k, v, w in self.rows():
            acc[(k, v)] += w
        return Counter({kv: w for kv, w in acc.items() if w != 0})


def _hashable(v: Any) -> Hashable:
    """Host-side canonical hashable form of a value (for multiset state)."""
    if isinstance(v, np.ndarray):
        if v.ndim == 0:
            return v.item()
        return tuple(_hashable(x) for x in v)
    if isinstance(v, (np.generic,)):
        return v.item()
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    return v


def collection_counter(batches: Iterable[DeltaBatch]) -> Counter:
    """Accumulate delta batches into a multiset Counter {(key, value): weight}."""
    acc: Counter = Counter()
    for b in batches:
        for k, v, w in b.rows():
            acc[(k, v)] += w
    return Counter({kv: w for kv, w in acc.items() if w != 0})


def counter_to_batch(acc: Mapping, like: DeltaBatch | None = None) -> DeltaBatch:
    """Materialize a {(key, value): weight} mapping as a DeltaBatch."""
    items = [(k, v, w) for (k, v), w in acc.items() if w != 0]
    if not items:
        return DeltaBatch.empty() if like is None or like.values.dtype == object else DeltaBatch(
            np.empty(0, dtype=like.keys.dtype),
            np.empty((0,) + like.values.shape[1:], dtype=like.values.dtype),
            np.empty(0, dtype=np.int64),
        )
    keys = np.array([k for k, _, _ in items], dtype=object)
    values = np.array([v for _, v, _ in items], dtype=object)
    weights = np.array([w for _, _, w in items], dtype=np.int64)
    if like is not None and like.keys.dtype != object:
        try:
            keys = keys.astype(like.keys.dtype)
        except (TypeError, ValueError):
            pass
    if like is not None and like.values.dtype != object:
        try:
            values = np.array([v for _, v, _ in items], dtype=like.values.dtype)
        except (TypeError, ValueError):
            pass
    return DeltaBatch(keys, values, weights)
