"""DirtyScheduler: the change-driven recompute loop (SURVEY.md §2 #8, §3 #2).

Tick protocol (tick-synchronous, batched — SURVEY.md §0):

1. ``push`` buffers deltas at sources (host boundary in).
2. ``tick()`` drains the buffers, computes the structural dirty frontier
   (nodes reachable from dirty sources, in topo order — no device values are
   consulted), and hands the plan to the executor.
3. Deltas arriving on back-edges re-enter at loop nodes; the scheduler
   re-runs the (restricted) plan until quiescence or ``max_loop_iters`` —
   this is the host-driven fixpoint for iterative graphs like PageRank.
4. Sink deltas are folded into materialized host views (host boundary out).

The scheduler is deliberately cheap, host-side Python: all heavy lifting is
in the executor.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter, defaultdict
from typing import Callable, Dict, List, Optional, Sequence

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors import CpuExecutor, Executor
from reflow_tpu.graph import FlowGraph, GraphError, Node

__all__ = ["DirtyScheduler", "TickResult"]


@dataclasses.dataclass
class TickResult:
    """Per-tick observability record (SURVEY.md §5 metrics).

    After ``tick(sync=False)`` the scalar fields may still be
    device-resident (pipelined streaming: nothing blocked on the device);
    call :meth:`block` to force them to host Python values.
    """

    tick: int
    sink_deltas: Dict[str, DeltaBatch]
    passes: int
    dirty_nodes: int
    deltas_in: int
    deltas_out: int
    wall_s: float
    quiesced: bool
    #: captured executor error check for streaming ticks whose per-tick
    #: check was deferred; ``block()`` (the documented streaming sync
    #: point) runs it so sticky flags can't finish a run unsurfaced
    #: (ADVICE r2: a pure-streaming run never otherwise checked)
    _check_errors: Optional[Callable[[], None]] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def delta_ops(self) -> int:
        """Delta rows processed — numerator of delta-ops/sec (BASELINE.md)."""
        return self.deltas_in + self.deltas_out

    def block(self) -> "TickResult":
        """Force any device-resident scalar fields to host values and
        surface deferred executor errors (the streaming sync point; a
        no-op for synchronous ticks)."""
        self.passes = int(self.passes)
        self.deltas_in = int(self.deltas_in)
        self.deltas_out = int(self.deltas_out)
        self.quiesced = bool(self.quiesced)
        if self._check_errors is not None:
            check, self._check_errors = self._check_errors, None
            check()
        return self


class DirtyScheduler:
    def __init__(self, graph: FlowGraph, executor: Optional[Executor] = None,
                 *, max_loop_iters: int = 10_000,
                 dedup_window: int = 1 << 20):
        graph.validate()
        self.graph = graph
        self.executor = executor if executor is not None else CpuExecutor()
        self.executor.bind(graph)
        self.max_loop_iters = max_loop_iters
        self._pending: Dict[int, List[DeltaBatch]] = defaultdict(list)
        #: insertion-ordered dedup set for idempotent pushes, bounded to
        #: the newest ``dedup_window`` ids (upstream redelivery must stay
        #: within that horizon)
        self._seen_batch_ids: Dict[str, None] = {}
        self.dedup_window = dedup_window
        self._tick = 0
        self.sink_views: Dict[str, Counter] = {s.name: Counter() for s in graph.sinks}
        self.history: List[TickResult] = []

    # -- host boundary in --------------------------------------------------

    def push(self, source: Node, batch: DeltaBatch, *,
             batch_id: Optional[str] = None) -> bool:
        """Buffer deltas at a source — or at a loop variable, which is how a
        fixpoint computation receives its initial condition.

        ``batch_id`` makes ingestion idempotent (exactly-once under
        at-least-once upstream delivery, SURVEY.md §5): a batch whose id
        was already accepted — including before a checkpoint/restore — is
        dropped. Returns whether the batch was accepted.
        """
        if source.kind not in ("source", "loop"):
            raise GraphError(f"can only push to sources/loops, not {source}")
        if batch_id is not None:
            if batch_id in self._seen_batch_ids:
                return False
            self._seen_batch_ids[batch_id] = None
            while len(self._seen_batch_ids) > self.dedup_window:
                self._seen_batch_ids.pop(next(iter(self._seen_batch_ids)))
        if len(batch):
            self._pending[source.id].append(batch)
        return True

    # -- dirty planning (structural) --------------------------------------

    def _dirty_plan(self, dirty_roots: Sequence[int]) -> List[Node]:
        dirty = set(dirty_roots)
        plan = []
        for node in self.graph.nodes:  # construction order == topo order
            if node.id in dirty:
                plan.append(node)
                continue
            if node.kind in ("source", "loop"):
                continue
            if any(i.id in dirty for i in node.inputs):
                dirty.add(node.id)
                plan.append(node)
        return plan

    # -- the tick ----------------------------------------------------------

    def tick(self, *, sync: bool = True) -> TickResult:
        """Run one tick. ``sync=False`` (streaming mode) skips the
        per-tick device readback for iterative graphs fully fused on
        device: ticks enqueue back-to-back and the returned TickResult's
        scalars stay device-resident until ``block()``. Graphs with sinks
        or host-driven loops still materialize synchronously."""
        t0 = time.perf_counter()
        ingress: Dict[int, DeltaBatch] = {
            nid: DeltaBatch.concat(batches)
            for nid, batches in self._pending.items()
        }
        self._pending.clear()
        deltas_in = sum(len(b) for b in ingress.values())
        deltas_out = 0
        passes = 0
        dirty_union: set = set()
        sink_deltas: Dict[str, List[DeltaBatch]] = defaultdict(list)
        quiesced = True
        sink_ids = {s.id: s for s in self.graph.sinks}

        while ingress:
            if passes >= self.max_loop_iters:
                quiesced = False
                break
            plan = self._dirty_plan(list(ingress))
            dirty_union.update(n.id for n in plan)
            if passes == 0 and self.graph.loops:
                # iterative graph: let the executor fuse the entire tick
                # (all fixpoint passes) into one on-device program
                fx = self.executor.run_tick_fixpoint(
                    plan, ingress, self.max_loop_iters, sync=sync)
                if fx is not None:
                    (sink_batches, fx_passes, loop_rows, quiesced,
                     extra_dirty) = fx
                    passes = fx_passes
                    deltas_in += loop_rows
                    dirty_union.update(extra_dirty)
                    for sid, batches in sink_batches.items():
                        sink_deltas[sink_ids[sid].name].extend(batches)
                    break
            egress = self.executor.run_pass(plan, ingress)
            passes += 1
            ingress = {}
            for nid, batch in egress.items():
                if nid in sink_ids:
                    if len(batch):
                        sink_deltas[sink_ids[nid].name].append(batch)
                elif len(batch):  # loop back-edge -> next pass
                    ingress[nid] = batch
                    deltas_in += len(batch)

        # fail loudly if any op state carries a sticky error flag (e.g. a
        # retraction reached an insert-only device min/max) BEFORE corrupt
        # deltas are folded into the materialized sink views. Streaming
        # ticks (sync=False) defer the check to the next sync point —
        # unless sink views are about to be materialized, which forces a
        # sync anyway and must not fold corrupt deltas
        checked = sync or bool(sink_deltas)
        if checked:
            self.executor.check_errors()

        out: Dict[str, DeltaBatch] = {}
        for name, batches in sink_deltas.items():
            # sink batches may still be device-resident (deferred readback:
            # the host crossing happens once per tick, not once per pass)
            merged = DeltaBatch.concat(
                [self.executor.materialize(b) for b in batches]).consolidate()
            out[name] = merged
            deltas_out += len(merged)
            view = self.sink_views[name]
            for k, v, w in merged.rows():
                view[(k, v)] += w
                if view[(k, v)] == 0:
                    del view[(k, v)]

        self._tick += 1
        result = TickResult(
            tick=self._tick,
            sink_deltas=out,
            passes=passes,
            dirty_nodes=len(dirty_union),
            deltas_in=deltas_in,
            deltas_out=deltas_out,
            wall_s=time.perf_counter() - t0,
            quiesced=quiesced,
            _check_errors=None if checked else self.executor.check_errors,
        )
        self.history.append(result)
        return result

    # -- host boundary out -------------------------------------------------

    def read_table(self, node: Node) -> Dict:
        """Materialized {key: value} of a stateful node's collection at the
        tick boundary (Reduce: last emitted aggregates; Join: the left
        table). This is the sink-style host crossing for collections that
        live inside loop regions, where a per-pass delta sink would force
        mid-tick readbacks."""
        return self.executor.read_table(node)

    def view(self, sink: str | Node) -> Counter:
        """Materialized multiset {(key, value): weight} at a sink."""
        name = sink if isinstance(sink, str) else sink.name
        return self.sink_views[name]

    def view_dict(self, sink: str | Node) -> Dict:
        """Materialized {key: value} for unique-keyed sink collections."""
        d: Dict = {}
        for (k, v), w in self.view(sink).items():
            if w > 0:
                if k in d:
                    raise GraphError(f"sink {sink} is not unique-keyed at {k!r}")
                d[k] = v
        return d
